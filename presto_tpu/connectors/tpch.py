"""Deterministic TPC-H data generator connector.

Reference analog: ``presto-tpch`` (io.airlift.tpch based generator
connector, `presto-tpch/src/main/java/com/facebook/presto/tpch/`),
which is the basis of most engine tests and benchmarks in the
reference. This is a from-scratch implementation of the TPC-H spec's
data distributions — NOT a port of airlift/tpch — built around two
TPU-driven requirements:

* **Stateless chunked generation.** Every value is a pure function of
  (table, column, row index) via a splitmix64-style counter hash, so any
  split [row0, row1) generates independently — SF100 streams split by
  split without materializing 600M rows, and workers generate their own
  splits without coordination (the reference achieves this with
  per-split generator offsets in TpchRecordSet).

* **Dictionary-first strings.** Low-cardinality columns (shipmode,
  priority, types...) use small vocab dictionaries; per-row unique
  strings (names, phones, comments) use :class:`PatternDictionary`
  which formats values lazily from the code, so devices only ever see
  int32 codes.

Distributions follow TPC-H spec v2 section 4.2 closely enough that the
standard 22 queries exercise the same paths (selectivities, key
sparsity, date ranges); exact dbgen byte-parity is a non-goal since
correctness is checked against an oracle fed the same data.
"""

from __future__ import annotations

import datetime
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DATE, DOUBLE, INTEGER, VARCHAR, DecimalType, Type

# ---------------------------------------------------------------------------
# counter-based RNG: value = f(seed, index), vectorized over index
# ---------------------------------------------------------------------------

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (public-domain algorithm), vectorized."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def _hash_u64(seed: int, idx: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return _mix(np.asarray(idx, dtype=np.uint64) + np.uint64(seed) * _GOLDEN)


def _uniform_int(seed: int, idx: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi] inclusive (like spec's random(lo,hi))."""
    span = np.uint64(hi - lo + 1)
    return (lo + (_hash_u64(seed, idx) % span).astype(np.int64)).astype(np.int64)


def _uniform_unit(seed: int, idx: np.ndarray) -> np.ndarray:
    return (_hash_u64(seed, idx) >> np.uint64(11)).astype(np.float64) / float(1 << 53)


def _seed(table: str, column: str) -> int:
    h = 1469598103934665603
    for c in f"{table}.{column}":
        h = ((h ^ ord(c)) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


def _date(y: int, m: int, d: int) -> int:
    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


MIN_ORDER_DATE = _date(1992, 1, 1)
MAX_ORDER_DATE = _date(1998, 8, 2)
CURRENT_DATE = _date(1995, 6, 17)

# ---------------------------------------------------------------------------
# vocabularies (TPC-H spec 4.2.2.13 / appendix; fixed text domains)
# ---------------------------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
# (name, regionkey) in nationkey order, spec table A-1
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("RUSSIA", 3), ("SAUDI ARABIA", 4), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1), ("VIETNAM", 2),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
TYPE_SYL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush brown "
    "burlywood burnished chartreuse chiffon chocolate coral cornflower cornsilk cream "
    "cyan dark deep dim dodger drab firebrick floral forest frosted gainsboro ghost "
    "goldenrod green grey honeydew hot indian ivory khaki lace lavender lawn lemon "
    "light lime linen magenta maroon medium metallic midnight mint misty moccasin "
    "navajo navy olive orange orchid pale papaya peach peru pink plum powder puff "
    "purple red rose rosy royal saddle salmon sandy seashell sienna sky slate smoke "
    "snow spring steel tan thistle tomato turquoise violet wheat white yellow"
).split()
_NOUNS = (
    "packages requests accounts deposits foxes ideas theodolites pinto beans "
    "instructions dependencies excuses platelets asymptotes courts dolphins "
    "multipliers sauternes warthogs frets dinos attainments somas braids "
    "hockey players frays warhorses dugouts notornis epitaphs pearls tithes "
    "waters orbits gifts sheaves depths sentiments decoys realms pains grouches "
    "escapades"
).split()
_VERBS = (
    "sleep wake are cajole haggle nag use boost affix detect integrate maintain "
    "nod was lose sublate solve thrash promise engage hinder print x-ray breach "
    "eat grow impress mold poach serve run dazzle snooze doze unwind kindle play "
    "hang believe doubt"
).split()
_ADJECTIVES = (
    "furious sly careful blithe quick fluffy slow quiet ruthless thin close dogged "
    "daring brave stealthy permanent enticing idle busy regular final ironic even "
    "bold silent special pending unusual express"
).split()
_ADVERBS = (
    "sometimes always never furiously slyly carefully blithely quickly fluffily "
    "slowly quietly ruthlessly thinly closely doggedly daringly bravely stealthily "
    "permanently enticingly idly busily regularly finally ironically evenly boldly "
    "silently"
).split()


def _make_comment_vocab(n: int, seed: int) -> List[str]:
    """Fixed-size sentence vocabulary for comment columns. A slice of
    entries embeds 'special … requests' / 'pending … deposits' style
    phrases so Q13-like LIKE predicates have real selectivity."""
    idx = np.arange(n)
    adv = _hash_u64(seed + 1, idx) % len(_ADVERBS)
    adj = _hash_u64(seed + 2, idx) % len(_ADJECTIVES)
    noun = _hash_u64(seed + 3, idx) % len(_NOUNS)
    verb = _hash_u64(seed + 4, idx) % len(_VERBS)
    adj2 = _hash_u64(seed + 5, idx) % len(_ADJECTIVES)
    noun2 = _hash_u64(seed + 6, idx) % len(_NOUNS)
    out = []
    for i in range(n):
        out.append(
            f"{_ADVERBS[adv[i]]} {_ADJECTIVES[adj[i]]} {_NOUNS[noun[i]]} "
            f"{_VERBS[verb[i]]} the {_ADJECTIVES[adj2[i]]} {_NOUNS[noun2[i]]}"
        )
    return out


class PatternDictionary(Dictionary):
    """Dictionary whose values are computed lazily from the code by a
    formatting function (e.g. ``Customer#%09d``). Avoids materializing
    millions of per-row-unique strings; devices see only the code."""

    __slots__ = ("fmt", "size")

    def __init__(self, fmt, size: int):
        self.fmt = fmt  # callable code -> str
        self.size = size
        self.values = _LazyValues(fmt, size)  # type: ignore[assignment]
        self._index = None

    def code_of(self, s: str) -> int:  # pragma: no cover - rarely used
        for i in range(self.size):
            if self.fmt(i) == s:
                return i
        return -1

    def decode(self, codes: np.ndarray) -> np.ndarray:
        flat = codes.ravel()
        out = np.empty(flat.shape, dtype=object)
        for j, c in enumerate(flat):
            out[j] = self.fmt(int(c)) if 0 <= c < self.size else None
        return out.reshape(codes.shape)

    def lut(self, predicate) -> np.ndarray:
        return np.asarray(
            [bool(predicate(self.fmt(i))) for i in range(self.size)], dtype=np.bool_
        )

    def __len__(self) -> int:
        return self.size

    def __repr__(self) -> str:
        return f"PatternDictionary({self.size} values)"


class _LazyValues:
    def __init__(self, fmt, size):
        self._fmt, self._size = fmt, size

    def __getitem__(self, i):
        return self._fmt(i)

    def __len__(self):
        return self._size

    def __iter__(self):
        return (self._fmt(i) for i in range(self._size))


def _phone_fmt(nation_of_code):
    def fmt(code: int) -> str:
        nk = nation_of_code(code)
        h = int(_hash_u64(77, np.asarray([code]))[0])
        return (
            f"{10 + nk}-{100 + h % 900}-{100 + (h >> 10) % 900}-{1000 + (h >> 20) % 9000}"
        )

    return fmt


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_MONEY = DecimalType(12, 2)
_PCT = DecimalType(12, 2)  # discount/tax stored scale-2 (0.05 -> 5)

SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "region": [("r_regionkey", BIGINT), ("r_name", VARCHAR), ("r_comment", VARCHAR)],
    "nation": [
        ("n_nationkey", BIGINT), ("n_name", VARCHAR),
        ("n_regionkey", BIGINT), ("n_comment", VARCHAR),
    ],
    "supplier": [
        ("s_suppkey", BIGINT), ("s_name", VARCHAR), ("s_address", VARCHAR),
        ("s_nationkey", BIGINT), ("s_phone", VARCHAR), ("s_acctbal", _MONEY),
        ("s_comment", VARCHAR),
    ],
    "customer": [
        ("c_custkey", BIGINT), ("c_name", VARCHAR), ("c_address", VARCHAR),
        ("c_nationkey", BIGINT), ("c_phone", VARCHAR), ("c_acctbal", _MONEY),
        ("c_mktsegment", VARCHAR), ("c_comment", VARCHAR),
    ],
    "part": [
        ("p_partkey", BIGINT), ("p_name", VARCHAR), ("p_mfgr", VARCHAR),
        ("p_brand", VARCHAR), ("p_type", VARCHAR), ("p_size", BIGINT),
        ("p_container", VARCHAR), ("p_retailprice", _MONEY), ("p_comment", VARCHAR),
    ],
    "partsupp": [
        ("ps_partkey", BIGINT), ("ps_suppkey", BIGINT), ("ps_availqty", BIGINT),
        ("ps_supplycost", _MONEY), ("ps_comment", VARCHAR),
    ],
    "orders": [
        ("o_orderkey", BIGINT), ("o_custkey", BIGINT), ("o_orderstatus", VARCHAR),
        ("o_totalprice", _MONEY), ("o_orderdate", DATE), ("o_orderpriority", VARCHAR),
        ("o_clerk", VARCHAR), ("o_shippriority", BIGINT), ("o_comment", VARCHAR),
    ],
    "lineitem": [
        ("l_orderkey", BIGINT), ("l_partkey", BIGINT), ("l_suppkey", BIGINT),
        ("l_linenumber", BIGINT), ("l_quantity", DecimalType(12, 2)),
        ("l_extendedprice", _MONEY), ("l_discount", _PCT), ("l_tax", _PCT),
        ("l_returnflag", VARCHAR), ("l_linestatus", VARCHAR),
        ("l_shipdate", DATE), ("l_commitdate", DATE), ("l_receiptdate", DATE),
        ("l_shipinstruct", VARCHAR), ("l_shipmode", VARCHAR), ("l_comment", VARCHAR),
    ],
}


class Tpch:
    """TPC-H generator: tables at scale factor ``sf``, split-chunked.

    Orders/lineitem splits are aligned on order ranges so each split is
    self-consistent (o_totalprice/o_orderstatus derive from that order's
    line items, as the spec requires)."""

    COMMENT_VOCAB = 4096

    def __init__(self, sf: float = 1.0, split_rows: int = 1 << 20,
                 aligned_buckets: bool = False):
        self.sf = float(sf)
        self.split_rows = int(split_rows)
        # aligned_buckets: orders and lineitem use the SAME order-range
        # granularity per split, making split index a shared bucket id
        # (ConnectorNodePartitioningProvider analog — enables colocated
        # joins; lineitem splits are ~4x the rows of orders splits)
        self.aligned_buckets = bool(aligned_buckets)
        self.n_orders = int(round(1_500_000 * self.sf))
        self.n_customers = int(round(150_000 * self.sf))
        self.n_parts = int(round(200_000 * self.sf))
        self.n_suppliers = int(round(10_000 * self.sf))
        self._dicts: Dict[str, Dictionary] = {}
        self._comment_vocab = Dictionary(
            _make_comment_vocab(self.COMMENT_VOCAB, seed=99)
        )

    # -- dictionaries -------------------------------------------------------
    def _dict(self, key: str) -> Dictionary:
        if key in self._dicts:
            return self._dicts[key]
        d: Dictionary
        if key == "r_name":
            d = Dictionary(REGIONS)
        elif key == "n_name":
            d = Dictionary([n for n, _ in NATIONS])
        elif key == "c_mktsegment":
            d = Dictionary(SEGMENTS)
        elif key == "o_orderpriority":
            d = Dictionary(PRIORITIES)
        elif key == "o_orderstatus":
            d = Dictionary(["F", "O", "P"])
        elif key == "l_returnflag":
            d = Dictionary(["A", "N", "R"])
        elif key == "l_linestatus":
            d = Dictionary(["F", "O"])
        elif key == "l_shipinstruct":
            d = Dictionary(INSTRUCTS)
        elif key == "l_shipmode":
            d = Dictionary(MODES)
        elif key == "p_type":
            d = Dictionary(
                [f"{a} {b} {c}" for a in TYPE_SYL1 for b in TYPE_SYL2 for c in TYPE_SYL3]
            )
        elif key == "p_container":
            d = Dictionary([f"{a} {b}" for a in CONTAINER_SYL1 for b in CONTAINER_SYL2])
        elif key == "p_brand":
            d = Dictionary([f"Brand#{m}{n}" for m in range(1, 6) for n in range(1, 6)])
        elif key == "p_mfgr":
            d = Dictionary([f"Manufacturer#{m}" for m in range(1, 6)])
        elif key == "p_name":
            d = self._part_name_dict()
        elif key == "c_name":
            d = PatternDictionary(lambda i: f"Customer#{i + 1:09d}", self.n_customers)
        elif key == "s_name":
            d = PatternDictionary(lambda i: f"Supplier#{i + 1:09d}", self.n_suppliers)
        elif key == "o_clerk":
            n_clerks = max(int(1000 * self.sf), 1)
            d = PatternDictionary(lambda i: f"Clerk#{i + 1:09d}", n_clerks)
        elif key == "c_phone":
            d = PatternDictionary(
                _phone_fmt(lambda c: int(_uniform_int(_seed("customer", "c_nationkey"), np.asarray([c]), 0, 24)[0])),
                self.n_customers,
            )
        elif key == "s_phone":
            d = PatternDictionary(
                _phone_fmt(lambda c: int(_uniform_int(_seed("supplier", "s_nationkey"), np.asarray([c]), 0, 24)[0])),
                self.n_suppliers,
            )
        elif key == "c_address":
            d = PatternDictionary(lambda i: _address(i, 101), self.n_customers)
        elif key == "s_address":
            d = PatternDictionary(lambda i: _address(i, 102), self.n_suppliers)
        elif key.endswith("_comment"):
            d = self._comment_vocab
        else:
            raise KeyError(key)
        self._dicts[key] = d
        return d

    def _part_name_dict(self) -> Dictionary:
        # 5 color words per part name (spec: P_NAME from 92-word list);
        # lazy: at SF100 there are 20M parts.
        def fmt(i: int) -> str:
            ia = np.asarray([i])
            return " ".join(
                COLORS[int(_hash_u64(300 + j, ia)[0] % len(COLORS))] for j in range(5)
            )

        return PatternDictionary(fmt, self.n_parts)

    # -- split layout -------------------------------------------------------
    def row_count(self, table: str) -> int:
        if table == "lineitem":
            return self._lineitem_count()
        return {
            "region": 5,
            "nation": 25,
            "supplier": self.n_suppliers,
            "customer": self.n_customers,
            "part": self.n_parts,
            "partsupp": self.n_parts * 4,
            "orders": self.n_orders,
        }[table]

    def _lines_per_order(self, order_idx: np.ndarray) -> np.ndarray:
        return _uniform_int(_seed("lineitem", "count"), order_idx, 1, 7)

    def _lineitem_count(self) -> int:
        # exact total: sum of per-order line counts, computed chunked
        if not hasattr(self, "_li_count"):
            total = 0
            for lo in range(0, self.n_orders, 4_000_000):
                hi = min(lo + 4_000_000, self.n_orders)
                total += int(self._lines_per_order(np.arange(lo, hi)).sum())
            self._li_count = total
        return self._li_count

    def max_split_rows(self, table: str) -> int:
        """Static upper bound on rows in any split (static-shape wave
        capacity for distributed scans)."""
        if table == "lineitem":
            per = self._per("lineitem")
            return min(per * 7, max(self.row_count("lineitem"), 1))
        return min(self.split_rows, max(self.row_count(table), 1))

    def num_splits(self, table: str) -> int:
        if table in ("orders", "lineitem"):
            per = self._per(table)
            return max(1, -(-self.n_orders // per))
        return max(1, -(-self.row_count(table) // self.split_rows))

    def table_version(self, table: str) -> int:
        """Generated data is immutable: a constant version marks every
        table cacheable forever (serving-tier result/subplan caches)."""
        return 0

    def _per(self, table: str) -> int:
        """Orders per split for the order-range-partitioned tables."""
        if table == "lineitem" and not self.aligned_buckets:
            return max(self.split_rows // 4, 1)
        return self.split_rows

    def _order_range(self, table: str, split: int) -> Tuple[int, int]:
        per = self._per(table)
        lo = split * per
        return lo, min(lo + per, self.n_orders)

    # -- generators ---------------------------------------------------------
    def generate_split(self, table: str, split: int) -> Dict[str, np.ndarray]:
        """Columns for one split as host numpy arrays (dictionary codes
        for VARCHAR); deterministic in (sf, table, split)."""
        if table in ("orders", "lineitem"):
            o0, o1 = self._order_range(table, split)
            return self._orders(o0, o1) if table == "orders" else self._lineitem(o0, o1)
        n = self.row_count(table)
        lo = split * self.split_rows
        hi = min(lo + self.split_rows, n)
        idx = np.arange(lo, hi)
        return getattr(self, f"_{table}")(idx)

    # each generator returns {column: np.ndarray}
    def _region(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "r_regionkey": idx.astype(np.int64),
            "r_name": idx.astype(np.int32),
            "r_comment": (_hash_u64(1, idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _nation(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        region = np.asarray([r for _, r in NATIONS], dtype=np.int64)
        return {
            "n_nationkey": idx.astype(np.int64),
            "n_name": idx.astype(np.int32),
            "n_regionkey": region[idx],
            "n_comment": (_hash_u64(2, idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _supplier(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("supplier", c)
        return {
            "s_suppkey": idx.astype(np.int64) + 1,
            "s_name": idx.astype(np.int32),
            "s_address": idx.astype(np.int32),
            "s_nationkey": _uniform_int(s("s_nationkey"), idx, 0, 24),
            "s_phone": idx.astype(np.int32),
            "s_acctbal": _uniform_int(s("s_acctbal"), idx, -99999, 999999),
            "s_comment": (_hash_u64(s("s_comment"), idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _customer(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("customer", c)
        return {
            "c_custkey": idx.astype(np.int64) + 1,
            "c_name": idx.astype(np.int32),
            "c_address": idx.astype(np.int32),
            "c_nationkey": _uniform_int(s("c_nationkey"), idx, 0, 24),
            "c_phone": idx.astype(np.int32),
            "c_acctbal": _uniform_int(s("c_acctbal"), idx, -99999, 999999),
            "c_mktsegment": (_hash_u64(s("c_mktsegment"), idx) % 5).astype(np.int32),
            "c_comment": (_hash_u64(s("c_comment"), idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _part(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("part", c)
        partkey = idx.astype(np.int64) + 1
        retail = self._retail_price(partkey)
        return {
            "p_partkey": partkey,
            "p_name": idx.astype(np.int32),
            "p_mfgr": (_hash_u64(s("p_mfgr"), idx) % 5).astype(np.int32),
            "p_brand": (_hash_u64(s("p_brand"), idx) % 25).astype(np.int32),
            "p_type": (_hash_u64(s("p_type"), idx) % 150).astype(np.int32),
            "p_size": _uniform_int(s("p_size"), idx, 1, 50),
            "p_container": (_hash_u64(s("p_container"), idx) % 40).astype(np.int32),
            "p_retailprice": retail,
            "p_comment": (_hash_u64(s("p_comment"), idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _suppkey_for(self, partkey: np.ndarray, j: np.ndarray) -> np.ndarray:
        # spec: PS_SUPPKEY = (ps_partkey + i*(S/4 + (ps_partkey-1)/S)) % S + 1
        # shared by partsupp and lineitem so l_suppkey always matches one
        # of the part's 4 suppliers.
        S = max(self.n_suppliers, 1)
        return ((partkey + j * (S // 4 + (partkey - 1) // S)) % S + 1).astype(np.int64)

    @staticmethod
    def _retail_price(partkey: np.ndarray) -> np.ndarray:
        # spec 4.2.3 (scale-2 money); shared by part and lineitem.
        return 90000 + (partkey // 10) % 20001 + 100 * (partkey % 1000)

    def _partsupp(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("partsupp", c)
        partkey = (idx // 4).astype(np.int64) + 1
        j = idx % 4
        return {
            "ps_partkey": partkey,
            "ps_suppkey": self._suppkey_for(partkey, j),
            "ps_availqty": _uniform_int(s("ps_availqty"), idx, 1, 9999),
            "ps_supplycost": _uniform_int(s("ps_supplycost"), idx, 100, 100000),
            "ps_comment": (_hash_u64(s("ps_comment"), idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    def _orderkey(self, order_idx: np.ndarray) -> np.ndarray:
        # dbgen-style sparse keys: 8 live keys per 32-key block
        return ((order_idx >> 3) << 5 | (order_idx & 7)).astype(np.int64) + 1

    def _order_dates(self, order_idx: np.ndarray) -> np.ndarray:
        return _uniform_int(
            _seed("orders", "o_orderdate"), order_idx, MIN_ORDER_DATE, MAX_ORDER_DATE - 121
        )

    def _order_custkeys(self, order_idx: np.ndarray) -> np.ndarray:
        return _uniform_int(
            _seed("orders", "o_custkey"), order_idx, 1, max(self.n_customers, 1)
        )

    def _lineitem_raw(self, o0: int, o1: int):
        """Line-level arrays for orders [o0, o1) plus per-order offsets."""
        order_idx = np.arange(o0, o1)
        counts = self._lines_per_order(order_idx)
        total = int(counts.sum())
        oi = np.repeat(order_idx, counts)  # order index per line
        starts = np.cumsum(counts) - counts
        linenum = np.arange(total) - np.repeat(starts, counts) + 1
        s = lambda c: _seed("lineitem", c)
        gidx = oi * np.int64(8) + linenum  # globally unique line id
        odate_l = np.repeat(self._order_dates(order_idx), counts)

        qty = _uniform_int(s("l_quantity"), gidx, 1, 50)
        partkey = _uniform_int(s("l_partkey"), gidx, 1, max(self.n_parts, 1))
        # supplier chosen among the 4 for the part (spec 4.2.3)
        j = _uniform_int(s("l_suppj"), gidx, 0, 3)
        suppkey = self._suppkey_for(partkey, j)
        # qty is unscaled units, retail is scale-2 -> product is scale-2 money
        extprice = qty * self._retail_price(partkey)
        discount = _uniform_int(s("l_discount"), gidx, 0, 10)  # scale-2 (0.00-0.10)
        tax = _uniform_int(s("l_tax"), gidx, 0, 8)
        shipdate = odate_l + _uniform_int(s("l_shipdate"), gidx, 1, 121)
        commitdate = odate_l + _uniform_int(s("l_commitdate"), gidx, 30, 90)
        receiptdate = shipdate + _uniform_int(s("l_receiptdate"), gidx, 1, 30)
        linestatus = (shipdate > CURRENT_DATE).astype(np.int32)  # 0=F,1=O
        returned = receiptdate <= CURRENT_DATE
        rflag_rand = (_hash_u64(s("l_returnflag"), gidx) % 2).astype(np.int32)  # A or R
        returnflag = np.where(returned, np.where(rflag_rand == 0, 0, 2), 1).astype(np.int32)
        cols = {
            "l_orderkey": np.repeat(self._orderkey(order_idx), counts),
            "l_partkey": partkey,
            "l_suppkey": suppkey.astype(np.int64),
            "l_linenumber": linenum.astype(np.int64),
            "l_quantity": qty * 100,  # scale-2
            "l_extendedprice": extprice,
            "l_discount": discount,
            "l_tax": tax,
            "l_returnflag": returnflag,
            "l_linestatus": linestatus,
            "l_shipdate": shipdate.astype(np.int32),
            "l_commitdate": commitdate.astype(np.int32),
            "l_receiptdate": receiptdate.astype(np.int32),
            "l_shipinstruct": (_hash_u64(s("l_shipinstruct"), gidx) % 4).astype(np.int32),
            "l_shipmode": (_hash_u64(s("l_shipmode"), gidx) % 7).astype(np.int32),
            "l_comment": (_hash_u64(s("l_comment"), gidx) % self.COMMENT_VOCAB).astype(np.int32),
        }
        return cols, counts

    def _lineitem(self, o0: int, o1: int) -> Dict[str, np.ndarray]:
        cols, _ = self._lineitem_raw(o0, o1)
        return cols

    def _orders(self, o0: int, o1: int) -> Dict[str, np.ndarray]:
        order_idx = np.arange(o0, o1)
        s = lambda c: _seed("orders", c)
        li, counts = self._lineitem_raw(o0, o1)
        # o_totalprice = sum(extprice * (1+tax) * (1-disc)) over the order's lines
        charge = (
            li["l_extendedprice"] * (100 + li["l_tax"]) * (100 - li["l_discount"])
        ) // 10000
        ends = np.cumsum(counts)
        starts = np.concatenate([[0], ends[:-1]])
        csum = np.concatenate([[0], np.cumsum(charge)])
        totalprice = csum[ends] - csum[starts]
        # o_orderstatus: F if all lines F, O if all O, else P
        ls = li["l_linestatus"]
        lsum = np.concatenate([[0], np.cumsum(ls)])
        o_sum = lsum[ends] - lsum[starts]
        status = np.where(o_sum == 0, 0, np.where(o_sum == counts, 1, 2)).astype(np.int32)
        return {
            "o_orderkey": self._orderkey(order_idx),
            "o_custkey": self._order_custkeys(order_idx),
            "o_orderstatus": status,
            "o_totalprice": totalprice.astype(np.int64),
            "o_orderdate": self._order_dates(order_idx).astype(np.int32),
            "o_orderpriority": (_hash_u64(s("o_orderpriority"), order_idx) % 5).astype(np.int32),
            "o_clerk": (_hash_u64(s("o_clerk"), order_idx) % max(int(1000 * self.sf), 1)).astype(np.int32),
            "o_shippriority": np.zeros(len(order_idx), dtype=np.int64),
            "o_comment": (_hash_u64(s("o_comment"), order_idx) % self.COMMENT_VOCAB).astype(np.int32),
        }

    # -- Page production ----------------------------------------------------
    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return SCHEMAS[table]

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        t = dict(SCHEMAS[table])[column]
        return self._dict(column) if t.is_string else None

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        cols = self.generate_split(table, split)
        schema = SCHEMAS[table]
        arrays = [cols[name] for name, _ in schema]
        types = [t for _, t in schema]
        dicts = [self.dictionary_for(table, name) for name, _ in schema]
        return Page.from_arrays(arrays, types, dictionaries=dicts, capacity=capacity)

    def pages(self, table: str, capacity: Optional[int] = None) -> Iterator[Page]:
        for i in range(self.num_splits(table)):
            yield self.page_for_split(table, i, capacity=capacity)

    def column_names(self, table: str) -> List[str]:
        return [n for n, _ in SCHEMAS[table]]

    def table_names(self) -> List[str]:
        return list(SCHEMAS.keys())

    def primary_key(self, table: str) -> Optional[List[str]]:
        return {
            "region": ["r_regionkey"],
            "nation": ["n_nationkey"],
            "supplier": ["s_suppkey"],
            "customer": ["c_custkey"],
            "part": ["p_partkey"],
            "partsupp": ["ps_partkey", "ps_suppkey"],
            "orders": ["o_orderkey"],
            "lineitem": ["l_orderkey", "l_linenumber"],
        }.get(table)

    def bucketing(self, table: str) -> Optional[Tuple[List[str], tuple, int]]:
        """(bucket_columns, alignment_token, bucket_count) — split index
        IS the bucket id; orders/lineitem share order-range buckets when
        ``aligned_buckets`` (ConnectorNodePartitioningProvider analog,
        presto-tpch TpchNodePartitioningProvider)."""
        if table in ("orders", "lineitem") and self._per("orders") == self._per(table):
            col = "o_orderkey" if table == "orders" else "l_orderkey"
            token = ("tpch-order-range", self.sf, self._per(table))
            return ([col], token, self.num_splits(table))
        return None

    def sort_order(self, table: str) -> Optional[List[str]]:
        """The generator emits rows in primary-key order (sequential
        keys per split), so the physical ordering IS the primary key —
        the streaming-aggregation trigger (ConnectorMetadata
        local-properties analog)."""
        return self.primary_key(table)

    def column_ndv(self, table: str, column: str) -> Optional[int]:
        """Distinct-value counts where the domain width overstates them
        (sparse keys: orderkeys skip 8-of-32 slots). Reference analog:
        presto-tpch/.../statistics/ ColumnStatisticsData distinctValues."""
        ndvs: Dict[str, int] = {
            "o_orderkey": self.n_orders,
            "l_orderkey": self.n_orders,
            "o_custkey": int(self.n_customers * 2 / 3),  # spec: 1/3 hold no orders
            "l_partkey": self.n_parts,
            "l_suppkey": self.n_suppliers,
            "ps_partkey": self.n_parts,
            "ps_suppkey": self.n_suppliers,
        }
        return ndvs.get(column)

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        """Known (lo, hi) of a column in its device representation —
        the stats feed for exact key packing (planner/exact joins).
        Reference analog: presto-tpch/.../statistics/ column stats."""
        t = dict(SCHEMAS[table])[column]
        if t.is_string:
            return (0, len(self.dictionary_for(table, column)) - 1)
        max_orderkey = int(((self.n_orders - 1) >> 3) << 5 | ((self.n_orders - 1) & 7)) + 1
        doms: Dict[str, Tuple[int, int]] = {
            "r_regionkey": (0, 4),
            "n_nationkey": (0, 24),
            "n_regionkey": (0, 4),
            "s_suppkey": (1, self.n_suppliers),
            "s_nationkey": (0, 24),
            "c_custkey": (1, self.n_customers),
            "c_nationkey": (0, 24),
            "p_partkey": (1, self.n_parts),
            "p_size": (1, 50),
            "ps_partkey": (1, self.n_parts),
            "ps_suppkey": (1, self.n_suppliers),
            "ps_availqty": (1, 9999),
            "o_orderkey": (1, max_orderkey),
            "o_custkey": (1, self.n_customers),
            "o_orderdate": (MIN_ORDER_DATE, MAX_ORDER_DATE),
            "o_shippriority": (0, 0),
            "l_orderkey": (1, max_orderkey),
            "l_partkey": (1, self.n_parts),
            "l_suppkey": (1, self.n_suppliers),
            "l_linenumber": (1, 7),
            "l_quantity": (100, 5000),
            "l_discount": (0, 10),
            "l_tax": (0, 8),
            "l_shipdate": (MIN_ORDER_DATE + 1, MAX_ORDER_DATE + 121),
            "l_commitdate": (MIN_ORDER_DATE + 30, MAX_ORDER_DATE + 90),
            "l_receiptdate": (MIN_ORDER_DATE + 2, MAX_ORDER_DATE + 151),
        }
        return doms.get(column)


def _address(i: int, salt: int) -> str:
    h = int(_hash_u64(salt, np.asarray([i]))[0])
    chars = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 ,"
    n = 10 + h % 25
    out = []
    x = h
    for _ in range(n):
        x = (x * 6364136223846793005 + 1442695040888963407) & 0xFFFFFFFFFFFFFFFF
        out.append(chars[(x >> 33) % len(chars)])
    return "".join(out)
