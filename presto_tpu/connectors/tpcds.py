"""Deterministic TPC-DS generator connector (star-schema subset).

Reference analog: ``presto-tpcds`` (teradata tpcds-backed generator,
`presto-tpcds/src/main/java/com/facebook/presto/tpcds/`).  From-scratch
counter-hash generation in the same style as connectors/tpch.py:
every value is a pure function of (table, column, row index), so splits
generate independently on any worker.  Distributions follow the TPC-DS
spec's shapes (fact rows scale with sf, dimensions fixed or sublinear;
customer_demographics is the spec's exact 1,920,800-row demographic
cross product) — byte-parity with the official dsdgen is a non-goal
since correctness is oracle-checked on the same generated data.

Covers the star-join benchmark queries (Q3/Q7/Q42/Q52/Q55 class):
store_sales fact + date_dim/item/customer_demographics/promotion/store
dimensions.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.tpch import PatternDictionary, _hash_u64, _uniform_int
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DATE, INTEGER, VARCHAR, DecimalType, Type

_MONEY = DecimalType(12, 2)

# date_dim: 1900-01-01 .. 2100-01-01, sk = julian-style offset
DATE_DIM_ROWS = 73049
D_SK0 = 2415022  # spec's first d_date_sk
_EPOCH_OFF = (np.datetime64("1970-01-01") - np.datetime64("1900-01-01")).astype(int)

# sales window: 1998-01-01 (+5 years)
_SALES_START = int((np.datetime64("1998-01-01") - np.datetime64("1900-01-01")).astype(int))
_SALES_DAYS = 1826

GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
YN = ["N", "Y"]

CD_ROWS = 2 * 5 * 7 * 20 * 4 * 7 * 7 * 7  # 1,920,800 (spec cross product)


def _seed(t: str, c: str) -> int:
    h = 1469598103934665603
    for ch in f"tpcds.{t}.{c}":
        h = ((h ^ ord(ch)) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date", DATE), ("d_year", BIGINT),
        ("d_moy", BIGINT), ("d_dom", BIGINT), ("d_qoy", BIGINT),
        ("d_day_name", VARCHAR), ("d_month_seq", BIGINT),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VARCHAR), ("i_item_desc", VARCHAR),
        ("i_brand_id", BIGINT), ("i_brand", VARCHAR),
        ("i_class_id", BIGINT), ("i_class", VARCHAR),
        ("i_category_id", BIGINT), ("i_category", VARCHAR),
        ("i_manufact_id", BIGINT), ("i_manager_id", BIGINT),
        ("i_current_price", _MONEY),
    ],
    "customer_demographics": [
        ("cd_demo_sk", BIGINT), ("cd_gender", VARCHAR),
        ("cd_marital_status", VARCHAR), ("cd_education_status", VARCHAR),
        ("cd_purchase_estimate", BIGINT), ("cd_credit_rating", VARCHAR),
        ("cd_dep_count", BIGINT), ("cd_dep_employed_count", BIGINT),
        ("cd_dep_college_count", BIGINT),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VARCHAR),
        ("p_channel_dmail", VARCHAR), ("p_channel_email", VARCHAR),
        ("p_channel_event", VARCHAR), ("p_channel_tv", VARCHAR),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VARCHAR),
        ("s_store_name", VARCHAR), ("s_number_employees", BIGINT),
        ("s_state", VARCHAR),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_item_sk", BIGINT),
        ("ss_customer_sk", BIGINT), ("ss_cdemo_sk", BIGINT),
        ("ss_store_sk", BIGINT), ("ss_promo_sk", BIGINT),
        ("ss_ticket_number", BIGINT), ("ss_quantity", BIGINT),
        ("ss_wholesale_cost", _MONEY), ("ss_list_price", _MONEY),
        ("ss_sales_price", _MONEY), ("ss_ext_discount_amt", _MONEY),
        ("ss_ext_sales_price", _MONEY), ("ss_ext_list_price", _MONEY),
        ("ss_coupon_amt", _MONEY), ("ss_net_paid", _MONEY),
        ("ss_net_profit", _MONEY),
    ],
}

STATES = ["TN", "CA", "TX", "OH", "GA", "NY", "WA", "IL", "MI", "FL"]


class Tpcds:
    def __init__(self, sf: float = 1.0, split_rows: int = 1 << 20,
                 cd_rows: Optional[int] = None):
        self.sf = float(sf)
        self.split_rows = int(split_rows)
        # test harnesses may truncate the demographic cross product
        self.cd_rows = int(cd_rows) if cd_rows is not None else CD_ROWS
        self.n_store_sales = max(int(round(2_880_000 * self.sf)), 1)
        self.n_items = 18000
        self.n_customers = max(int(round(100_000 * self.sf)), 1)
        self.n_promos = 300
        self.n_stores = max(int(round(12 * max(self.sf, 1.0))), 1)
        self._dicts: Dict[str, Dictionary] = {}

    # -- metadata -----------------------------------------------------------
    def table_names(self) -> List[str]:
        return list(SCHEMAS.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return SCHEMAS[table]

    def row_count(self, table: str) -> int:
        return {
            "date_dim": DATE_DIM_ROWS,
            "item": self.n_items,
            "customer_demographics": self.cd_rows,
            "promotion": self.n_promos,
            "store": self.n_stores,
            "store_sales": self.n_store_sales,
        }[table]

    def num_splits(self, table: str) -> int:
        return max(1, -(-self.row_count(table) // self.split_rows))

    def max_split_rows(self, table: str) -> int:
        return min(self.split_rows, max(self.row_count(table), 1))

    def primary_key(self, table: str) -> Optional[List[str]]:
        return {
            "date_dim": ["d_date_sk"],
            "item": ["i_item_sk"],
            "customer_demographics": ["cd_demo_sk"],
            "promotion": ["p_promo_sk"],
            "store": ["s_store_sk"],
            "store_sales": None,
        }[table]

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        t = dict(SCHEMAS[table])[column]
        if t.is_string:
            return (0, len(self.dictionary_for(table, column)) - 1)
        doms: Dict[str, Tuple[int, int]] = {
            "d_date_sk": (D_SK0, D_SK0 + DATE_DIM_ROWS - 1),
            "d_year": (1900, 2100),
            "d_moy": (1, 12),
            "d_dom": (1, 31),
            "d_qoy": (1, 4),
            "i_item_sk": (1, self.n_items),
            "i_brand_id": (1, 1000),
            "i_class_id": (1, 100),
            "i_category_id": (1, 10),
            "i_manufact_id": (1, 1000),
            "i_manager_id": (1, 100),
            "cd_demo_sk": (1, self.cd_rows),
            "p_promo_sk": (1, self.n_promos),
            "s_store_sk": (1, self.n_stores),
            "ss_sold_date_sk": (D_SK0 + _SALES_START, D_SK0 + _SALES_START + _SALES_DAYS - 1),
            "ss_item_sk": (1, self.n_items),
            "ss_customer_sk": (1, self.n_customers),
            "ss_cdemo_sk": (1, self.cd_rows),
            "ss_store_sk": (1, self.n_stores),
            "ss_promo_sk": (0, self.n_promos),
            "ss_quantity": (1, 100),
        }
        return doms.get(column)

    # -- dictionaries -------------------------------------------------------
    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        t = dict(SCHEMAS[table])[column]
        if not t.is_string:
            return None
        if column in self._dicts:
            return self._dicts[column]
        d: Dictionary
        if column == "d_day_name":
            d = Dictionary(["Sunday", "Monday", "Tuesday", "Wednesday",
                            "Thursday", "Friday", "Saturday"])
        elif column == "i_item_id":
            d = PatternDictionary(lambda i: f"AAAAAAAA{i + 1:08d}", self.n_items)
        elif column == "i_item_desc":
            d = PatternDictionary(lambda i: f"item description {i + 1}", 4096)
        elif column == "i_brand":
            d = PatternDictionary(lambda i: f"brand#{i + 1}", 1000)
        elif column == "i_class":
            d = PatternDictionary(lambda i: f"class#{i + 1}", 100)
        elif column == "i_category":
            d = Dictionary(CATEGORIES)
        elif column == "cd_gender":
            d = Dictionary(GENDERS)
        elif column == "cd_marital_status":
            d = Dictionary(MARITAL)
        elif column == "cd_education_status":
            d = Dictionary(EDUCATION)
        elif column == "cd_credit_rating":
            d = Dictionary(CREDIT)
        elif column == "p_promo_id":
            d = PatternDictionary(lambda i: f"promo#{i + 1:08d}", self.n_promos)
        elif column in ("p_channel_dmail", "p_channel_email", "p_channel_event", "p_channel_tv"):
            d = Dictionary(YN)
        elif column == "s_store_id":
            d = PatternDictionary(lambda i: f"store#{i + 1:08d}", self.n_stores)
        elif column == "s_store_name":
            d = Dictionary(["ought", "able", "pri", "ese", "anti", "cally", "ation", "eing"])
        elif column == "s_state":
            d = Dictionary(STATES)
        else:
            raise KeyError(column)
        self._dicts[column] = d
        return d

    # -- generators ---------------------------------------------------------
    def generate_split(self, table: str, split: int) -> Dict[str, np.ndarray]:
        n = self.row_count(table)
        lo = split * self.split_rows
        hi = min(lo + self.split_rows, n)
        idx = np.arange(lo, hi)
        return getattr(self, f"_{table}")(idx)

    def _date_dim(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        days = idx.astype("int64")  # days since 1900-01-01
        dt = np.datetime64("1900-01-01") + days.astype("timedelta64[D]")
        y = dt.astype("datetime64[Y]").astype(int) + 1970
        month0 = dt.astype("datetime64[M]").astype(int)
        moy = month0 % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        dow = (days + 1) % 7  # 1900-01-01 was a Monday; 0=Sunday
        return {
            "d_date_sk": days + D_SK0,
            "d_date": (days - _EPOCH_OFF).astype(np.int32),
            "d_year": y.astype(np.int64),
            "d_moy": moy.astype(np.int64),
            "d_dom": dom.astype(np.int64),
            "d_qoy": ((moy - 1) // 3 + 1).astype(np.int64),
            "d_day_name": dow.astype(np.int32),
            "d_month_seq": (month0 + 840).astype(np.int64),
        }

    def _item(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("item", c)
        brand_id = _uniform_int(s("brand"), idx, 1, 1000)
        class_id = _uniform_int(s("class"), idx, 1, 100)
        return {
            "i_item_sk": idx.astype(np.int64) + 1,
            "i_item_id": idx.astype(np.int32),
            "i_item_desc": (_hash_u64(s("desc"), idx) % 4096).astype(np.int32),
            "i_brand_id": brand_id,
            "i_brand": (brand_id - 1).astype(np.int32),
            "i_class_id": class_id,
            "i_class": (class_id - 1).astype(np.int32),
            "i_category_id": (class_id - 1) % 10 + 1,
            "i_category": ((class_id - 1) % 10).astype(np.int32),
            "i_manufact_id": _uniform_int(s("manufact"), idx, 1, 1000),
            "i_manager_id": _uniform_int(s("manager"), idx, 1, 100),
            "i_current_price": _uniform_int(s("price"), idx, 100, 9999),
        }

    def _customer_demographics(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        # mixed-radix decode of the demographic cross product (spec
        # enumerates all combinations exactly once)
        x = idx.copy()
        gender = x % 2; x //= 2
        marital = x % 5; x //= 5
        education = x % 7; x //= 7
        purchase = x % 20; x //= 20
        credit = x % 4; x //= 4
        dep = x % 7; x //= 7
        dep_emp = x % 7; x //= 7
        dep_col = x % 7
        return {
            "cd_demo_sk": idx.astype(np.int64) + 1,
            "cd_gender": gender.astype(np.int32),
            "cd_marital_status": marital.astype(np.int32),
            "cd_education_status": education.astype(np.int32),
            "cd_purchase_estimate": (purchase + 1).astype(np.int64) * 500,
            "cd_credit_rating": credit.astype(np.int32),
            "cd_dep_count": dep.astype(np.int64),
            "cd_dep_employed_count": dep_emp.astype(np.int64),
            "cd_dep_college_count": dep_col.astype(np.int64),
        }

    def _promotion(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("promotion", c)
        chan = lambda c: (_hash_u64(s(c), idx) % 10 == 0).astype(np.int32)  # 10% 'Y'
        return {
            "p_promo_sk": idx.astype(np.int64) + 1,
            "p_promo_id": idx.astype(np.int32),
            "p_channel_dmail": chan("dmail"),
            "p_channel_email": chan("email"),
            "p_channel_event": chan("event"),
            "p_channel_tv": chan("tv"),
        }

    def _store(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("store", c)
        return {
            "s_store_sk": idx.astype(np.int64) + 1,
            "s_store_id": idx.astype(np.int32),
            "s_store_name": (idx % 8).astype(np.int32),
            "s_number_employees": _uniform_int(s("emp"), idx, 200, 300),
            "s_state": (_hash_u64(s("state"), idx) % len(STATES)).astype(np.int32),
        }

    def _store_sales(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("store_sales", c)
        date_sk = D_SK0 + _SALES_START + _uniform_int(s("date"), idx, 0, _SALES_DAYS - 1)
        qty = _uniform_int(s("qty"), idx, 1, 100)
        wholesale = _uniform_int(s("wholesale"), idx, 100, 8800)
        markup = _uniform_int(s("markup"), idx, 100, 200)  # 1.00x-2.00x, scale 2
        list_price = wholesale * markup // 100
        discount = _uniform_int(s("discount"), idx, 0, 99)  # % of list
        sales_price = list_price * (100 - discount) // 100
        coupon_on = _hash_u64(s("coupon_on"), idx) % 5 == 0
        coupon = np.where(coupon_on, sales_price * qty // 10, 0)
        ext_sales = qty * sales_price
        ext_list = qty * list_price
        net_paid = ext_sales - coupon
        # 20% of cdemo/promo fks are 0 = "null" (no matching dimension row)
        promo = np.where(
            _hash_u64(s("promo_null"), idx) % 5 == 0,
            0,
            _uniform_int(s("promo"), idx, 1, self.n_promos),
        )
        return {
            "ss_sold_date_sk": date_sk,
            "ss_item_sk": _uniform_int(s("item"), idx, 1, self.n_items),
            "ss_customer_sk": _uniform_int(s("cust"), idx, 1, self.n_customers),
            "ss_cdemo_sk": _uniform_int(s("cdemo"), idx, 1, self.cd_rows),
            "ss_store_sk": _uniform_int(s("store"), idx, 1, self.n_stores),
            "ss_promo_sk": promo,
            "ss_ticket_number": idx.astype(np.int64) + 1,
            "ss_quantity": qty,
            "ss_wholesale_cost": wholesale,
            "ss_list_price": list_price,
            "ss_sales_price": sales_price,
            "ss_ext_discount_amt": (ext_list - ext_sales),
            "ss_ext_sales_price": ext_sales,
            "ss_ext_list_price": ext_list,
            "ss_coupon_amt": coupon,
            "ss_net_paid": net_paid,
            "ss_net_profit": net_paid - qty * wholesale,
        }

    # -- Page production ----------------------------------------------------
    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        cols = self.generate_split(table, split)
        schema = SCHEMAS[table]
        arrays = [cols[name] for name, _ in schema]
        types = [t for _, t in schema]
        dicts = [self.dictionary_for(table, name) for name, _ in schema]
        return Page.from_arrays(arrays, types, dictionaries=dicts, capacity=capacity)

    def pages(self, table: str, capacity: Optional[int] = None) -> Iterator[Page]:
        for i in range(self.num_splits(table)):
            yield self.page_for_split(table, i, capacity=capacity)
