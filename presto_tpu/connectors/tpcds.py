"""Deterministic TPC-DS generator connector — full 24-table schema.

Reference analog: ``presto-tpcds`` (teradata tpcds-backed generator,
`presto-tpcds/src/main/java/com/facebook/presto/tpcds/`).  From-scratch
counter-hash generation in the same style as connectors/tpch.py:
every value is a pure function of (table, column, row index), so splits
generate independently on any worker.  Distributions follow the TPC-DS
spec's shapes (fact rows scale with sf, dimensions fixed or sublinear;
customer_demographics is the spec's exact 1,920,800-row demographic
cross product; returns sample their parent sales so return joins on
(item, ticket/order) resolve) — byte-parity with the official dsdgen is
a non-goal since correctness is oracle-checked on the same generated
data.

All 24 spec tables exist with the column subsets the benchmark corpus
(tests/tpcds_queries.py) exercises; columns grow with the corpus.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from presto_tpu.connectors.tpch import PatternDictionary, _hash_u64, _uniform_int
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DATE, INTEGER, VARCHAR, DecimalType, Type

_MONEY = DecimalType(12, 2)
_GMT = DecimalType(5, 2)

# date_dim: 1900-01-01 .. 2100-01-01, sk = julian-style offset
DATE_DIM_ROWS = 73049
D_SK0 = 2415022  # spec's first d_date_sk
_EPOCH_OFF = (np.datetime64("1970-01-01") - np.datetime64("1900-01-01")).astype(int)

# sales window: 1998-01-01 (+5 years)
_SALES_START = int((np.datetime64("1998-01-01") - np.datetime64("1900-01-01")).astype(int))
_SALES_DAYS = 1826

GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
YN = ["N", "Y"]
BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown"]
COLORS = ["red", "green", "blue", "yellow", "black", "white", "pink", "purple",
          "orange", "brown", "cyan", "magenta", "olive", "navy", "teal", "maroon"]
SIZES = ["small", "medium", "large", "extra large", "economy", "N/A", "petite"]
SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "PRIVATECARRIER",
            "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES",
            "ZOUROS", "GERMA", "DIAMOND", "RUPEKSA", "GREAT EASTERN", "HARMSTORF"]
CITIES = ["Fairview", "Midway", "Oakland", "Riverside", "Centerville", "Five Points",
          "Greenville", "Liberty", "Pleasant Hill", "Salem", "Union", "Bethel",
          "Clinton", "Enterprise", "Friendship", "Glendale", "Lakeview", "Marion",
          "Mount Olive", "Springfield"]
COUNTIES = ["Williamson County", "Ziebach County", "Walker County", "Daviess County",
            "Barrow County", "Franklin Parish", "Luce County", "Richland County",
            "Furnas County", "Maverick County"]
COUNTRIES = ["United States"]
REASONS = ["Package was damaged", "Stopped working", "Did not like the color",
           "Did not like the model", "Parts missing", "Does not work with a product",
           "Gift exchange", "Did not fit", "Wrong size", "Not the product ordered",
           "Found a better price", "Ordered twice", "No longer needed",
           "Did not like the warranty", "unknown"]
STATES = ["TN", "CA", "TX", "OH", "GA", "NY", "WA", "IL", "MI", "FL"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
             "Saturday"]

CD_ROWS = 2 * 5 * 7 * 20 * 4 * 7 * 7 * 7  # 1,920,800 (spec cross product)
HD_ROWS = 20 * 6 * 10 * 6  # 7,200 (income band x buy potential x deps x vehicles)
IB_ROWS = 20
TIME_ROWS = 86400
INV_WEEKS = 261


def _seed(t: str, c: str) -> int:
    h = 1469598103934665603
    for ch in f"tpcds.{t}.{c}":
        h = ((h ^ ord(ch)) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


SCHEMAS: Dict[str, List[Tuple[str, Type]]] = {
    "date_dim": [
        ("d_date_sk", BIGINT), ("d_date", DATE), ("d_year", BIGINT),
        ("d_moy", BIGINT), ("d_dom", BIGINT), ("d_qoy", BIGINT),
        ("d_day_name", VARCHAR), ("d_month_seq", BIGINT),
        ("d_week_seq", BIGINT), ("d_dow", BIGINT),
    ],
    "time_dim": [
        ("t_time_sk", BIGINT), ("t_time", BIGINT), ("t_hour", BIGINT),
        ("t_minute", BIGINT), ("t_second", BIGINT), ("t_am_pm", VARCHAR),
    ],
    "item": [
        ("i_item_sk", BIGINT), ("i_item_id", VARCHAR), ("i_item_desc", VARCHAR),
        ("i_brand_id", BIGINT), ("i_brand", VARCHAR),
        ("i_class_id", BIGINT), ("i_class", VARCHAR),
        ("i_category_id", BIGINT), ("i_category", VARCHAR),
        ("i_manufact_id", BIGINT), ("i_manufact", VARCHAR),
        ("i_manager_id", BIGINT), ("i_current_price", _MONEY),
        ("i_color", VARCHAR), ("i_size", VARCHAR),
    ],
    "customer": [
        ("c_customer_sk", BIGINT), ("c_customer_id", VARCHAR),
        ("c_current_cdemo_sk", BIGINT), ("c_current_hdemo_sk", BIGINT),
        ("c_current_addr_sk", BIGINT), ("c_first_name", VARCHAR),
        ("c_last_name", VARCHAR), ("c_birth_month", BIGINT),
        ("c_birth_year", BIGINT), ("c_birth_country", VARCHAR),
        ("c_first_sales_date_sk", BIGINT), ("c_first_shipto_date_sk", BIGINT),
    ],
    "customer_address": [
        ("ca_address_sk", BIGINT), ("ca_address_id", VARCHAR),
        ("ca_city", VARCHAR), ("ca_county", VARCHAR), ("ca_state", VARCHAR),
        ("ca_zip", VARCHAR), ("ca_country", VARCHAR), ("ca_gmt_offset", _GMT),
    ],
    "customer_demographics": [
        ("cd_demo_sk", BIGINT), ("cd_gender", VARCHAR),
        ("cd_marital_status", VARCHAR), ("cd_education_status", VARCHAR),
        ("cd_purchase_estimate", BIGINT), ("cd_credit_rating", VARCHAR),
        ("cd_dep_count", BIGINT), ("cd_dep_employed_count", BIGINT),
        ("cd_dep_college_count", BIGINT),
    ],
    "household_demographics": [
        ("hd_demo_sk", BIGINT), ("hd_income_band_sk", BIGINT),
        ("hd_buy_potential", VARCHAR), ("hd_dep_count", BIGINT),
        ("hd_vehicle_count", BIGINT),
    ],
    "income_band": [
        ("ib_income_band_sk", BIGINT), ("ib_lower_bound", BIGINT),
        ("ib_upper_bound", BIGINT),
    ],
    "promotion": [
        ("p_promo_sk", BIGINT), ("p_promo_id", VARCHAR),
        ("p_channel_dmail", VARCHAR), ("p_channel_email", VARCHAR),
        ("p_channel_event", VARCHAR), ("p_channel_tv", VARCHAR),
    ],
    "reason": [
        ("r_reason_sk", BIGINT), ("r_reason_id", VARCHAR),
        ("r_reason_desc", VARCHAR),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", BIGINT), ("sm_ship_mode_id", VARCHAR),
        ("sm_type", VARCHAR), ("sm_carrier", VARCHAR),
    ],
    "store": [
        ("s_store_sk", BIGINT), ("s_store_id", VARCHAR),
        ("s_store_name", VARCHAR), ("s_number_employees", BIGINT),
        ("s_state", VARCHAR), ("s_city", VARCHAR), ("s_county", VARCHAR),
        ("s_zip", VARCHAR), ("s_gmt_offset", _GMT),
    ],
    "warehouse": [
        ("w_warehouse_sk", BIGINT), ("w_warehouse_id", VARCHAR),
        ("w_warehouse_name", VARCHAR), ("w_warehouse_sq_ft", BIGINT),
        ("w_state", VARCHAR),
    ],
    "call_center": [
        ("cc_call_center_sk", BIGINT), ("cc_call_center_id", VARCHAR),
        ("cc_name", VARCHAR), ("cc_manager", VARCHAR), ("cc_county", VARCHAR),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", BIGINT), ("cp_catalog_page_id", VARCHAR),
    ],
    "web_page": [
        ("wp_web_page_sk", BIGINT), ("wp_web_page_id", VARCHAR),
        ("wp_char_count", BIGINT),
    ],
    "web_site": [
        ("web_site_sk", BIGINT), ("web_site_id", VARCHAR), ("web_name", VARCHAR),
    ],
    "inventory": [
        ("inv_date_sk", BIGINT), ("inv_item_sk", BIGINT),
        ("inv_warehouse_sk", BIGINT), ("inv_quantity_on_hand", BIGINT),
    ],
    "store_sales": [
        ("ss_sold_date_sk", BIGINT), ("ss_sold_time_sk", BIGINT),
        ("ss_item_sk", BIGINT), ("ss_customer_sk", BIGINT),
        ("ss_cdemo_sk", BIGINT), ("ss_hdemo_sk", BIGINT),
        ("ss_addr_sk", BIGINT), ("ss_store_sk", BIGINT),
        ("ss_promo_sk", BIGINT), ("ss_ticket_number", BIGINT),
        ("ss_quantity", BIGINT),
        ("ss_wholesale_cost", _MONEY), ("ss_list_price", _MONEY),
        ("ss_sales_price", _MONEY), ("ss_ext_discount_amt", _MONEY),
        ("ss_ext_sales_price", _MONEY), ("ss_ext_wholesale_cost", _MONEY),
        ("ss_ext_list_price", _MONEY), ("ss_coupon_amt", _MONEY),
        ("ss_net_paid", _MONEY), ("ss_net_profit", _MONEY),
    ],
    "store_returns": [
        ("sr_returned_date_sk", BIGINT), ("sr_item_sk", BIGINT),
        ("sr_customer_sk", BIGINT), ("sr_cdemo_sk", BIGINT),
        ("sr_store_sk", BIGINT), ("sr_reason_sk", BIGINT),
        ("sr_ticket_number", BIGINT), ("sr_return_quantity", BIGINT),
        ("sr_return_amt", _MONEY), ("sr_net_loss", _MONEY),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", BIGINT), ("cs_sold_time_sk", BIGINT),
        ("cs_ship_date_sk", BIGINT), ("cs_bill_customer_sk", BIGINT),
        ("cs_bill_cdemo_sk", BIGINT), ("cs_bill_hdemo_sk", BIGINT),
        ("cs_bill_addr_sk", BIGINT), ("cs_ship_customer_sk", BIGINT),
        ("cs_ship_addr_sk", BIGINT), ("cs_call_center_sk", BIGINT),
        ("cs_catalog_page_sk", BIGINT), ("cs_ship_mode_sk", BIGINT),
        ("cs_warehouse_sk", BIGINT), ("cs_item_sk", BIGINT),
        ("cs_promo_sk", BIGINT), ("cs_order_number", BIGINT),
        ("cs_quantity", BIGINT),
        ("cs_wholesale_cost", _MONEY), ("cs_list_price", _MONEY),
        ("cs_sales_price", _MONEY), ("cs_ext_discount_amt", _MONEY),
        ("cs_ext_sales_price", _MONEY), ("cs_ext_wholesale_cost", _MONEY),
        ("cs_ext_list_price", _MONEY), ("cs_ext_ship_cost", _MONEY),
        ("cs_coupon_amt", _MONEY),
        ("cs_net_paid", _MONEY), ("cs_net_profit", _MONEY),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", BIGINT), ("cr_item_sk", BIGINT),
        ("cr_returning_customer_sk", BIGINT), ("cr_call_center_sk", BIGINT),
        ("cr_reason_sk", BIGINT), ("cr_order_number", BIGINT),
        ("cr_return_quantity", BIGINT), ("cr_return_amount", _MONEY),
        ("cr_net_loss", _MONEY),
    ],
    "web_sales": [
        ("ws_sold_date_sk", BIGINT), ("ws_sold_time_sk", BIGINT),
        ("ws_ship_date_sk", BIGINT), ("ws_item_sk", BIGINT),
        ("ws_bill_customer_sk", BIGINT), ("ws_bill_addr_sk", BIGINT),
        ("ws_ship_customer_sk", BIGINT), ("ws_ship_addr_sk", BIGINT),
        ("ws_web_page_sk", BIGINT), ("ws_web_site_sk", BIGINT),
        ("ws_ship_mode_sk", BIGINT), ("ws_warehouse_sk", BIGINT),
        ("ws_promo_sk", BIGINT), ("ws_order_number", BIGINT),
        ("ws_quantity", BIGINT),
        ("ws_wholesale_cost", _MONEY), ("ws_list_price", _MONEY),
        ("ws_sales_price", _MONEY), ("ws_ext_discount_amt", _MONEY),
        ("ws_ext_sales_price", _MONEY), ("ws_ext_wholesale_cost", _MONEY),
        ("ws_ext_list_price", _MONEY), ("ws_ext_ship_cost", _MONEY),
        ("ws_net_paid", _MONEY), ("ws_net_profit", _MONEY),
    ],
    "web_returns": [
        ("wr_returned_date_sk", BIGINT), ("wr_item_sk", BIGINT),
        ("wr_returning_customer_sk", BIGINT), ("wr_reason_sk", BIGINT),
        ("wr_order_number", BIGINT), ("wr_return_quantity", BIGINT),
        ("wr_return_amt", _MONEY), ("wr_net_loss", _MONEY),
    ],
}


class Tpcds:
    def __init__(self, sf: float = 1.0, split_rows: int = 1 << 20,
                 cd_rows: Optional[int] = None, inv_rows: Optional[int] = None):
        self.sf = float(sf)
        self.split_rows = int(split_rows)
        # test harnesses may truncate the demographic cross product and
        # the inventory fact (both are sf-independent monsters)
        self.cd_rows = int(cd_rows) if cd_rows is not None else CD_ROWS
        self.n_store_sales = max(int(round(2_880_000 * self.sf)), 1)
        self.n_catalog_sales = max(int(round(1_441_548 * self.sf)), 1)
        self.n_web_sales = max(int(round(719_384 * self.sf)), 1)
        self.n_store_returns = max(int(round(287_514 * self.sf)), 1)
        self.n_catalog_returns = max(int(round(144_067 * self.sf)), 1)
        self.n_web_returns = max(int(round(71_763 * self.sf)), 1)
        self.n_items = 18000
        self.n_customers = max(int(round(100_000 * self.sf)), 1)
        self.n_addresses = max(int(round(50_000 * self.sf)), 1)
        self.n_promos = 300
        self.n_stores = max(int(round(12 * max(self.sf, 1.0))), 1)
        self.n_warehouses = 5
        self.n_call_centers = 6
        self.n_catalog_pages = 11718
        self.n_web_pages = 60
        self.n_web_sites = 30
        self.n_reasons = len(REASONS)
        self.n_ship_modes = len(SHIP_TYPES) * 4
        default_inv = INV_WEEKS * self.n_warehouses * self.n_items
        self.inv_rows = int(inv_rows) if inv_rows is not None else default_inv
        self._dicts: Dict[str, Dictionary] = {}

    # -- metadata -----------------------------------------------------------
    def table_names(self) -> List[str]:
        return list(SCHEMAS.keys())

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return SCHEMAS[table]

    def row_count(self, table: str) -> int:
        return {
            "date_dim": DATE_DIM_ROWS,
            "time_dim": TIME_ROWS,
            "item": self.n_items,
            "customer": self.n_customers,
            "customer_address": self.n_addresses,
            "customer_demographics": self.cd_rows,
            "household_demographics": HD_ROWS,
            "income_band": IB_ROWS,
            "promotion": self.n_promos,
            "reason": self.n_reasons,
            "ship_mode": self.n_ship_modes,
            "store": self.n_stores,
            "warehouse": self.n_warehouses,
            "call_center": self.n_call_centers,
            "catalog_page": self.n_catalog_pages,
            "web_page": self.n_web_pages,
            "web_site": self.n_web_sites,
            "inventory": self.inv_rows,
            "store_sales": self.n_store_sales,
            "store_returns": self.n_store_returns,
            "catalog_sales": self.n_catalog_sales,
            "catalog_returns": self.n_catalog_returns,
            "web_sales": self.n_web_sales,
            "web_returns": self.n_web_returns,
        }[table]

    def num_splits(self, table: str) -> int:
        return max(1, -(-self.row_count(table) // self.split_rows))

    def table_version(self, table: str) -> int:
        """Generated data is immutable: a constant version marks every
        table cacheable forever (serving-tier result/subplan caches)."""
        return 0

    def max_split_rows(self, table: str) -> int:
        return min(self.split_rows, max(self.row_count(table), 1))

    def primary_key(self, table: str) -> Optional[List[str]]:
        return {
            "date_dim": ["d_date_sk"],
            "time_dim": ["t_time_sk"],
            "item": ["i_item_sk"],
            "customer": ["c_customer_sk"],
            "customer_address": ["ca_address_sk"],
            "customer_demographics": ["cd_demo_sk"],
            "household_demographics": ["hd_demo_sk"],
            "income_band": ["ib_income_band_sk"],
            "promotion": ["p_promo_sk"],
            "reason": ["r_reason_sk"],
            "ship_mode": ["sm_ship_mode_sk"],
            "store": ["s_store_sk"],
            "warehouse": ["w_warehouse_sk"],
            "call_center": ["cc_call_center_sk"],
            "catalog_page": ["cp_catalog_page_sk"],
            "web_page": ["wp_web_page_sk"],
            "web_site": ["web_site_sk"],
        }.get(table)

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        t = dict(SCHEMAS[table])[column]
        if t.is_string:
            return (0, len(self.dictionary_for(table, column)) - 1)
        sales_dates = (D_SK0 + _SALES_START, D_SK0 + _SALES_START + _SALES_DAYS - 1)
        return_dates = (sales_dates[0], sales_dates[1] + 90)
        doms: Dict[str, Tuple[int, int]] = {
            "d_date_sk": (D_SK0, D_SK0 + DATE_DIM_ROWS - 1),
            "d_year": (1900, 2100),
            "d_moy": (1, 12),
            "d_dom": (1, 31),
            "d_qoy": (1, 4),
            "d_dow": (0, 6),
            "t_time_sk": (0, TIME_ROWS - 1),
            "t_hour": (0, 23),
            "t_minute": (0, 59),
            "t_second": (0, 59),
            "i_item_sk": (1, self.n_items),
            "i_brand_id": (1, 1000),
            "i_class_id": (1, 100),
            "i_category_id": (1, 10),
            "i_manufact_id": (1, 1000),
            "i_manager_id": (1, 100),
            "c_customer_sk": (1, self.n_customers),
            "c_current_cdemo_sk": (1, self.cd_rows),
            "c_current_hdemo_sk": (1, HD_ROWS),
            "c_current_addr_sk": (1, self.n_addresses),
            "c_birth_month": (1, 12),
            "c_birth_year": (1920, 1992),
            "ca_address_sk": (1, self.n_addresses),
            "cd_demo_sk": (1, self.cd_rows),
            "hd_demo_sk": (1, HD_ROWS),
            "hd_income_band_sk": (1, IB_ROWS),
            "hd_dep_count": (0, 9),
            "hd_vehicle_count": (0, 5),
            "ib_income_band_sk": (1, IB_ROWS),
            "p_promo_sk": (1, self.n_promos),
            "r_reason_sk": (1, self.n_reasons),
            "sm_ship_mode_sk": (1, self.n_ship_modes),
            "s_store_sk": (1, self.n_stores),
            "w_warehouse_sk": (1, self.n_warehouses),
            "cc_call_center_sk": (1, self.n_call_centers),
            "cp_catalog_page_sk": (1, self.n_catalog_pages),
            "wp_web_page_sk": (1, self.n_web_pages),
            "web_site_sk": (1, self.n_web_sites),
            "inv_date_sk": (D_SK0 + _SALES_START, D_SK0 + _SALES_START + 7 * INV_WEEKS),
            "inv_item_sk": (1, self.n_items),
            "inv_warehouse_sk": (1, self.n_warehouses),
            "ss_sold_date_sk": sales_dates,
            "ss_sold_time_sk": (0, TIME_ROWS - 1),
            "ss_item_sk": (1, self.n_items),
            "ss_customer_sk": (1, self.n_customers),
            "ss_cdemo_sk": (1, self.cd_rows),
            "ss_hdemo_sk": (1, HD_ROWS),
            "ss_addr_sk": (1, self.n_addresses),
            "ss_store_sk": (1, self.n_stores),
            "ss_promo_sk": (0, self.n_promos),
            "ss_quantity": (1, 100),
            "sr_returned_date_sk": return_dates,
            "sr_item_sk": (1, self.n_items),
            "sr_customer_sk": (1, self.n_customers),
            "sr_cdemo_sk": (1, self.cd_rows),
            "sr_store_sk": (1, self.n_stores),
            "sr_reason_sk": (1, self.n_reasons),
            "cs_sold_date_sk": sales_dates,
            "cs_sold_time_sk": (0, TIME_ROWS - 1),
            "cs_ship_date_sk": (sales_dates[0], sales_dates[1] + 30),
            "cs_bill_customer_sk": (1, self.n_customers),
            "cs_bill_cdemo_sk": (1, self.cd_rows),
            "cs_bill_hdemo_sk": (1, HD_ROWS),
            "cs_bill_addr_sk": (1, self.n_addresses),
            "cs_ship_customer_sk": (1, self.n_customers),
            "cs_ship_addr_sk": (1, self.n_addresses),
            "cs_call_center_sk": (1, self.n_call_centers),
            "cs_catalog_page_sk": (1, self.n_catalog_pages),
            "cs_ship_mode_sk": (1, self.n_ship_modes),
            "cs_warehouse_sk": (1, self.n_warehouses),
            "cs_item_sk": (1, self.n_items),
            "cs_promo_sk": (0, self.n_promos),
            "cs_quantity": (1, 100),
            "cr_returned_date_sk": return_dates,
            "cr_item_sk": (1, self.n_items),
            "cr_returning_customer_sk": (1, self.n_customers),
            "cr_call_center_sk": (1, self.n_call_centers),
            "cr_reason_sk": (1, self.n_reasons),
            "ws_sold_date_sk": sales_dates,
            "ws_sold_time_sk": (0, TIME_ROWS - 1),
            "ws_ship_date_sk": (sales_dates[0], sales_dates[1] + 30),
            "ws_item_sk": (1, self.n_items),
            "ws_bill_customer_sk": (1, self.n_customers),
            "ws_bill_addr_sk": (1, self.n_addresses),
            "ws_ship_customer_sk": (1, self.n_customers),
            "ws_ship_addr_sk": (1, self.n_addresses),
            "ws_web_page_sk": (1, self.n_web_pages),
            "ws_web_site_sk": (1, self.n_web_sites),
            "ws_ship_mode_sk": (1, self.n_ship_modes),
            "ws_warehouse_sk": (1, self.n_warehouses),
            "ws_promo_sk": (0, self.n_promos),
            "ws_quantity": (1, 100),
            "wr_returned_date_sk": return_dates,
            "wr_item_sk": (1, self.n_items),
            "wr_returning_customer_sk": (1, self.n_customers),
            "wr_reason_sk": (1, self.n_reasons),
        }
        return doms.get(column)

    # -- dictionaries -------------------------------------------------------
    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        t = dict(SCHEMAS[table])[column]
        if not t.is_string:
            return None
        if column in self._dicts:
            return self._dicts[column]
        fixed = {
            "d_day_name": DAY_NAMES,
            "t_am_pm": ["AM", "PM"],
            "i_category": CATEGORIES,
            "i_color": COLORS,
            "i_size": SIZES,
            "cd_gender": GENDERS,
            "cd_marital_status": MARITAL,
            "cd_education_status": EDUCATION,
            "cd_credit_rating": CREDIT,
            "hd_buy_potential": BUY_POTENTIAL,
            "ca_city": CITIES,
            "ca_county": COUNTIES,
            "ca_state": STATES,
            "ca_country": COUNTRIES,
            "c_birth_country": ["UNITED STATES", "CANADA", "MEXICO", "GERMANY",
                                "JAPAN", "BRAZIL", "INDIA", "FRANCE"],
            "p_channel_dmail": YN, "p_channel_email": YN,
            "p_channel_event": YN, "p_channel_tv": YN,
            "r_reason_desc": REASONS,
            "sm_type": SHIP_TYPES,
            "sm_carrier": CARRIERS,
            "s_store_name": ["ought", "able", "pri", "ese", "anti", "cally",
                             "ation", "eing"],
            "s_state": STATES,
            "s_city": CITIES,
            "s_county": COUNTIES,
            "w_warehouse_name": ["Conventional childr", "Important issues liv",
                                 "Doors canno", "Bad cards must make.", "arehouse"],
            "w_state": STATES,
            "cc_name": ["NY Metro", "Mid Atlantic", "Midwest", "North Midwest",
                        "Pacific Northwest", "California"],
            "cc_county": COUNTIES,
            "web_name": [f"site_{i}" for i in range(30)],
        }
        if column in fixed:
            d: Dictionary = Dictionary(fixed[column])
        elif column == "i_item_id":
            d = PatternDictionary(lambda i: f"AAAAAAAA{i + 1:08d}", self.n_items)
        elif column == "i_item_desc":
            d = PatternDictionary(lambda i: f"item description {i + 1}", 4096)
        elif column == "i_brand":
            d = PatternDictionary(lambda i: f"brand#{i + 1}", 1000)
        elif column == "i_class":
            d = PatternDictionary(lambda i: f"class#{i + 1}", 100)
        elif column == "i_manufact":
            d = PatternDictionary(lambda i: f"manufact#{i + 1}", 1000)
        elif column == "c_customer_id":
            d = PatternDictionary(lambda i: f"AAAAAAAA{i + 1:08d}C", self.n_customers)
        elif column == "c_first_name":
            d = PatternDictionary(lambda i: f"First{i}", 512)
        elif column == "c_last_name":
            d = PatternDictionary(lambda i: f"Last{i}", 1024)
        elif column == "ca_address_id":
            d = PatternDictionary(lambda i: f"AAAAAAAA{i + 1:08d}A", self.n_addresses)
        elif column in ("ca_zip", "s_zip"):
            d = PatternDictionary(lambda i: f"{10000 + i * 7 % 90000:05d}", 400)
        elif column == "p_promo_id":
            d = PatternDictionary(lambda i: f"promo#{i + 1:08d}", self.n_promos)
        elif column == "r_reason_id":
            d = PatternDictionary(lambda i: f"reason#{i + 1}", self.n_reasons)
        elif column == "sm_ship_mode_id":
            d = PatternDictionary(lambda i: f"ship#{i + 1}", self.n_ship_modes)
        elif column == "s_store_id":
            d = PatternDictionary(lambda i: f"store#{i + 1:08d}", self.n_stores)
        elif column == "w_warehouse_id":
            d = PatternDictionary(lambda i: f"wh#{i + 1}", self.n_warehouses)
        elif column == "cc_call_center_id":
            d = PatternDictionary(lambda i: f"cc#{i + 1}", self.n_call_centers)
        elif column == "cc_manager":
            d = PatternDictionary(lambda i: f"Manager {i}", 64)
        elif column == "cp_catalog_page_id":
            d = PatternDictionary(lambda i: f"cp#{i + 1:08d}", self.n_catalog_pages)
        elif column == "wp_web_page_id":
            d = PatternDictionary(lambda i: f"wp#{i + 1}", self.n_web_pages)
        elif column == "web_site_id":
            d = PatternDictionary(lambda i: f"web#{i + 1}", self.n_web_sites)
        else:
            raise KeyError(column)
        self._dicts[column] = d
        return d

    # -- generators ---------------------------------------------------------
    def generate_split(self, table: str, split: int) -> Dict[str, np.ndarray]:
        n = self.row_count(table)
        lo = split * self.split_rows
        hi = min(lo + self.split_rows, n)
        idx = np.arange(lo, hi)
        return getattr(self, f"_{table}")(idx)

    def _date_dim(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        days = idx.astype("int64")  # days since 1900-01-01
        dt = np.datetime64("1900-01-01") + days.astype("timedelta64[D]")
        y = dt.astype("datetime64[Y]").astype(int) + 1970
        month0 = dt.astype("datetime64[M]").astype(int)
        moy = month0 % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        dow = (days + 1) % 7  # 1900-01-01 was a Monday; 0=Sunday
        return {
            "d_date_sk": days + D_SK0,
            "d_date": (days - _EPOCH_OFF).astype(np.int32),
            "d_year": y.astype(np.int64),
            "d_moy": moy.astype(np.int64),
            "d_dom": dom.astype(np.int64),
            "d_qoy": ((moy - 1) // 3 + 1).astype(np.int64),
            "d_day_name": dow.astype(np.int32),
            "d_month_seq": (month0 + 840).astype(np.int64),
            "d_week_seq": ((days + 1) // 7 + 5217).astype(np.int64),
            "d_dow": dow.astype(np.int64),
        }

    def _time_dim(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        sec = idx.astype(np.int64)
        return {
            "t_time_sk": sec,
            "t_time": sec,
            "t_hour": sec // 3600,
            "t_minute": (sec // 60) % 60,
            "t_second": sec % 60,
            "t_am_pm": (sec >= 43200).astype(np.int32),
        }

    def _item(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("item", c)
        brand_id = _uniform_int(s("brand"), idx, 1, 1000)
        class_id = _uniform_int(s("class"), idx, 1, 100)
        return {
            "i_item_sk": idx.astype(np.int64) + 1,
            "i_item_id": idx.astype(np.int32),
            "i_item_desc": (_hash_u64(s("desc"), idx) % 4096).astype(np.int32),
            "i_brand_id": brand_id,
            "i_brand": (brand_id - 1).astype(np.int32),
            "i_class_id": class_id,
            "i_class": (class_id - 1).astype(np.int32),
            "i_category_id": (class_id - 1) % 10 + 1,
            "i_category": ((class_id - 1) % 10).astype(np.int32),
            "i_manufact_id": _uniform_int(s("manufact"), idx, 1, 1000),
            "i_manufact": (_uniform_int(s("manufact"), idx, 1, 1000) - 1).astype(np.int32),
            "i_manager_id": _uniform_int(s("manager"), idx, 1, 100),
            "i_current_price": _uniform_int(s("price"), idx, 100, 9999),
            "i_color": (_hash_u64(s("color"), idx) % len(COLORS)).astype(np.int32),
            "i_size": (_hash_u64(s("size"), idx) % len(SIZES)).astype(np.int32),
        }

    def _customer(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("customer", c)
        first_sale = (D_SK0 + _SALES_START
                      + _uniform_int(s("first_sale"), idx, 0, _SALES_DAYS - 1))
        return {
            "c_customer_sk": idx.astype(np.int64) + 1,
            "c_customer_id": idx.astype(np.int32),
            "c_current_cdemo_sk": _uniform_int(s("cdemo"), idx, 1, self.cd_rows),
            "c_current_hdemo_sk": _uniform_int(s("hdemo"), idx, 1, HD_ROWS),
            "c_current_addr_sk": _uniform_int(s("addr"), idx, 1, self.n_addresses),
            "c_first_name": (_hash_u64(s("first"), idx) % 512).astype(np.int32),
            "c_last_name": (_hash_u64(s("last"), idx) % 1024).astype(np.int32),
            "c_birth_month": _uniform_int(s("bmonth"), idx, 1, 12),
            "c_birth_year": _uniform_int(s("byear"), idx, 1920, 1992),
            "c_birth_country": (_hash_u64(s("bcountry"), idx) % 8).astype(np.int32),
            "c_first_sales_date_sk": first_sale,
            "c_first_shipto_date_sk": first_sale + 30,
        }

    def _customer_address(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("customer_address", c)
        return {
            "ca_address_sk": idx.astype(np.int64) + 1,
            "ca_address_id": idx.astype(np.int32),
            "ca_city": (_hash_u64(s("city"), idx) % len(CITIES)).astype(np.int32),
            "ca_county": (_hash_u64(s("county"), idx) % len(COUNTIES)).astype(np.int32),
            "ca_state": (_hash_u64(s("state"), idx) % len(STATES)).astype(np.int32),
            "ca_zip": (_hash_u64(s("zip"), idx) % 400).astype(np.int32),
            "ca_country": np.zeros(len(idx), dtype=np.int32),
            "ca_gmt_offset": -(_uniform_int(s("gmt"), idx, 5, 8)) * 100,
        }

    def _customer_demographics(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        # mixed-radix decode of the demographic cross product (spec
        # enumerates all combinations exactly once)
        x = idx.copy()
        gender = x % 2; x //= 2
        marital = x % 5; x //= 5
        education = x % 7; x //= 7
        purchase = x % 20; x //= 20
        credit = x % 4; x //= 4
        dep = x % 7; x //= 7
        dep_emp = x % 7; x //= 7
        dep_col = x % 7
        return {
            "cd_demo_sk": idx.astype(np.int64) + 1,
            "cd_gender": gender.astype(np.int32),
            "cd_marital_status": marital.astype(np.int32),
            "cd_education_status": education.astype(np.int32),
            "cd_purchase_estimate": (purchase + 1).astype(np.int64) * 500,
            "cd_credit_rating": credit.astype(np.int32),
            "cd_dep_count": dep.astype(np.int64),
            "cd_dep_employed_count": dep_emp.astype(np.int64),
            "cd_dep_college_count": dep_col.astype(np.int64),
        }

    def _household_demographics(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        x = idx.copy()
        ib = x % IB_ROWS; x //= IB_ROWS
        bp = x % 6; x //= 6
        dep = x % 10; x //= 10
        veh = x % 6
        return {
            "hd_demo_sk": idx.astype(np.int64) + 1,
            "hd_income_band_sk": (ib + 1).astype(np.int64),
            "hd_buy_potential": bp.astype(np.int32),
            "hd_dep_count": dep.astype(np.int64),
            "hd_vehicle_count": veh.astype(np.int64),  # 0..5
        }

    def _income_band(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "ib_income_band_sk": idx.astype(np.int64) + 1,
            "ib_lower_bound": idx.astype(np.int64) * 10000,
            "ib_upper_bound": (idx.astype(np.int64) + 1) * 10000,
        }

    def _promotion(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("promotion", c)
        chan = lambda c: (_hash_u64(s(c), idx) % 10 == 0).astype(np.int32)  # 10% 'Y'
        return {
            "p_promo_sk": idx.astype(np.int64) + 1,
            "p_promo_id": idx.astype(np.int32),
            "p_channel_dmail": chan("dmail"),
            "p_channel_email": chan("email"),
            "p_channel_event": chan("event"),
            "p_channel_tv": chan("tv"),
        }

    def _reason(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "r_reason_sk": idx.astype(np.int64) + 1,
            "r_reason_id": idx.astype(np.int32),
            "r_reason_desc": idx.astype(np.int32),
        }

    def _ship_mode(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "sm_ship_mode_sk": idx.astype(np.int64) + 1,
            "sm_ship_mode_id": idx.astype(np.int32),
            "sm_type": (idx % len(SHIP_TYPES)).astype(np.int32),
            "sm_carrier": (idx % len(CARRIERS)).astype(np.int32),
        }

    def _store(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("store", c)
        return {
            "s_store_sk": idx.astype(np.int64) + 1,
            "s_store_id": idx.astype(np.int32),
            "s_store_name": (idx % 8).astype(np.int32),
            "s_number_employees": _uniform_int(s("emp"), idx, 200, 300),
            "s_state": (_hash_u64(s("state"), idx) % len(STATES)).astype(np.int32),
            "s_city": (_hash_u64(s("city"), idx) % len(CITIES)).astype(np.int32),
            "s_county": (_hash_u64(s("county"), idx) % len(COUNTIES)).astype(np.int32),
            # zips share customer_address's 400-value dictionary (first
            # 40 values only) so s_zip = ca_zip equijoins (q24) and
            # shared prefixes (q8) hit at useful rates
            "s_zip": (_hash_u64(s("zip"), idx) % 40).astype(np.int32),
            "s_gmt_offset": -(_uniform_int(s("gmt"), idx, 5, 8)) * 100,
        }

    def _warehouse(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("warehouse", c)
        return {
            "w_warehouse_sk": idx.astype(np.int64) + 1,
            "w_warehouse_id": idx.astype(np.int32),
            "w_warehouse_name": (idx % 5).astype(np.int32),
            "w_warehouse_sq_ft": _uniform_int(s("sqft"), idx, 50000, 1000000),
            "w_state": (_hash_u64(s("state"), idx) % len(STATES)).astype(np.int32),
        }

    def _call_center(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("call_center", c)
        return {
            "cc_call_center_sk": idx.astype(np.int64) + 1,
            "cc_call_center_id": idx.astype(np.int32),
            "cc_name": (idx % 6).astype(np.int32),
            "cc_manager": (_hash_u64(s("mgr"), idx) % 64).astype(np.int32),
            "cc_county": (_hash_u64(s("county"), idx) % len(COUNTIES)).astype(np.int32),
        }

    def _catalog_page(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "cp_catalog_page_sk": idx.astype(np.int64) + 1,
            "cp_catalog_page_id": idx.astype(np.int32),
        }

    def _web_page(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("web_page", c)
        return {
            "wp_web_page_sk": idx.astype(np.int64) + 1,
            "wp_web_page_id": idx.astype(np.int32),
            "wp_char_count": _uniform_int(s("chars"), idx, 100, 8000),
        }

    def _web_site(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "web_site_sk": idx.astype(np.int64) + 1,
            "web_site_id": idx.astype(np.int32),
            "web_name": (idx % 30).astype(np.int32),
        }

    def _inventory(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        # mixed-radix (item, warehouse, week) enumeration of the cross
        # product prefix; inv dates land on week boundaries like dsdgen.
        # week varies FASTEST so a truncated inv_rows still spans many
        # weeks (a per-item time series — q39's month-over-month cov
        # self-join needs at least two months of snapshots)
        s = lambda c: _seed("inventory", c)
        x = idx.copy()
        week = x % INV_WEEKS; x //= INV_WEEKS
        wh = x % self.n_warehouses; x //= self.n_warehouses
        item = x
        return {
            "inv_date_sk": (D_SK0 + _SALES_START + week * 7).astype(np.int64),
            "inv_item_sk": (item + 1).astype(np.int64),
            "inv_warehouse_sk": (wh + 1).astype(np.int64),
            "inv_quantity_on_hand": _uniform_int(s("qty"), idx, 0, 1000),
        }

    # ---- sales facts ------------------------------------------------------
    def _sales_core(self, t: str, idx: np.ndarray, n_items: int) -> Dict[str, np.ndarray]:
        """Shared price waterfall for the three sales channels."""
        s = lambda c: _seed(t, c)
        date_sk = D_SK0 + _SALES_START + _uniform_int(s("date"), idx, 0, _SALES_DAYS - 1)
        qty = _uniform_int(s("qty"), idx, 1, 100)
        wholesale = _uniform_int(s("wholesale"), idx, 100, 8800)
        markup = _uniform_int(s("markup"), idx, 100, 200)  # 1.00x-2.00x, scale 2
        list_price = wholesale * markup // 100
        discount = _uniform_int(s("discount"), idx, 0, 99)  # % of list
        sales_price = list_price * (100 - discount) // 100
        coupon_on = _hash_u64(s("coupon_on"), idx) % 5 == 0
        coupon = np.where(coupon_on, sales_price * qty // 10, 0)
        ext_sales = qty * sales_price
        ext_list = qty * list_price
        net_paid = ext_sales - coupon
        promo = np.where(
            _hash_u64(s("promo_null"), idx) % 5 == 0,
            0,
            _uniform_int(s("promo"), idx, 1, self.n_promos),
        )
        return {
            "date_sk": date_sk,
            "time_sk": _uniform_int(s("time"), idx, 0, TIME_ROWS - 1),
            "item_sk": _uniform_int(s("item"), idx, 1, n_items),
            "promo_sk": promo,
            "quantity": qty,
            "wholesale_cost": wholesale,
            "list_price": list_price,
            "sales_price": sales_price,
            "ext_discount_amt": ext_list - ext_sales,
            "ext_sales_price": ext_sales,
            "ext_wholesale_cost": qty * wholesale,
            "ext_list_price": ext_list,
            "coupon_amt": coupon,
            "net_paid": net_paid,
            "net_profit": net_paid - qty * wholesale,
        }

    def _store_sales(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("store_sales", c)
        core = self._sales_core("store_sales", idx, self.n_items)
        return {
            "ss_sold_date_sk": core["date_sk"],
            "ss_sold_time_sk": core["time_sk"],
            "ss_item_sk": core["item_sk"],
            "ss_customer_sk": _uniform_int(s("cust"), idx, 1, self.n_customers),
            "ss_cdemo_sk": _uniform_int(s("cdemo"), idx, 1, self.cd_rows),
            "ss_hdemo_sk": _uniform_int(s("hdemo"), idx, 1, HD_ROWS),
            "ss_addr_sk": _uniform_int(s("addr"), idx, 1, self.n_addresses),
            "ss_store_sk": _uniform_int(s("store"), idx, 1, self.n_stores),
            "ss_promo_sk": core["promo_sk"],
            "ss_ticket_number": idx.astype(np.int64) + 1,
            "ss_quantity": core["quantity"],
            "ss_wholesale_cost": core["wholesale_cost"],
            "ss_list_price": core["list_price"],
            "ss_sales_price": core["sales_price"],
            "ss_ext_discount_amt": core["ext_discount_amt"],
            "ss_ext_sales_price": core["ext_sales_price"],
            "ss_ext_wholesale_cost": core["ext_wholesale_cost"],
            "ss_ext_list_price": core["ext_list_price"],
            "ss_coupon_amt": core["coupon_amt"],
            "ss_net_paid": core["net_paid"],
            "ss_net_profit": core["net_profit"],
        }

    def _returns_core(self, ret_table: str, sale_table: str, n_sales: int,
                      idx: np.ndarray) -> Dict[str, np.ndarray]:
        """Shared return-fact machinery: each return samples a parent
        sale (pure index function, so (item, ticket/order) joins back)
        and re-derives the parent's price waterfall from the sale seeds."""
        s = lambda c: _seed(ret_table, c)
        ps = lambda c: _seed(sale_table, c)
        sale = (_hash_u64(s("sale"), idx) % n_sales).astype(np.int64)
        sale_date = D_SK0 + _SALES_START + _uniform_int(ps("date"), sale, 0, _SALES_DAYS - 1)
        sale_qty = _uniform_int(ps("qty"), sale, 1, 100)
        wholesale = _uniform_int(ps("wholesale"), sale, 100, 8800)
        markup = _uniform_int(ps("markup"), sale, 100, 200)
        list_price = wholesale * markup // 100
        discount = _uniform_int(ps("discount"), sale, 0, 99)
        sales_price = list_price * (100 - discount) // 100
        rqty = 1 + _hash_u64(s("rqty"), idx) % np.maximum(sale_qty, 1)
        ramt = rqty * sales_price
        return {
            "sale": sale,
            "returned_date_sk": sale_date + _uniform_int(s("lag"), idx, 1, 90),
            "item_sk": _uniform_int(ps("item"), sale, 1, self.n_items),
            "reason_sk": _uniform_int(s("reason"), idx, 1, self.n_reasons),
            "return_quantity": rqty.astype(np.int64),
            "return_amt": ramt.astype(np.int64),
            "net_loss": (ramt + _uniform_int(s("fee"), idx, 50, 10000)).astype(np.int64),
        }

    def _store_returns(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        core = self._returns_core("store_returns", "store_sales", self.n_store_sales, idx)
        sale = core["sale"]
        ss = lambda c: _seed("store_sales", c)
        return {
            "sr_returned_date_sk": core["returned_date_sk"],
            "sr_item_sk": core["item_sk"],
            "sr_customer_sk": _uniform_int(ss("cust"), sale, 1, self.n_customers),
            "sr_cdemo_sk": _uniform_int(ss("cdemo"), sale, 1, self.cd_rows),
            "sr_store_sk": _uniform_int(ss("store"), sale, 1, self.n_stores),
            "sr_reason_sk": core["reason_sk"],
            "sr_ticket_number": sale + 1,
            "sr_return_quantity": core["return_quantity"],
            "sr_return_amt": core["return_amt"],
            "sr_net_loss": core["net_loss"],
        }

    def _catalog_sales(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("catalog_sales", c)
        core = self._sales_core("catalog_sales", idx, self.n_items)
        ship_cost = core["ext_sales_price"] // 20
        return {
            "cs_sold_date_sk": core["date_sk"],
            "cs_sold_time_sk": core["time_sk"],
            "cs_ship_date_sk": core["date_sk"] + _uniform_int(s("shiplag"), idx, 1, 30),
            "cs_bill_customer_sk": _uniform_int(s("bcust"), idx, 1, self.n_customers),
            "cs_bill_cdemo_sk": _uniform_int(s("bcdemo"), idx, 1, self.cd_rows),
            "cs_bill_hdemo_sk": _uniform_int(s("bhdemo"), idx, 1, HD_ROWS),
            "cs_bill_addr_sk": _uniform_int(s("baddr"), idx, 1, self.n_addresses),
            "cs_ship_customer_sk": _uniform_int(s("scust"), idx, 1, self.n_customers),
            "cs_ship_addr_sk": _uniform_int(s("saddr"), idx, 1, self.n_addresses),
            "cs_call_center_sk": _uniform_int(s("cc"), idx, 1, self.n_call_centers),
            "cs_catalog_page_sk": _uniform_int(s("cp"), idx, 1, self.n_catalog_pages),
            "cs_ship_mode_sk": _uniform_int(s("sm"), idx, 1, self.n_ship_modes),
            "cs_warehouse_sk": _uniform_int(s("wh"), idx, 1, self.n_warehouses),
            "cs_item_sk": core["item_sk"],
            "cs_promo_sk": core["promo_sk"],
            "cs_order_number": idx.astype(np.int64) + 1,
            "cs_quantity": core["quantity"],
            "cs_wholesale_cost": core["wholesale_cost"],
            "cs_list_price": core["list_price"],
            "cs_sales_price": core["sales_price"],
            "cs_ext_discount_amt": core["ext_discount_amt"],
            "cs_ext_sales_price": core["ext_sales_price"],
            "cs_ext_wholesale_cost": core["ext_wholesale_cost"],
            "cs_ext_list_price": core["ext_list_price"],
            "cs_ext_ship_cost": ship_cost,
            "cs_coupon_amt": core["coupon_amt"],
            "cs_net_paid": core["net_paid"],
            "cs_net_profit": core["net_profit"] - ship_cost,
        }

    def _catalog_returns(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        core = self._returns_core("catalog_returns", "catalog_sales",
                                  self.n_catalog_sales, idx)
        sale = core["sale"]
        cs = lambda c: _seed("catalog_sales", c)
        return {
            "cr_returned_date_sk": core["returned_date_sk"],
            "cr_item_sk": core["item_sk"],
            "cr_returning_customer_sk": _uniform_int(cs("bcust"), sale, 1, self.n_customers),
            "cr_call_center_sk": _uniform_int(cs("cc"), sale, 1, self.n_call_centers),
            "cr_reason_sk": core["reason_sk"],
            "cr_order_number": sale + 1,
            "cr_return_quantity": core["return_quantity"],
            "cr_return_amount": core["return_amt"],
            "cr_net_loss": core["net_loss"],
        }

    def _web_sales(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        s = lambda c: _seed("web_sales", c)
        core = self._sales_core("web_sales", idx, self.n_items)
        ship_cost = core["ext_sales_price"] // 20
        return {
            "ws_sold_date_sk": core["date_sk"],
            "ws_sold_time_sk": core["time_sk"],
            "ws_ship_date_sk": core["date_sk"] + _uniform_int(s("shiplag"), idx, 1, 30),
            "ws_item_sk": core["item_sk"],
            "ws_bill_customer_sk": _uniform_int(s("bcust"), idx, 1, self.n_customers),
            "ws_bill_addr_sk": _uniform_int(s("baddr"), idx, 1, self.n_addresses),
            "ws_ship_customer_sk": _uniform_int(s("scust"), idx, 1, self.n_customers),
            "ws_ship_addr_sk": _uniform_int(s("saddr"), idx, 1, self.n_addresses),
            "ws_web_page_sk": _uniform_int(s("wp"), idx, 1, self.n_web_pages),
            "ws_web_site_sk": _uniform_int(s("wsite"), idx, 1, self.n_web_sites),
            "ws_ship_mode_sk": _uniform_int(s("sm"), idx, 1, self.n_ship_modes),
            "ws_warehouse_sk": _uniform_int(s("wh"), idx, 1, self.n_warehouses),
            "ws_promo_sk": core["promo_sk"],
            "ws_order_number": idx.astype(np.int64) + 1,
            "ws_quantity": core["quantity"],
            "ws_wholesale_cost": core["wholesale_cost"],
            "ws_list_price": core["list_price"],
            "ws_sales_price": core["sales_price"],
            "ws_ext_discount_amt": core["ext_discount_amt"],
            "ws_ext_sales_price": core["ext_sales_price"],
            "ws_ext_wholesale_cost": core["ext_wholesale_cost"],
            "ws_ext_list_price": core["ext_list_price"],
            "ws_ext_ship_cost": ship_cost,
            "ws_net_paid": core["net_paid"],
            "ws_net_profit": core["net_profit"] - ship_cost,
        }

    def _web_returns(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        core = self._returns_core("web_returns", "web_sales", self.n_web_sales, idx)
        sale = core["sale"]
        ws = lambda c: _seed("web_sales", c)
        return {
            "wr_returned_date_sk": core["returned_date_sk"],
            "wr_item_sk": core["item_sk"],
            "wr_returning_customer_sk": _uniform_int(ws("bcust"), sale, 1, self.n_customers),
            "wr_reason_sk": core["reason_sk"],
            "wr_order_number": sale + 1,
            "wr_return_quantity": core["return_quantity"],
            "wr_return_amt": core["return_amt"],
            "wr_net_loss": core["net_loss"],
        }

    # -- Page production ----------------------------------------------------
    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        cols = self.generate_split(table, split)
        schema = SCHEMAS[table]
        arrays = [cols[name] for name, _ in schema]
        types = [t for _, t in schema]
        dicts = [self.dictionary_for(table, name) for name, _ in schema]
        return Page.from_arrays(arrays, types, dictionaries=dicts, capacity=capacity)

    def pages(self, table: str, capacity: Optional[int] = None) -> Iterator[Page]:
        for i in range(self.num_splits(table)):
            yield self.page_for_split(table, i, capacity=capacity)
