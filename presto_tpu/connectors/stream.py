"""Stream connectors: append-only message logs and key/value snapshots
as tables, decoded through the shared record-decoder layer.

Reference analogs:

- ``presto-kafka`` (topic = table; splits are per-partition offset
  ranges; messages decoded by ``presto-record-decoder``; internal
  ``_partition_id`` / ``_partition_offset`` / ``_message`` columns).
  Here the broker is a directory of segment files per topic — one
  split per segment, so leaf parallelism scales with retention exactly
  like kafka's offset-range splits — and the table description maps
  topic -> schema + format the way kafka's JSON table description
  files do (``kafka/KafkaTopicDescription.java``).
- ``presto-redis`` (key/value store scanned as a table: key column +
  decoded value fields, ``redis/RedisRowDecoder``): ``KvConnector``
  over a sqlite key/value snapshot.

Because the engine enumerates splits at EXECUTION time, a re-run of
the same (cached) query observes newly appended segments — the
streaming re-scan semantics kafka users expect.
"""

from __future__ import annotations

import json
import os
import sqlite3
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.sync import named_lock

import numpy as np

from presto_tpu.connectors.jdbc import _encode_column
from presto_tpu.page import Dictionary, Page
from presto_tpu.record_decoder import decoder_for
from presto_tpu.types import BIGINT, VARCHAR, Type, parse_type

_SEGMENT_MAGIC = b"PSEG"


class LogBroker:
    """Append-only segmented message log (the kafka-broker stand-in:
    producers append; segments roll at ``segment_bytes``)."""

    def __init__(self, root: str, segment_bytes: int = 1 << 20):
        self.root = root
        self.segment_bytes = segment_bytes
        self._lock = named_lock("stream.LogBroker._lock")
        os.makedirs(root, exist_ok=True)

    def _topic_dir(self, topic: str) -> str:
        return os.path.join(self.root, topic)

    def segments(self, topic: str) -> List[str]:
        d = self._topic_dir(topic)
        if not os.path.isdir(d):
            return []
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".seg"))

    def append(self, topic: str, messages: Sequence[str]) -> None:
        with self._lock:
            d = self._topic_dir(topic)
            os.makedirs(d, exist_ok=True)
            segs = self.segments(topic)
            if segs and os.path.getsize(segs[-1]) < self.segment_bytes:
                path = segs[-1]
            else:
                path = os.path.join(d, f"{len(segs):08d}.seg")
                with open(path, "wb") as f:
                    f.write(_SEGMENT_MAGIC)
            with open(path, "ab") as f:
                for m in messages:
                    raw = m.encode()
                    f.write(struct.pack("<I", len(raw)))
                    f.write(raw)

    def read_segment(self, path: str) -> List[str]:
        out: List[str] = []
        with open(path, "rb") as f:
            assert f.read(4) == _SEGMENT_MAGIC, f"bad segment {path}"
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break
                (ln,) = struct.unpack("<I", hdr)
                out.append(f.read(ln).decode())
        return out


class StreamConnector:
    """Topics of a LogBroker as tables (presto-kafka slot).

    ``descriptions`` mirrors kafka's table description files::

        {"events": {"format": "json",
                    "schema": [["ts", "bigint"], ["msg", "varchar"]]}}
    """

    INTERNAL = (("_segment", BIGINT), ("_offset", BIGINT))

    def __init__(self, broker: LogBroker, descriptions: Dict[str, dict]):
        self.broker = broker
        self._desc = {
            t: {"format": d["format"],
                "schema": [(c, parse_type(s) if isinstance(s, str) else s)
                           for c, s in d["schema"]]}
            for t, d in descriptions.items()
        }
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}

    def table_names(self) -> List[str]:
        return list(self._desc)

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return list(self._desc[table]["schema"]) + list(self.INTERNAL)

    def num_splits(self, table: str) -> int:
        return max(1, len(self.broker.segments(table)))

    def row_count(self, table: str) -> int:
        return sum(
            int(np.asarray(self.page_for_split(table, s).row_mask).sum())
            for s in range(self.num_splits(table)))

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        return self._dicts.get(table, {}).get(column)

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        desc = self._desc[table]
        segs = self.broker.segments(table)
        lines = self.broker.read_segment(segs[split]) if segs else []
        decoder = decoder_for(desc["format"], desc["schema"])
        cols = decoder.decode(lines) if lines else [[] for _ in desc["schema"]]
        n = len(cols[0]) if cols else 0
        cols = cols + [[split] * n, list(range(n))]  # internal columns
        dicts = self._dicts.setdefault(table, {})
        data_list, valids, dict_list = [], [], []
        for (name, t), raw in zip(self.schema(table), cols):
            data, valid, d = _encode_column(raw, t, dicts.get(name))
            if d is not None:
                dicts[name] = d
            data_list.append(data)
            valids.append(valid)
            dict_list.append(d)
        return Page.from_arrays(data_list, [t for _, t in self.schema(table)],
                                valids=valids, dictionaries=dict_list)


class KvConnector:
    """Key/value snapshot tables (presto-redis slot): a sqlite-backed
    store scanned as (key, decoded value fields)."""

    def __init__(self, path: str, descriptions: Dict[str, dict]):
        self.path = path
        self._desc = {
            t: {"format": d["format"],
                "schema": [(c, parse_type(s) if isinstance(s, str) else s)
                           for c, s in d["schema"]]}
            for t, d in descriptions.items()
        }
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}
        db = sqlite3.connect(path)
        db.execute("CREATE TABLE IF NOT EXISTS kv "
                   "(tbl TEXT, k TEXT, v TEXT, PRIMARY KEY (tbl, k))")
        db.commit()
        db.close()

    def put(self, table: str, key: str, value) -> None:
        if not isinstance(value, str):
            value = json.dumps(value)
        db = sqlite3.connect(self.path)
        db.execute("INSERT OR REPLACE INTO kv VALUES (?, ?, ?)",
                   (table, key, value))
        db.commit()
        db.close()

    def table_names(self) -> List[str]:
        return list(self._desc)

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return [("_key", VARCHAR)] + list(self._desc[table]["schema"])

    def num_splits(self, table: str) -> int:
        return 1

    def row_count(self, table: str) -> int:
        db = sqlite3.connect(self.path)
        (n,) = db.execute("SELECT count(*) FROM kv WHERE tbl = ?",
                          (table,)).fetchone()
        db.close()
        return int(n)

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        return self._dicts.get(table, {}).get(column)

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        desc = self._desc[table]
        db = sqlite3.connect(self.path)
        rows = db.execute(
            "SELECT k, v FROM kv WHERE tbl = ? ORDER BY k", (table,)).fetchall()
        db.close()
        decoder = decoder_for(desc["format"], desc["schema"])
        cols = (decoder.decode([v for _, v in rows]) if rows
                else [[] for _ in desc["schema"]])
        cols = [[k for k, _ in rows]] + cols
        dicts = self._dicts.setdefault(table, {})
        data_list, valids, dict_list = [], [], []
        for (name, t), raw in zip(self.schema(table), cols):
            data, valid, d = _encode_column(raw, t, dicts.get(name))
            if d is not None:
                dicts[name] = d
            data_list.append(data)
            valids.append(valid)
            dict_list.append(d)
        return Page.from_arrays(data_list, [t for _, t in self.schema(table)],
                                valids=valids, dictionaries=dict_list)
