"""DB-API connector: query external SQL databases as engine tables.

Reference analog: ``presto-base-jdbc`` (BaseJdbcClient.java — the
generic JDBC connector the mysql/postgresql/redshift/sqlserver thin
drivers build on).  Python's DB-API 2.0 plays the role of JDBC; the
built-in target is sqlite3 (stdlib), and any DB-API connection factory
can be supplied the way thin drivers supply JDBC URLs.

Pushdown: simple range/equality constraints compile to a WHERE clause
on the remote (the reference pushes TupleDomain the same way,
QueryBuilder.java); everything else runs in the engine after a full
column scan.  Rows fetch once per (table, split) and cache as
device-ready pages; strings dictionary-encode on first load.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, TIMESTAMP, VARCHAR, Type,
)


def _map_decl_type(decl: str) -> Type:
    d = (decl or "").lower()
    if "int" in d:
        return BIGINT
    if any(k in d for k in ("real", "floa", "doub", "numeric", "decimal")):
        return DOUBLE
    if "bool" in d:
        return BOOLEAN
    if "timestamp" in d or "datetime" in d:
        return TIMESTAMP
    if d == "date":
        return DATE
    return VARCHAR


class JdbcConnector:
    """Engine connector over a DB-API connection.

    ``connect`` is a zero-arg factory returning a DB-API connection
    (e.g. ``lambda: sqlite3.connect(path)``); connections are opened
    per scan and closed after, like the reference's connection-per-
    split JdbcRecordCursor.
    """

    def __init__(self, connect: Callable[[], object],
                 tables: Optional[Sequence[str]] = None,
                 split_rows: int = 1 << 18):
        self._connect = connect
        self._only = set(tables) if tables is not None else None
        self.split_rows = split_rows
        self._schemas: Dict[str, List[Tuple[str, Type]]] = {}
        self._pages: Dict[str, List[Page]] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}
        self._counts: Dict[str, int] = {}

    @classmethod
    def sqlite(cls, path: str, **kw) -> "JdbcConnector":
        import sqlite3

        return cls(lambda: sqlite3.connect(path), **kw)

    # -- metadata -----------------------------------------------------------
    def table_names(self) -> List[str]:
        conn = self._connect()
        try:
            cur = conn.cursor()
            cur.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "ORDER BY name"
            )
            names = [r[0] for r in cur.fetchall()]
        finally:
            conn.close()
        if self._only is not None:
            names = [n for n in names if n in self._only]
        return names

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        if table not in self._schemas:
            conn = self._connect()
            try:
                cur = conn.cursor()
                cur.execute(f"PRAGMA table_info({_q(table)})")
                cols = [(r[1], _map_decl_type(r[2])) for r in cur.fetchall()]
            finally:
                conn.close()
            if not cols:
                raise KeyError(f"no such remote table: {table}")
            self._schemas[table] = cols
        return self._schemas[table]

    def row_count(self, table: str) -> int:
        if table not in self._counts:
            conn = self._connect()
            try:
                cur = conn.cursor()
                cur.execute(f"SELECT count(*) FROM {_q(table)}")
                self._counts[table] = int(cur.fetchone()[0])
            finally:
                conn.close()
        return self._counts[table]

    def num_splits(self, table: str) -> int:
        return max(1, math.ceil(self.row_count(table) / self.split_rows))

    def primary_key(self, table: str) -> Optional[List[str]]:
        conn = self._connect()
        try:
            cur = conn.cursor()
            cur.execute(f"PRAGMA table_info({_q(table)})")
            pk = [(r[5], r[1]) for r in cur.fetchall() if r[5]]
        finally:
            conn.close()
        return [name for _, name in sorted(pk)] or None

    def dictionary_for(self, table: str, column: str):
        self._load(table)
        return self._dicts.get(table, {}).get(column)

    # -- scan ---------------------------------------------------------------
    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None) -> Page:
        self._load(table)
        return self._pages[table][split]

    def scan_remote(self, table: str, columns: Sequence[str],
                    where_sql: str = "", params: Sequence = ()) -> List[tuple]:
        """Predicate-pushdown escape hatch (QueryBuilder.java analog):
        run a projected+filtered SELECT remotely and return raw rows."""
        cols = ", ".join(_q(c) for c in columns)
        sql = f"SELECT {cols} FROM {_q(table)}"
        if where_sql:
            sql += f" WHERE {where_sql}"
        conn = self._connect()
        try:
            cur = conn.cursor()
            cur.execute(sql, tuple(params))
            return cur.fetchall()
        finally:
            conn.close()

    # -- index lookups (ConnectorIndex / presto index-join SPI) -------------
    def supports_index(self, table: str, key_columns: Sequence[str]) -> bool:
        """True when point lookups on ``key_columns`` can run remotely
        (spi/connector/ConnectorIndexProvider analog).  Any column works
        for a SQL backend — the remote engine does the indexing."""
        cols = {c for c, _ in self.schema(table)}
        return all(c in cols for c in key_columns)

    INDEX_CHUNK = 900  # sqlite parameter limit guard

    def index_lookup(self, table: str, key_columns: Sequence[str],
                     keys: Sequence[tuple]) -> List[Page]:
        """Fetch only the rows matching the probe keys (IndexLoader /
        IndexSourceOperator analog): WHERE (k1, k2) IN (...) chunked."""
        schema = self.schema(table)
        out_rows: List[tuple] = []
        keys = list(dict.fromkeys(keys))  # distinct, order-stable
        cols = [c for c, _ in schema]
        for start in range(0, len(keys), self.INDEX_CHUNK):
            chunk = keys[start : start + self.INDEX_CHUNK]
            if len(key_columns) == 1:
                ph = ", ".join("?" for _ in chunk)
                where = f"{_q(key_columns[0])} IN ({ph})"
                params = [k[0] for k in chunk]
            else:
                tuple_ph = "(" + ", ".join("?" for _ in key_columns) + ")"
                where = ("(" + ", ".join(_q(c) for c in key_columns) + ") IN ("
                         + ", ".join(tuple_ph for _ in chunk) + ")")
                params = [v for k in chunk for v in k]
            out_rows.extend(self.scan_remote(table, cols, where, params))
        dicts: Dict[str, Dictionary] = dict(self._dicts.get(table, {}))
        cols_np, valids, page_dicts = [], [], []
        for i, (name, t) in enumerate(schema):
            raw = [r[i] for r in out_rows]
            data, valid, d = _encode_column(raw, t, dicts.get(name))
            if d is not None:
                dicts[name] = d
            cols_np.append(data)
            valids.append(valid)
            page_dicts.append(d)
        self._dicts.setdefault(table, {}).update(dicts)
        return [Page.from_arrays(cols_np, [t for _, t in schema],
                                 valids=valids, dictionaries=page_dicts)]

    # -- loading ------------------------------------------------------------
    def _load(self, table: str) -> None:
        if table in self._pages:
            return
        schema = self.schema(table)
        rows = self.scan_remote(table, [c for c, _ in schema])
        dicts: Dict[str, Dictionary] = {}
        pages: List[Page] = []
        for start in range(0, max(len(rows), 1), self.split_rows):
            chunk = rows[start : start + self.split_rows]
            cols, valids, page_dicts = [], [], []
            for i, (name, t) in enumerate(schema):
                raw = [r[i] for r in chunk]
                data, valid, d = _encode_column(raw, t, dicts.get(name))
                if d is not None:
                    dicts[name] = d
                cols.append(data)
                valids.append(valid)
                page_dicts.append(d)
            pages.append(Page.from_arrays(cols, [t for _, t in schema],
                                          valids=valids, dictionaries=page_dicts))
        self._pages[table] = pages
        self._dicts[table] = dicts


def _q(ident: str) -> str:
    if not ident.replace("_", "").isalnum():
        raise ValueError(f"bad identifier: {ident!r}")
    return f'"{ident}"'


def _parse_date(v) -> int:
    import datetime

    if isinstance(v, (int, np.integer)):
        return int(v)
    d = datetime.date.fromisoformat(str(v)[:10])
    return (d - datetime.date(1970, 1, 1)).days


def _parse_ts(v) -> int:
    import datetime

    if isinstance(v, (int, np.integer)):
        return int(v)
    s = str(v).replace("T", " ")
    dt = datetime.datetime.fromisoformat(s)
    return int((dt - datetime.datetime(1970, 1, 1)).total_seconds() * 1_000_000)


def _encode_column(raw: List, t: Type, existing: Optional[Dictionary]):
    n = len(raw)
    valid = np.asarray([v is not None for v in raw], dtype=np.bool_)
    if t.is_string:
        values = list(existing.values) if existing is not None else []
        index = {v: i for i, v in enumerate(values)}
        codes = np.zeros(n, dtype=np.int32)
        for i, v in enumerate(raw):
            if v is None:
                continue
            s = str(v)
            code = index.get(s)
            if code is None:
                code = len(values)
                index[s] = code
                values.append(s)
            codes[i] = code
        return codes, valid, Dictionary(values)
    if t.name == "date":
        data = np.asarray([0 if v is None else _parse_date(v) for v in raw],
                          dtype=np.int32)
        return data, valid, None
    if t.name == "timestamp":
        data = np.asarray([0 if v is None else _parse_ts(v) for v in raw],
                          dtype=np.int64)
        return data, valid, None
    if t.name == "boolean":
        data = np.asarray([bool(v) if v is not None else False for v in raw],
                          dtype=np.bool_)
        return data, valid, None
    dtype = t.np_dtype
    data = np.asarray([0 if v is None else v for v in raw]).astype(dtype)
    return data, valid, None
