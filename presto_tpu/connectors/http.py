"""HTTP connector: tables served by remote HTTP endpoints.

Reference analog: ``presto-example-http`` (the connector-SPI tutorial
connector: a JSON catalog maps tables to lists of data URIs, each URI
serving CSV; one URI = one split).  Same shape here, riding the shared
record-decoder layer.

Catalog description::

    {
      "tables": {
        "events": {
          "format": "csv",
          "schema": [["ts", "varchar"], ["n", "bigint"]],
          "sources": ["http://host/part1.csv", "http://host/part2.csv"]
        }
      }
    }
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional, Tuple

from presto_tpu.connectors.jdbc import _encode_column
from presto_tpu.page import Dictionary, Page
from presto_tpu.record_decoder import decoder_for
from presto_tpu.types import Type, parse_type


class HttpConnector:
    def __init__(self, catalog_uri: Optional[str] = None,
                 description: Optional[dict] = None, timeout: float = 30.0):
        if description is None:
            if catalog_uri is None:
                raise ValueError("need catalog_uri or description")
            with urllib.request.urlopen(catalog_uri, timeout=timeout) as r:
                description = json.load(r)
        self.tables = description["tables"]
        self.timeout = timeout
        self._cache: Dict[Tuple[str, int], Page] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}

    # -- connector protocol -------------------------------------------------
    def table_names(self) -> List[str]:
        return list(self.tables)

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return [(c, parse_type(t)) for c, t in self.tables[table]["schema"]]

    def num_splits(self, table: str) -> int:
        return len(self.tables[table]["sources"])

    def row_count(self, table: str) -> int:
        import numpy as np

        return sum(
            int(np.asarray(self.page_for_split(table, s).row_mask).sum())
            for s in range(self.num_splits(table))
        )

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None) -> Page:
        key = (table, split)
        if key not in self._cache:
            meta = self.tables[table]
            uri = meta["sources"][split]
            with urllib.request.urlopen(uri, timeout=self.timeout) as r:
                text = r.read().decode()
            schema = self.schema(table)
            dec = decoder_for(meta.get("format", "csv"), schema,
                              **meta.get("decoder", {}))
            cols_raw = dec.decode(text.splitlines())
            dicts = self._dicts.setdefault(table, {})
            cols, valids, page_dicts = [], [], []
            for (name, t), raw in zip(schema, cols_raw):
                data, valid, d = _encode_column(raw, t, dicts.get(name))
                if d is not None:
                    dicts[name] = d
                cols.append(data)
                valids.append(valid)
                page_dicts.append(d)
            self._cache[key] = Page.from_arrays(
                cols, [t for _, t in schema], valids=valids,
                dictionaries=page_dicts)
        return self._cache[key]

    def dictionary_for(self, table: str, column: str):
        # ensure dictionaries cover every split before predicates bind
        for s in range(self.num_splits(table)):
            self.page_for_split(table, s)
        return self._dicts.get(table, {}).get(column)
