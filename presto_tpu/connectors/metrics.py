"""Runtime-metrics connector: process/VM internals as queryable tables.

Reference analog: ``presto-jmx`` (JMX MBeans of each node queryable as
SQL tables — jmx.current."java.lang:type=memory" etc.).  The python
runtime's equivalents: process memory/cpu from /proc, gc generation
stats, thread counts, and the JAX device inventory.

Tables:
  runtime   one row per (name, value) process metric
  gc        one row per gc generation
  devices   one row per jax device
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR, Type


def _proc_status() -> Dict[str, int]:
    out = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(("VmRSS", "VmHWM", "VmSize", "Threads")):
                    k, v = line.split(":", 1)
                    out[k] = int(v.strip().split()[0])
    except OSError:
        pass
    return out


class MetricsConnector:
    """Live metrics snapshot per scan (presto-jmx analog)."""

    def table_names(self) -> List[str]:
        return ["runtime", "gc", "devices"]

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        if table == "runtime":
            return [("name", VARCHAR), ("value", DOUBLE)]
        if table == "gc":
            return [("generation", BIGINT), ("collections", BIGINT),
                    ("collected", BIGINT), ("uncollectable", BIGINT)]
        if table == "devices":
            return [("id", BIGINT), ("platform", VARCHAR), ("kind", VARCHAR)]
        raise KeyError(table)

    def num_splits(self, table: str) -> int:
        return 1

    def row_count(self, table: str) -> int:
        return int(np.asarray(self.page_for_split(table, 0).row_mask).sum())

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None) -> Page:
        if table == "runtime":
            status = _proc_status()
            cpu = os.times()
            rows = [
                ("process.rss_kb", float(status.get("VmRSS", 0))),
                ("process.peak_rss_kb", float(status.get("VmHWM", 0))),
                ("process.vsize_kb", float(status.get("VmSize", 0))),
                ("process.threads", float(threading.active_count())),
                ("process.cpu_user_s", float(cpu.user)),
                ("process.cpu_system_s", float(cpu.system)),
                ("process.uptime_s", float(time.monotonic())),
            ]
            names = [r[0] for r in rows]
            d = Dictionary(names)
            return Page.from_arrays(
                [np.arange(len(rows), dtype=np.int32),
                 np.asarray([r[1] for r in rows])],
                [VARCHAR, DOUBLE], dictionaries=[d, None],
            )
        if table == "gc":
            stats = gc.get_stats()
            return Page.from_arrays(
                [np.arange(len(stats), dtype=np.int64),
                 np.asarray([s.get("collections", 0) for s in stats], np.int64),
                 np.asarray([s.get("collected", 0) for s in stats], np.int64),
                 np.asarray([s.get("uncollectable", 0) for s in stats], np.int64)],
                [BIGINT] * 4,
            )
        if table == "devices":
            import jax

            devs = jax.devices()
            plats = Dictionary(sorted({d.platform for d in devs}))
            kinds = Dictionary(sorted({d.device_kind for d in devs}))
            return Page.from_arrays(
                [np.asarray([d.id for d in devs], np.int64),
                 np.asarray([plats.code_of(d.platform) for d in devs], np.int32),
                 np.asarray([kinds.code_of(d.device_kind) for d in devs], np.int32)],
                [BIGINT, VARCHAR, VARCHAR], dictionaries=[None, plats, kinds],
            )
        raise KeyError(table)

    def dictionary_for(self, table: str, column: str):
        # dictionaries are per-snapshot; predicates re-resolve per scan
        page = self.page_for_split(table, 0)
        for (name, t), b in zip(self.schema(table), page.blocks):
            if name == column:
                return b.dictionary
        return None
