"""Session + system properties.

Reference analog: ``Session.java`` + ``SystemSessionProperties.java:50``
(57 typed session properties, settable per query over the wire or via
SET SESSION) and the ``@Config``-bound config beans
(execution/TaskManagerConfig.java).  One typed registry serves both
roles; connectors may register their own namespaced properties.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    default: Any
    parse: Callable[[str], Any]


def _bool(s: str) -> bool:
    return s.strip().lower() in ("true", "1", "yes", "on")


def _duration(s: str) -> str:
    """Validate a duration-typed property at SET time ('30s', '10m',
    plain seconds; empty = server default) — a malformed value must
    fail the SET SESSION statement, not the next query's execution."""
    s = s.strip()
    if s:
        from presto_tpu.config import parse_duration

        if parse_duration(s, default=None) is None:
            raise ValueError(
                f"invalid duration {s!r} (use e.g. '30s', '10m', '2h')")
    return s


SYSTEM_PROPERTIES = [
    PropertyMetadata(
        "jit", "compile streaming chains with XLA (debugging escape hatch)",
        True, _bool,
    ),
    PropertyMetadata(
        "distributed", "execute over the device mesh when the plan shape allows",
        False, _bool,
    ),
    PropertyMetadata(
        "hash_partition_count",
        "partitions for distributed exchanges (devices used of the mesh)",
        0, int,  # 0 = all mesh devices
    ),
    PropertyMetadata(
        "max_groups",
        "default static group-by capacity before overflow retry",
        1 << 16, int,
    ),
    PropertyMetadata(
        "split_capacity",
        "pad scan splits to this static row capacity (0 = natural size)",
        0, int,
    ),
    PropertyMetadata(
        "collect_stats",
        "record per-stage rows/wall-time (EXPLAIN ANALYZE forces this)",
        False, _bool,
    ),
    PropertyMetadata(
        "query_priority",
        "admission priority within query_priority resource groups",
        0, int,
    ),
    PropertyMetadata(
        "distributed_sort",
        "multi-producer ORDER BY: per-page sorts + order-preserving merge",
        True, _bool,
    ),
    PropertyMetadata(
        "colocated_join",
        "use bucket-aligned exchange-free joins when tables allow",
        True, _bool,
    ),
    PropertyMetadata(
        "join_distribution_type",
        "AUTOMATIC | BROADCAST | PARTITIONED (DetermineJoinDistributionType)",
        "AUTOMATIC", lambda s: s.strip().upper(),
    ),
    PropertyMetadata(
        "trace",
        "record lifecycle/operator/compile spans for every query "
        "(exportable as Chrome-trace JSON; query.trace-dir config "
        "writes one file per query)",
        False, _bool,
    ),
    PropertyMetadata(
        "validate_plans",
        "run the static plan/IR validator on every bound plan "
        "(EXPLAIN (TYPE VALIDATE) always does; query.validate-plans "
        "config key sets the default)",
        False, _bool,
    ),
    PropertyMetadata(
        "validate_rewrites",
        "gate every optimizer rule application with the rewrite-"
        "soundness checker (analysis/soundness.py; EXPLAIN (TYPE "
        "VALIDATE) always does; query.validate-rewrites config key "
        "sets the default)",
        False, _bool,
    ),
    PropertyMetadata(
        "validate_kernels",
        "run the expression-tier abstract interpreter on every bound "
        "plan: overflow, lossy-cast, division, accumulator, and "
        "null-policy soundness (analysis/kernel_soundness.py; EXPLAIN "
        "(TYPE VALIDATE) always does; query.validate-kernels config "
        "key sets the default)",
        False, _bool,
    ),
    PropertyMetadata(
        "distributed_min_stage_rows",
        "stages over intermediates smaller than this run on the "
        "coordinator (0 = every stage on the mesh)",
        1 << 13, int,
    ),
    PropertyMetadata(
        "exchange_streaming",
        "stream stage-boundary pages through the token-acked exchange "
        "(parallel/streams.py) so consuming stages overlap producers; "
        "false = materialize each stage before the next starts (A/B leg)",
        True, _bool,
    ),
    PropertyMetadata(
        "exchange_buffer_bytes",
        "unacknowledged-byte cap per exchange stream (producer "
        "backpressure bound); 0 = process default "
        "(PRESTO_TPU_EXCHANGE_BUFFER_BYTES)",
        0, int,
    ),
    PropertyMetadata(
        "exchange_merge_fanin",
        "pre-sorted runs the distributed-ORDER-BY consumer folds per "
        "k-way merge batch (bounds merge memory while runs stream in)",
        8, int,
    ),
    PropertyMetadata(
        "task_concurrency",
        "splits in flight per scan pipeline (morsel scheduler, "
        "exec/tasks.py); 1 = serial legacy path, 0 = process default "
        "(query.task-concurrency config / PRESTO_TPU_TASK_CONCURRENCY)",
        0, int,
    ),
    PropertyMetadata(
        "query_max_execution_time",
        "kill the query after this long running (duration: '30s', "
        "'10m'; empty = the coordinator's query.max-execution-time "
        "config default, '0' = no deadline)",
        "", _duration,
    ),
    PropertyMetadata(
        "task_prefetch",
        "host pages prepared ahead of the split worker pool "
        "(double-buffering depth); -1 = process default "
        "(PRESTO_TPU_TASK_PREFETCH)",
        -1, int,
    ),
    PropertyMetadata(
        "result_cache_enabled",
        "serve repeated read-only queries from the structural result "
        "cache (keyed by plan signature, invalidated by table "
        "versions; docs/serving.md — query.result-cache-enabled "
        "config sets the default, query.result-cache-bytes the budget)",
        False, _bool,
    ),
    PropertyMetadata(
        "subplan_cache_enabled",
        "reuse warm stage intermediates at exchange boundaries when a "
        "distributed stage's signature and table versions match a "
        "prior execution (docs/serving.md)",
        False, _bool,
    ),
    PropertyMetadata(
        "feedback_stats",
        "let the planner consult the plan-history store: observed row "
        "counts from prior executions override textbook selectivities "
        "on structural-signature match (obs/history.py; "
        "docs/observability.md 'Estimate vs actual')",
        False, _bool,
    ),
    PropertyMetadata(
        "misestimate_factor",
        "flag EXPLAIN ANALYZE operators whose actual/estimate row "
        "ratio exceeds this factor (either direction); also the doctor "
        "misestimate rule's evidence threshold source",
        8.0, float,
    ),
]


class Session:
    """Per-query context: properties + (later) principal/tx/trace."""

    def __init__(self, properties: Optional[Dict[str, Any]] = None, user: str = "presto",
                 trace_token: Optional[str] = None):
        self._meta = {p.name: p for p in SYSTEM_PROPERTIES}
        self.properties: Dict[str, Any] = {
            p.name: p.default for p in SYSTEM_PROPERTIES
        }
        if properties:
            for k, v in properties.items():
                self.set(k, v)
        self.user = user
        # request-correlation token (X-Presto-Trace-Token analog); one
        # is generated per query when the client supplies none
        self.trace_token = trace_token
        # USE state (Session.java catalog/schema; execution/UseTask.java
        # mutates these): unqualified names resolve against them first
        self.catalog: Optional[str] = None
        self.schema: str = "default"
        # SET PATH (sql/tree/SetPath.java): SQL function-resolution
        # path; recorded for protocol parity (one flat function
        # namespace here, so it does not affect resolution)
        self.path: str = ""

    def get(self, name: str) -> Any:
        return self.properties[name]

    def set(self, name: str, value) -> None:
        meta = self._meta.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        if isinstance(value, str):
            value = meta.parse(value)
        self.properties[name] = value

    def reset(self, name: str) -> None:
        """RESET SESSION: back to the property's default."""
        meta = self._meta.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        self.properties[name] = meta.default

    def describe(self):
        return [
            (p.name, self.properties[p.name], p.default, p.description)
            for p in SYSTEM_PROPERTIES
        ]
