"""Row decoders: parse external byte/text records into typed columns.

Reference analog: ``presto-record-decoder`` (decoder/RowDecoder.java
with csv/json/raw field decoders) — the shared parsing layer the
reference's kafka/redis connectors use; here the local-file connector
(and any stream source) uses it the same way.

A decoder turns an iterable of records (text lines) into column lists
per a declared schema; ``presto_tpu.connectors.jdbc._encode_column``
then produces the device representation, so every decoder output lands
in the engine's normal Page form.
"""

from __future__ import annotations

import csv as _csv
import io
import json as _json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from presto_tpu.types import Type


class DecodeError(Exception):
    pass


def _coerce(v, t: Type):
    """Text/JSON scalar -> python value for _encode_column."""
    if v is None or v == "":
        return None
    if t.name in ("bigint", "integer"):
        return int(v)
    if t.name == "double" or t.is_decimal:
        return float(v)
    if t.name == "boolean":
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("true", "1", "t", "yes")
    return v  # varchar/date/timestamp strings pass through


class CsvRowDecoder:
    """csv lines -> columns (decoder/csv/CsvRowDecoderFactory.java)."""

    def __init__(self, schema: Sequence[Tuple[str, Type]],
                 delimiter: str = ",", header: bool = False):
        self.schema = list(schema)
        self.delimiter = delimiter
        self.header = header

    def decode(self, lines: Iterable[str]) -> List[List]:
        reader = _csv.reader(lines, delimiter=self.delimiter)
        cols: List[List] = [[] for _ in self.schema]
        for i, row in enumerate(reader):
            if i == 0 and self.header:
                continue
            if len(row) < len(self.schema):
                raise DecodeError(
                    f"row {i}: {len(row)} fields, schema has {len(self.schema)}")
            for j, (_, t) in enumerate(self.schema):
                cols[j].append(_coerce(row[j], t))
        return cols


class JsonRowDecoder:
    """One JSON object per line (decoder/json/JsonRowDecoder.java);
    fields resolve by column name, missing keys are NULL."""

    def __init__(self, schema: Sequence[Tuple[str, Type]]):
        self.schema = list(schema)

    def decode(self, lines: Iterable[str]) -> List[List]:
        cols: List[List] = [[] for _ in self.schema]
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                obj = _json.loads(line)
            except Exception as e:
                raise DecodeError(f"row {i}: bad json: {e}")
            for j, (name, t) in enumerate(self.schema):
                cols[j].append(_coerce(obj.get(name), t))
        return cols


def decoder_for(fmt: str, schema, **kw):
    if fmt == "csv":
        return CsvRowDecoder(schema, **kw)
    if fmt == "json":
        return JsonRowDecoder(schema, **kw)
    raise ValueError(f"unknown record format {fmt!r}")
