"""Expression IR and its JAX compiler.

Reference analog: the expression JIT tier — RowExpression trees compiled
to JVM bytecode PageProjection/PageFilter classes
(presto-main/.../sql/gen/ExpressionCompiler.java:53,
PageFunctionCompiler.java:101). Here the "bytecode" target is XLA: an
Expr tree compiles to a Python closure over jnp ops, which jits (and
fuses) into the enclosing stage program.
"""

from presto_tpu.expr.ir import (  # noqa: F401
    AggCall,
    Call,
    ColumnRef,
    Expr,
    Literal,
    and_,
    call,
    col,
    eq,
    lit,
)
from presto_tpu.expr.compile import compile_expr, compile_filter  # noqa: F401
