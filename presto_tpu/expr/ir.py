"""Row expression IR.

Reference analog: RowExpression (presto-main/.../sql/relational/
RowExpression.java and CallExpression/InputReferenceExpression/
ConstantExpression) — the typed post-analysis expression form the
reference compiles to bytecode. Same role here, compiled to jnp ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    TIMESTAMP,
    DecimalType,
    Type,
    common_super_type,
)


@dataclasses.dataclass(frozen=True)
class Expr:
    type: Type


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    """Input channel reference (InputReferenceExpression analog)."""

    index: int = 0
    name: str = ""  # debugging only

    def __repr__(self):
        return f"${self.index}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """Constant (ConstantExpression analog). Decimals store the scaled
    int; dates store epoch days; strings store the raw python str
    (resolved to a dictionary code at compile time)."""

    value: Any = None

    def __repr__(self):
        return f"lit({self.value!r}:{self.type})"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Function call (CallExpression analog)."""

    fn: str = ""
    args: Tuple[Expr, ...] = ()

    def __repr__(self):
        return f"{self.fn}({', '.join(map(repr, self.args))})"


@dataclasses.dataclass(frozen=True)
class LambdaVar(Expr):
    """A bound variable of a lambda body
    (VariableReferenceExpression inside LambdaDefinitionExpression) —
    only meaningful inside lambda-taking function arguments, where the
    compiler binds it to flattened element lanes.  ``slot`` identifies
    the parameter position for multi-parameter lambdas ((k, v) ->,
    (state, x) ->)."""

    slot: int = 0

    def __repr__(self):
        return f"λ{self.slot}:{self.type}"


@dataclasses.dataclass(frozen=True)
class LambdaExpr(Expr):
    """A lambda argument of a lambda-taking function call
    (LambdaDefinitionExpression): ``params`` are this lambda's OWN
    slot-numbered variables, ``body`` the expression over them.  Slots
    are binder-unique across a statement, so substituting an outer
    lambda's variables descends through inner lambda bodies without
    capturing the inner parameters.  ``type`` is the body's type."""

    params: Tuple[LambdaVar, ...] = ()
    body: Optional[Expr] = None

    def __repr__(self):
        return f"({', '.join(map(repr, self.params))}) -> {self.body!r}"


@dataclasses.dataclass(frozen=True)
class AggCall:
    """One aggregate in an aggregation node: fn over an argument
    expression, with optional DISTINCT and output type.

    Reference analog: the parsed form behind
    operator/aggregation/InternalAggregationFunction.java.

    ``arg2`` is the second argument of two-argument aggregates
    (min_by/max_by's key, approx_percentile's fraction literal).
    """

    fn: str  # sum | count | count_star | min | max | avg | min_by | ...
    arg: Optional[Expr]
    type: Type
    distinct: bool = False
    filter: Optional[Expr] = None
    arg2: Optional[Expr] = None
    # third argument (approx_percentile's weight column)
    arg3: Optional[Expr] = None

    def __repr__(self):
        a = "*" if self.arg is None else repr(self.arg)
        return f"{self.fn}({'DISTINCT ' if self.distinct else ''}{a})"


# ---------------------------------------------------------------------------
# Typing rules (FunctionRegistry / SignatureBinder analog, kept pragmatic)
# ---------------------------------------------------------------------------

ARITH = {"add", "sub", "mul", "div", "mod", "neg"}
CMP = {"eq", "ne", "lt", "le", "gt", "ge"}
LOGIC = {"and", "or", "not"}


def infer_type(fn: str, args: Sequence[Expr]) -> Type:
    ts = [a.type for a in args]
    # declarative generic signatures resolve first (FunctionRegistry +
    # SignatureBinder analog, presto_tpu/signature.py); unknown names
    # fall through to the structural arms below
    from presto_tpu.signature import REGISTRY

    resolved = REGISTRY.resolve(fn, ts)
    if resolved is not None:
        return resolved
    if fn in CMP or fn in LOGIC or fn in ("like", "is_null", "not_null", "in", "between"):
        return BOOLEAN
    if fn == "neg":
        return ts[0]
    if fn in ARITH:
        a, b = ts[0], ts[1]
        if a.is_decimal or b.is_decimal:
            ad = a if a.is_decimal else DecimalType(18, 0)
            bd = b if b.is_decimal else DecimalType(18, 0)
            if a.name == "double" or b.name == "double":
                return DOUBLE
            if a.name == "real" or b.name == "real":
                from presto_tpu.types import REAL

                return REAL  # DECIMAL op REAL -> REAL (reference parity)
            # long operands stay long (two-limb); short stays short —
            # deviation: the reference widens short x short products
            # past p=18 automatically, here that needs an explicit cast
            long_ = ad.is_long_decimal or bd.is_long_decimal
            wide = (ad.precision or 0) > 36 or (bd.precision or 0) > 36
            p = 38 if wide else (36 if long_ else 18)
            if fn == "mul":
                return DecimalType(p, ad.scale + bd.scale)
            if fn == "div":
                return DOUBLE  # deviation: reference returns decimal
            return DecimalType(p, max(ad.scale, bd.scale))
        if fn == "div" and a.name != "double" and b.name != "double":
            return common_super_type(a, b)  # integer division stays integral
        return common_super_type(a, b)
    if fn in ("year", "month", "day", "day_of_week", "day_of_year", "quarter", "week",
              "hour", "minute", "second", "millisecond", "date_diff"):
        return BIGINT
    if fn in ("date_add_days", "date_add_months"):
        return DATE
    if fn in ("ts_add_micros", "ts_add_months", "cast_timestamp", "from_unixtime"):
        return TIMESTAMP
    if fn == "cast_date":
        return DATE
    if fn == "to_unixtime":
        return DOUBLE
    if fn == "date_trunc":
        return ts[1]  # truncation preserves the operand's type
    if fn == "date_add":
        return ts[2]
    if fn in ("sqrt", "cbrt", "exp", "ln", "log10", "log2", "power", "pow",
              "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
              "sinh", "cosh", "tanh", "degrees", "radians", "truncate"):
        return DOUBLE
    if fn in ("is_nan", "is_finite"):
        return BOOLEAN
    if fn == "width_bucket":
        return BIGINT
    if fn == "pi":
        return DOUBLE
    if fn == "e":
        return DOUBLE
    if fn == "abs":
        return ts[0]
    if fn in ("ceil", "ceiling", "floor"):
        t = ts[0]
        return BIGINT if t.is_decimal else t
    if fn == "round":
        t = ts[0]
        if t.is_decimal:
            digits = args[1].value if len(args) > 1 and isinstance(args[1], Literal) else 0
            return DecimalType(18, min(digits, t.scale))
        return t
    if fn == "sign":
        return BIGINT
    if fn == "nullif":
        return ts[0]
    if fn in ("length", "strpos", "codepoint", "json_array_length",
              "url_extract_port", "hll_bucket", "hll_rho", "json_size"):
        return BIGINT
    if fn == "concat" and any(t.is_raw_string for t in ts):
        from presto_tpu.types import VarcharType

        width = 0
        for a in args:
            if isinstance(a, Literal):
                width += len(str(a.value).encode()) if a.value is not None else 0
            elif a.type.is_raw_string:
                width += a.type.value_shape[0]
            else:
                raise TypeError("concat mixes raw and dictionary strings")
        return VarcharType(max(width, 1), raw=True)
    if fn in ("char2hexint",
              "upper", "lower", "trim", "ltrim", "rtrim", "reverse",
              "regexp_extract", "regexp_replace", "replace", "split_part",
              "lpad", "rpad", "concat", "json_extract", "json_extract_scalar",
              "json_format", "url_extract_host", "url_extract_path",
              "url_extract_protocol", "url_extract_query", "url_decode",
              "url_encode", "normalize", "to_hex", "translate", "soundex",
              "json_parse", "md5_hex", "sha1_hex", "sha256_hex"):
        return ts[0]
    if fn in ("bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
              "bitwise_shift_left", "bitwise_shift_right", "bit_count",
              "from_base", "crc32", "xxhash64", "year_of_week",
              "levenshtein_distance", "hamming_distance"):
        return BIGINT
    if fn == "is_infinite":
        return BOOLEAN
    if fn == "date_format":
        from presto_tpu.types import VARCHAR as _VARCHAR

        return _VARCHAR
    if fn == "date_parse":
        return TIMESTAMP
    if fn in ("from_iso8601_date", "last_day_of_month"):
        return DATE
    if fn == "to_utf8":
        from presto_tpu.types import VarbinaryType

        return VarbinaryType(64)
    if fn == "split":
        from presto_tpu.types import ArrayType, VARCHAR as _VARCHAR

        cap = int(args[2].value) if len(args) > 2 and \
            isinstance(args[2], Literal) and args[2].value else 8
        return ArrayType(_VARCHAR, min(cap, 64))
    if fn in ("array_intersect", "array_except", "array_remove"):
        return ts[0]  # bounded by the left array's capacity
    if fn == "array_union":
        from presto_tpu.types import ArrayType

        return ArrayType(common_super_type(ts[0].element, ts[1].element),
                         min(64, ts[0].max_elems + ts[1].max_elems))
    if fn == "arrays_overlap":
        return BOOLEAN
    if fn == "map_concat":
        from presto_tpu.types import MapType

        cap = min(64, sum(t.max_elems for t in ts))
        kt, vt = ts[0].key_element, ts[0].element
        for t in ts[1:]:
            kt = common_super_type(kt, t.key_element)
            vt = common_super_type(vt, t.element)
        return MapType(kt, vt, cap)
    if fn in ("regexp_like", "starts_with", "ends_with", "contains_str",
              "is_json_scalar"):
        return BOOLEAN
    if fn in ("coalesce", "if", "case"):
        # supertype over value branches; untyped NULL literals (bound
        # as bigint by default) unify with anything, so skip them —
        # coalesce(null, varchar_col, 'x') must not fold bigint+varchar
        if fn == "coalesce":
            branches = list(args)
        elif fn == "if":
            branches = [args[1], args[2]]
        else:  # case: [when1, then1, ..., else]
            branches = [args[i] for i in range(1, len(args) - 1, 2)] + [args[-1]]
        typed = [b.type for b in branches
                 if not (isinstance(b, Literal) and b.value is None)]
        if not typed:
            return branches[0].type
        out = typed[0]
        for t in typed[1:]:
            out = common_super_type(out, t)
        return out
    if fn == "cast_double":
        return DOUBLE
    if fn == "cast_bigint":
        return BIGINT
    if fn == "cast_real":
        from presto_tpu.types import REAL

        return REAL
    if fn == "cast_smallint":
        from presto_tpu.types import SMALLINT

        return SMALLINT
    if fn == "cast_tinyint":
        from presto_tpu.types import TINYINT

        return TINYINT
    if fn == "cast_time":
        from presto_tpu.types import TIME

        return TIME
    if fn == "cast_char":
        from presto_tpu.types import CharType

        return CharType(int(args[1].value))
    if fn == "cast_varbinary":
        from presto_tpu.types import VarbinaryType

        return VarbinaryType(int(args[1].value))
    if fn == "cast_decimal":
        return DecimalType(int(args[1].value), int(args[2].value))
    if fn == "substr":
        return ts[0]  # dictionary codes pass through; values derive
    # -- ML (reference: presto-ml LearnClassifierAggregation etc.)
    if fn == "regress":
        return DOUBLE
    if fn == "classify":
        return BIGINT
    # -- geospatial (reference: presto-geospatial GeoFunctions.java)
    if fn in ("st_area", "st_x", "st_y", "st_distance"):
        return DOUBLE
    if fn == "st_contains":
        return BOOLEAN
    if fn == "st_geometryfromtext":
        return ts[0]
    if fn == "st_point":
        from presto_tpu.types import GEOMETRY_POINT

        return GEOMETRY_POINT
    # -- ARRAY / MAP (reference: operator/scalar/ArrayFunctions et al.)
    if fn == "array_construct":
        from presto_tpu.types import ArrayType

        elem = ts[0] if ts else BIGINT
        for t in ts[1:]:
            elem = common_super_type(elem, t)
        return ArrayType(elem, max(len(ts), 1))
    if fn == "array_sum":
        e = ts[0].element
        return DOUBLE if e.name == "double" else (e if e.is_decimal else BIGINT)
    if fn == "array_average":
        return DOUBLE
    if fn == "sequence":
        from presto_tpu.types import ArrayType

        if not all(isinstance(a, Literal) for a in args):
            raise TypeError("sequence() bounds must be literals (static shape)")
        lo, hi = int(args[0].value), int(args[1].value)
        step = int(args[2].value) if len(args) > 2 else 1
        n = max((hi - lo) // step + 1, 0) if step else 0
        if n <= 0 or n > 10000:
            raise TypeError(f"sequence() produces {n} elements (1..10000)")
        return ArrayType(BIGINT, n)
    if fn == "slice":
        if len(args) != 3 or not (isinstance(args[1], Literal)
                                  and isinstance(args[2], Literal)):
            raise TypeError("slice(arr, start, length) needs literal "
                            "start/length (static shape)")
        if int(args[1].value) == 0:
            raise TypeError("SQL array indices start at 1")
        if int(args[2].value) < 0:
            raise TypeError("slice() length must be >= 0")
        return ts[0]
    if fn == "repeat":
        from presto_tpu.types import ArrayType

        if not isinstance(args[1], Literal):
            raise TypeError("repeat() count must be a literal (static shape)")
        n = int(args[1].value)
        if n < 0 or n > 10000:
            raise TypeError("repeat() count out of range")
        return ArrayType(ts[0], max(n, 1))
    if fn == "array_concat":
        from presto_tpu.types import ArrayType

        elem = common_super_type(ts[0].element, ts[1].element)
        return ArrayType(elem, ts[0].max_elems + ts[1].max_elems)
    if fn == "array_transform":
        from presto_tpu.types import ArrayType

        return ArrayType(ts[1], ts[0].max_elems)  # args = (arr, body)
    if fn == "array_filter":
        return ts[0]
    if fn in ("any_match", "all_match", "none_match"):
        return BOOLEAN
    if fn in ("map", "map_construct"):
        from presto_tpu.types import MapType

        if len(ts) != 2 or not (ts[0].is_array and ts[1].is_array):
            raise TypeError("map(keys_array, values_array) expected")
        return MapType(ts[0].element, ts[1].element,
                       min(ts[0].max_elems, ts[1].max_elems))
    # typed, message-bearing error: a KeyError here leaked raw through
    # the SPI boundary (engine_lint spi-exception rule); the binder's
    # statement boundary re-wraps this as a BindError
    raise TypeError(f"unknown function {fn} for types {ts}")


# -- convenience constructors ------------------------------------------------

def col(index: int, type_: Type, name: str = "") -> ColumnRef:
    return ColumnRef(type=type_, index=index, name=name)


def lit(value: Any, type_: Type) -> Literal:
    return Literal(type=type_, value=value)


def call(fn: str, *args: Expr) -> Call:
    return Call(type=infer_type(fn, args), fn=fn, args=tuple(args))


def eq(a: Expr, b: Expr) -> Call:
    return call("eq", a, b)


def and_(*xs: Expr) -> Expr:
    out = xs[0]
    for x in xs[1:]:
        out = call("and", out, x)
    return out
