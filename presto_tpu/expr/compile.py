"""Compile Expr trees to JAX functions over Pages.

Reference analog: sql/gen/PageFunctionCompiler.java:164
(compileProjection/compileFilter -> bytecode PageProjection/PageFilter).
The compiled artifact here is a closure ``page -> (data, valid)`` built
from jnp primitives; XLA fuses the whole tree (plus its consumers) into
one kernel, which is the TPU equivalent of the reference's generated
``evaluate`` loops.

SQL NULL semantics: every compiled node returns (data, valid). Scalar
functions are null-propagating; AND/OR implement three-valued logic
(false AND null = false). Filters select rows where data & valid.

String handling: VARCHAR columns are dictionary codes. String literals
resolve to codes at compile time against the column's Dictionary;
LIKE / IN / prefix predicates evaluate host-side once over the
dictionary into a boolean LUT, and the device does one gather —
reference analog of dictionary-aware processing
(operator/project/DictionaryAwarePageProjection.java).
"""

from __future__ import annotations

import fnmatch
import re
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.expr.ir import AggCall, Call, ColumnRef, Expr, Literal
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT as BIGINT_T
from presto_tpu.types import BOOLEAN, DOUBLE, MICROS_PER_DAY, Type

CompiledExpr = Callable[[Page], Tuple[jax.Array, jax.Array]]

# derived-dictionary cache: (id(inner), start, length) -> (inner, derived).
# Keeping the inner reference alive pins its id.
_DERIVED_DICTS: dict = {}


# fns whose result is a per-value string transform of a single string
# column: codes pass through, only the dictionary's values change
# (DictionaryAwarePageProjection analog). Transforms may return None
# (SQL NULL) — compile() folds a null-LUT into validity.
STRING_TRANSFORM_FNS = frozenset({
    "substr", "upper", "lower", "trim", "ltrim", "rtrim", "reverse",
    "char2hexint",
    "regexp_extract", "regexp_replace", "replace", "split_part",
    "lpad", "rpad", "concat", "json_extract", "json_extract_scalar",
    "url_extract_host", "url_extract_path", "url_extract_protocol",
    "url_extract_query", "translate", "normalize", "soundex",
    "url_encode", "url_decode", "json_format", "json_parse",
    "md5_hex", "sha1_hex", "sha256_hex",
})


_GEO_FNS = frozenset({
    "st_geometryfromtext", "st_point", "st_distance", "st_contains",
    "st_area", "st_x", "st_y",
})

_CONTAINER_FNS = frozenset({
    "array_construct", "subscript", "element_at", "cardinality",
    "jaccard_index", "intersection_cardinality", "hash_counts",
    "contains", "array_position", "array_min", "array_max", "array_sum",
    "array_average", "array_sort", "array_distinct", "map_keys",
    "map_values", "map", "map_construct",
    "array_transform", "array_filter", "any_match", "all_match",
    "none_match", "sequence", "slice", "repeat", "array_concat",
    "array_intersect", "array_union", "array_except", "arrays_overlap",
    "array_remove", "map_concat",
    "map_filter", "transform_keys", "transform_values", "zip_with",
    "reduce", "split",
})


# single-argument double -> double math (MathFunctions.java sweep)
_UNARY_DOUBLE_FNS = {
    "sqrt": jnp.sqrt, "cbrt": jnp.cbrt, "exp": jnp.exp, "ln": jnp.log,
    "log10": jnp.log10, "log2": jnp.log2,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "is_nan": jnp.isnan, "is_finite": jnp.isfinite,
    "is_infinite": jnp.isinf,
}


# -- null-mask policy declarations ------------------------------------------
# Expression-level analogue of analysis/rules.NULL_MASK_POLICY: every
# scalar kernel family declares how its output validity mask relates to
# its inputs'.  analysis/kernel_soundness.py proves this table against an
# independent model (analysis/ranges.null_effect, derived from the
# abstract-transfer catalog); a kernel with no declaration — or one whose
# declaration disagrees with the model — fails EXPLAIN (TYPE VALIDATE)
# and the corpus gate.
#
#   strict      output NULL iff any input NULL (validity = AND of inputs)
#   preserving  validity is DERIVED, not intersected: 3VL short-circuits,
#               conditionals, and null tests can return non-NULL from
#               NULL inputs
#   generating  the kernel itself introduces NULLs beyond its inputs'
#               (overflow / zero-divisor / out-of-range-cast / parse
#               failure lanes go invalid at runtime)
NULL_POLICY = {}
for _f in (
    # comparisons and predicates over valid lanes
    "eq", "ne", "lt", "le", "gt", "ge", "not", "like", "in",
    "regexp_like", "starts_with", "ends_with", "contains",
    "arrays_overlap", "is_json_scalar", "st_contains",
    # arithmetic carried in float lanes (NaN, never wraps)
    "pow", "power", "atan2", "sqrt", "cbrt", "exp", "ln", "log10", "log2",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "degrees", "radians", "is_nan", "is_finite", "is_infinite",
    "sign", "ceil", "ceiling", "floor", "round", "truncate",
    # widening / representation-preserving casts
    "cast_real", "cast_decimal", "cast_char", "cast_varbinary",
    "cast_date", "cast_time", "cast_timestamp",
    # calendar moves and field extraction (every date has every field)
    "year", "month", "day", "quarter", "week", "year_of_week",
    "day_of_week", "day_of_year", "hour", "minute", "second",
    "millisecond", "date_add", "date_add_days", "date_add_months",
    "date_diff", "date_trunc", "date_format", "last_day_of_month",
    "ts_add_micros", "ts_add_months", "to_unixtime",
    # string transforms (total functions over their domain)
    "length", "lower", "upper", "trim", "ltrim", "rtrim", "substr",
    "concat", "replace", "reverse", "lpad", "rpad", "split",
    "regexp_replace", "translate", "normalize", "soundex", "codepoint",
    "levenshtein_distance", "hamming_distance", "jaccard_index",
    "char2hexint", "to_utf8", "url_encode", "json_format", "repeat",
    # digests and hashes
    "md5_hex", "sha1_hex", "sha256_hex", "crc32", "xxhash64",
    "hll_bucket", "hll_rho", "hash_counts", "classify", "regress",
    "intersection_cardinality",
    # containers: construction and total accessors
    "cardinality", "array_construct", "array_concat", "array_distinct",
    "array_union", "array_intersect", "array_except", "array_position",
    "array_remove", "array_sort", "array_filter", "array_transform",
    "any_match", "none_match", "all_match", "zip_with", "slice",
    "sequence", "map", "map_construct", "map_keys", "map_values",
    "map_filter", "transform_keys", "transform_values",
    "row_construct", "row_field", "retype_row", "split_to_map",
    # bitwise (wrap-free lane ops)
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_shift_left", "bitwise_shift_right", "bit_count",
    # strict-null variadics (any NULL argument nulls the row)
    "greatest", "least",
    # geometry
    "st_point", "st_x", "st_y", "st_area", "st_distance",
    "st_geometryfromtext",
    # TRY marker: runtime identity, mask passes through unchanged (the
    # child's own policy accounts for its trapped lanes)
    "try",
):
    NULL_POLICY[_f] = "strict"
for _f in (
    # 3VL short-circuits and conditionals derive their own validity
    "and", "or", "coalesce", "if", "case",
    # null tests always return a non-NULL boolean
    "is_null", "not_null",
    # and(ge, le) under the hood: FALSE can emerge from a NULL bound
    "between",
):
    NULL_POLICY[_f] = "preserving"
for _f in (
    # wrapped add/sub/mul/neg/abs lanes NULL at runtime (the reference
    # raises ARITHMETIC_OVERFLOW; see _ovf_add and friends)
    "add", "sub", "mul", "neg", "abs",
    # zero divisors NULL the lane (reference raises DIVISION_BY_ZERO)
    "div", "mod",
    # out-of-range narrowing NULLs (reference raises INVALID_CAST_ARGUMENT)
    "cast_smallint", "cast_tinyint",
    # varchar parse failures NULL (reference raises on bad input)
    "cast_bigint", "cast_double",
    "nullif",
    # out-of-bounds / missing-key access
    "subscript", "element_at",
    # partial parses and extractions
    "json_extract", "json_extract_scalar", "json_array_length",
    "json_size", "json_parse",
    "url_extract_host", "url_extract_path", "url_extract_port",
    "url_extract_protocol", "url_extract_query", "url_decode",
    "regexp_extract", "from_base", "date_parse", "from_iso8601_date",
    "split_part", "array_min", "array_max", "array_sum", "array_average",
    "reduce", "map_concat", "strpos", "width_bucket", "from_unixtime",
):
    NULL_POLICY[_f] = "generating"
del _f


# MySQL date_format/date_parse pattern -> python strftime/strptime
# (DateTimeFunctions.java's JodaTime DateTimeFormat table)
_MYSQL_FMT = {
    "Y": "%Y", "y": "%y", "m": "%m", "c": "%-m", "d": "%d", "e": "%-d",
    "j": "%j", "a": "%a", "W": "%A", "b": "%b", "M": "%B", "w": "%w",
    "H": "%H", "k": "%-H", "h": "%I", "I": "%I", "i": "%M", "s": "%S",
    "S": "%S", "f": "%f", "p": "%p", "T": "%H:%M:%S", "r": "%I:%M:%S %p",
    "%": "%%",
    # %-m / %-d / %-H (non-padded c/e/k) are glibc strftime extensions;
    # strptime ignores the flag, so parsing accepts both forms
}

#: format codes that need time-of-day (unsupported for DATE columns'
#: domain-dictionary path only when formatting, fine for parsing)
_MYSQL_TIME_CODES = frozenset("HkhIisSfpTr")


def _mysql_to_strftime(fmt: str, for_parse: bool = False) -> str:
    out, i = [], 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%" and i + 1 < len(fmt):
            code = fmt[i + 1]
            got = _MYSQL_FMT.get(code)
            if got is None:
                raise ValueError(f"unsupported date format code %{code}")
            if for_parse:
                # strptime rejects the glibc no-pad flag but already
                # accepts non-padded numbers under the plain codes
                got = got.replace("%-", "%")
            out.append(got)
            i += 2
        else:
            out.append(ch.replace("%", "%%"))
            i += 1
    return "".join(out)


_INT_RX = re.compile(r"^[+-]?\d+$")
_FLOAT_RX = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?$")
_I64_LO, _I64_HI = -(1 << 63), (1 << 63) - 1


def parse_number_strict(v, to_double: bool):
    """varchar -> number with the reference's accepted syntax only (no
    python extras like '1_0' or padding) and int64 range enforcement;
    None for anything else (shared by the bind-time literal fold and
    the column dictionary LUT so they cannot diverge)."""
    if not isinstance(v, str):
        return None
    if to_double:
        if _FLOAT_RX.match(v):
            return float(v)
        if v in ("Infinity", "-Infinity", "+Infinity", "NaN"):
            return float(v.replace("Infinity", "inf"))
        return None
    if not _INT_RX.match(v):
        return None
    n = int(v)
    return n if _I64_LO <= n <= _I64_HI else None


def mysql_datetime_micros(v: str, fmt: str):
    """date_parse's conversion, shared by the bind-time literal fold
    and the column LUT so they cannot diverge.  None on parse failure
    (deviation: the reference raises)."""
    import datetime as _dt

    try:
        ts = _dt.datetime.strptime(v, _mysql_to_strftime(fmt, for_parse=True))
    except ValueError:
        return None
    delta = ts - _dt.datetime(1970, 1, 1)
    return ((delta.days * 86400 + delta.seconds) * 1_000_000
            + delta.microseconds)  # exact, no float round-trip


def iso_date_days(v: str):
    """from_iso8601_date's epoch-day conversion (shared fold/LUT)."""
    import datetime as _dt

    try:
        return _dt.date.fromisoformat(v).toordinal() - 719163
    except ValueError:
        return None


def xxh64_signed(data: bytes) -> int:
    """xxhash64 wrapped into BIGINT's signed range (shared fold/LUT)."""
    h = _xxh64(data)
    return h - (1 << 64) if h >= (1 << 63) else h


def _subst_lambda_vars(e, slot_to_index: dict):
    """Replace THIS lambda's slot-numbered variables with ColumnRefs
    into the lambda-evaluation page's appended virtual channels.  Slots
    are binder-unique, so descending through nested LambdaExprs only
    rewrites captures of the outer variables — the inner lambda's own
    parameters (different slots) are left for its compile site."""
    from presto_tpu.expr.ir import (
        ColumnRef as _Ref, LambdaExpr as _LE, LambdaVar as _LV,
    )

    if isinstance(e, _LV):
        if e.slot not in slot_to_index:
            return e  # an inner lambda's own parameter
        return _Ref(type=e.type, index=slot_to_index[e.slot], name=f"λ{e.slot}")
    if isinstance(e, _LE):
        return _LE(type=e.type, params=e.params,
                   body=_subst_lambda_vars(e.body, slot_to_index))
    if isinstance(e, Call):
        return Call(type=e.type, fn=e.fn,
                    args=tuple(_subst_lambda_vars(a, slot_to_index)
                               for a in e.args))
    return e


def _levenshtein(a: str, b: str) -> int:
    """Classic DP edit distance (StringFunctions.java#levenshteinDistance)."""
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _xxh64(data: bytes, seed: int = 0) -> int:
    """xxHash64 (public spec, xxhash.com) — host-side over dictionary
    values, one device gather for the column form."""
    P1, P2, P3 = 0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9
    P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i + 32 <= n:
            for k, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 8 * k:i + 8 * k + 8], "little")
                v = (v + lane * P2) & M
                v = (rotl(v, 31) * P1) & M
                if k == 0:
                    v1 = v
                elif k == 1:
                    v2 = v
                elif k == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            v = (rotl((v * P2) & M, 31) * P1) & M
            h = ((h ^ v) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 8 <= n:
        lane = int.from_bytes(data[i:i + 8], "little")
        h ^= (rotl((lane * P2) & M, 31) * P1) & M
        h = (rotl(h, 27) * P1 + P4) & M
        i += 8
    if i + 4 <= n:
        h ^= (int.from_bytes(data[i:i + 4], "little") * P1) & M
        h = (rotl(h, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h ^= (data[i] * P5) & M
        h = (rotl(h, 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h


def _json_path_lookup(doc: str, path: str):
    """Tiny JSONPath subset: $, .name, [idx] (reference:
    operator/scalar/JsonExtract.java's path engine).
    Returns (found, value) so a JSON null VALUE is distinguishable
    from a missing path."""
    import json as _json

    try:
        cur = _json.loads(doc)
    except Exception:
        return False, None
    if not path.startswith("$"):
        return False, None
    i = 1
    toks = re.findall(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]", path[i:])
    consumed = sum(len(f".{a}") if a else len(f"[{b}]") for a, b in toks)
    if consumed != len(path) - 1:
        return False, None
    for name, idx in toks:
        if name:
            if not isinstance(cur, dict) or name not in cur:
                return False, None
            cur = cur[name]
        else:
            j = int(idx)
            if not isinstance(cur, list) or j >= len(cur):
                return False, None
            cur = cur[j]
    return True, cur


def _json_path_get(doc: str, path: str):
    found, cur = _json_path_lookup(doc, path)
    if not found:
        return None
    return cur


def _string_transform(e: "Call"):
    """value -> Optional[value] host transform for STRING_TRANSFORM_FNS,
    plus a hashable cache key; None if ``e`` is not such a call."""
    fn = e.fn
    lits = tuple(a.value for a in e.args if isinstance(a, Literal))
    key = (fn,) + lits

    if fn == "substr":
        start = e.args[1].value
        length = e.args[2].value if len(e.args) > 2 else None
        end = None if length is None else start - 1 + length
        return lambda v: v[start - 1 : end], key
    if fn in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse"):
        f = {"upper": str.upper, "lower": str.lower, "trim": str.strip,
             "ltrim": str.lstrip, "rtrim": str.rstrip,
             "reverse": lambda s: s[::-1]}[fn]
        return f, key
    if fn == "char2hexint":
        # teradata: utf-16be code units as uppercase hex
        return lambda v: "".join(f"{ord(ch):04X}" for ch in v), key
    if fn == "regexp_extract":
        rx = re.compile(e.args[1].value)
        group = int(e.args[2].value) if len(e.args) > 2 else 0

        def f(v, rx=rx, g=group):
            m = rx.search(v)
            return m.group(g) if m else None

        return f, key
    if fn == "regexp_replace":
        rx = re.compile(e.args[1].value)
        repl = e.args[2].value if len(e.args) > 2 else ""
        # $N -> \g<N> (plain \N would make $0 a NUL octal escape)
        py_repl = re.sub(r"\$(\d+)", r"\\g<\1>", repl)
        return lambda v: rx.sub(py_repl, v), key
    if fn == "replace":
        frm = e.args[1].value
        to = e.args[2].value if len(e.args) > 2 else ""
        return lambda v: v.replace(frm, to), key
    if fn == "translate":
        # chars of `from` map positionally to `to`; unpaired chars drop
        # (StringFunctions.java#translate)
        frm = e.args[1].value
        to = e.args[2].value
        table: dict = {}
        for i, f in enumerate(frm):
            # first occurrence of a duplicated `from` char wins
            table.setdefault(ord(f), to[i] if i < len(to) else None)
        return lambda v: v.translate(table), key
    if fn == "normalize":
        form = e.args[1].value if len(e.args) > 1 else "NFC"
        import unicodedata

        return lambda v: unicodedata.normalize(form, v), key
    if fn == "url_encode":
        # application/x-www-form-urlencoded (the reference's
        # URLEncoder): space -> '+', '*' '-' '.' '_' stay bare
        from urllib.parse import quote_plus

        # quote_plus hard-codes '~' as safe; URLEncoder encodes it
        return lambda v: quote_plus(v, safe="*-._").replace("~", "%7E"), key
    if fn == "url_decode":
        from urllib.parse import unquote_plus

        return lambda v: unquote_plus(v), key
    if fn in ("json_format", "json_parse"):
        # both normalize JSON text (the engine's JSON values are
        # varchar); invalid input -> NULL (deviation: json_parse raises
        # in the reference)
        import json as _json

        def jf(v):
            try:
                return _json.dumps(_json.loads(v), separators=(",", ":"))
            except Exception:
                return None

        return jf, key
    if fn in ("md5_hex", "sha1_hex", "sha256_hex"):
        import hashlib

        algo = fn[:-4]

        def hx(v, algo=algo):
            # reference to_hex (BaseEncoding.base16) is UPPERCASE
            return hashlib.new(algo, v.encode()).hexdigest().upper()

        return hx, key
    if fn == "soundex":
        # classic American Soundex (StringFunctions.java#soundex)
        codes = {}
        for group, digit in (("BFPV", "1"), ("CGJKQSXZ", "2"),
                             ("DT", "3"), ("L", "4"), ("MN", "5"),
                             ("R", "6")):
            for ch in group:
                codes[ch] = digit

        def sdx(v, codes=codes):
            s = [c for c in v.upper() if c.isalpha()]
            if not s:
                return None
            out = s[0]
            prev = codes.get(s[0], "")
            for c in s[1:]:
                d = codes.get(c, "")
                if d and d != prev:
                    out += d
                if c not in "HW":
                    prev = d
            return (out + "000")[:4]

        return sdx, key
    if fn == "split_part":
        delim, n = e.args[1].value, int(e.args[2].value)

        def f(v, delim=delim, n=n):
            parts = v.split(delim)
            return parts[n - 1] if 0 < n <= len(parts) else None

        return f, key
    if fn in ("lpad", "rpad"):
        n = int(e.args[1].value)
        pad = e.args[2].value if len(e.args) > 2 else " "
        if fn == "lpad":
            def f(v, n=n, pad=pad):
                if len(v) >= n:
                    return v[:n]
                fill = (pad * n)[: n - len(v)]
                return fill + v
        else:
            def f(v, n=n, pad=pad):
                if len(v) >= n:
                    return v[:n]
                return v + (pad * n)[: n - len(v)]
        return f, key
    if fn == "concat":
        # one string column + literals in any positions
        parts = []
        for a in e.args:
            parts.append(a.value if isinstance(a, Literal) else None)
        if parts.count(None) != 1:
            return None

        def f(v, parts=tuple(parts)):
            return "".join(v if p is None else str(p) for p in parts)

        return f, key + ("@" + str(parts.index(None)),)
    if fn in ("json_extract", "json_extract_scalar"):
        path = e.args[1].value
        scalar = fn == "json_extract_scalar"

        def f(v, path=path, scalar=scalar):
            import json as _json

            got = _json_path_get(v, path)
            if got is None:
                return None
            if scalar:
                if isinstance(got, (dict, list)):
                    return None
                if isinstance(got, bool):
                    return "true" if got else "false"
                return str(got)
            return _json.dumps(got, separators=(",", ":"))

        return f, key
    if fn.startswith("url_extract_"):
        from urllib.parse import urlparse

        part = fn[len("url_extract_"):]

        def f(v, part=part):
            try:
                u = urlparse(v)
            except Exception:
                return None
            got = {"host": u.hostname, "path": u.path, "protocol": u.scheme,
                   "query": u.query}[part]
            return got if got else (got if part == "path" else None)

        return f, key
    return None


def literal_array_dictionary(values) -> Dictionary:
    """Shared dictionary for an all-literal string array
    (ARRAY['a','b']): codes are positions in the sorted distinct
    values.  Cached by content so binder, compiler, and channel
    provenance all resolve to the SAME identity-hashed Dictionary."""
    key = ("$litarr", tuple(values))
    if key not in _DERIVED_DICTS:
        _DERIVED_DICTS[key] = (None, Dictionary(sorted(set(values))), [False])
    return _DERIVED_DICTS[key][1]


def expr_dictionary(e: Expr, dictionaries: Sequence[Optional[Dictionary]]) -> Optional[Dictionary]:
    """Dictionary provenance of a string-typed expression: bare columns
    keep theirs; string-transform calls derive a transformed dictionary
    host-side (codes unchanged — only the code->value mapping
    transforms; None results become "" with validity handled by the
    compiler's null LUT)."""
    if isinstance(e, ColumnRef):
        return dictionaries[e.index]
    if isinstance(e, Literal) and e.value is not None:
        # projected string constant ('store' AS channel): a singleton
        # dictionary whose only code is the literal (cached so repeated
        # plans share the identity-hashed Dictionary)
        key = ("$lit", e.value)
        if key not in _DERIVED_DICTS:
            _DERIVED_DICTS[key] = (None, Dictionary([e.value]), [False])
        return _DERIVED_DICTS[key][1]
    if isinstance(e, Call) and e.fn == "cast_char":
        # metadata-only re-type: same codes, same dictionary
        return expr_dictionary(e.args[0], dictionaries)
    if isinstance(e, Call) and e.fn == "split":
        inner = expr_dictionary(e.args[0], dictionaries)
        delim = e.args[1]
        if inner is None or not isinstance(delim, Literal) \
                or delim.value is None:
            return None
        pd, _ = ExprCompiler.split_parts(inner, delim.value,
                                         e.type.max_elems)
        return pd
    if isinstance(e, Call) and e.fn in ("subscript", "element_at") \
            and e.args[0].type.is_array \
            and e.args[0].type.element is not None \
            and e.args[0].type.element.is_string:
        # an element of a dictionary-coded string array keeps the
        # array's element dictionary
        return expr_dictionary(e.args[0], dictionaries)
    if isinstance(e, Call) and e.fn == "array_construct" \
            and e.type.is_array and e.type.element is not None \
            and e.type.element.is_string \
            and all(isinstance(a, Literal) for a in e.args):
        # ARRAY['a','b']: the elements code into one derived dictionary
        return literal_array_dictionary(
            [a.value for a in e.args if a.value is not None])
    if isinstance(e, Call) and e.fn == "date_format":
        fmt = e.args[1]
        if isinstance(fmt, Literal) and fmt.value is not None:
            return ExprCompiler.date_format_dictionary(fmt.value)
        return None
    if isinstance(e, Call) and e.fn in ("case", "if", "coalesce"):
        return merged_string_dictionary(e, dictionaries)
    if isinstance(e, Call) and e.fn in STRING_TRANSFORM_FNS:
        col = _transform_column(e)
        if col is None:
            return None
        inner = expr_dictionary(col, dictionaries)
        if inner is None:
            return None
        tf = _string_transform(e)
        if tf is None:
            return None
        f, tkey = tf
        key = (id(inner),) + tkey
        if key not in _DERIVED_DICTS:
            values = [f(v) for v in inner.values]
            nulls = [v is None for v in values]
            d = Dictionary(["" if v is None else v for v in values])
            _DERIVED_DICTS[key] = (inner, d, nulls)
        return _DERIVED_DICTS[key][1]
    return None


def _string_case_branches(e: "Call") -> Sequence[Expr]:
    """Value-producing operands of a case/if/coalesce expression."""
    if e.fn == "case":
        return list(e.args[1::2]) + [e.args[-1]]
    if e.fn == "if":
        return [e.args[1], e.args[2]]
    return list(e.args)  # coalesce


def merged_string_dictionary(e: "Call", dictionaries) -> Optional[Dictionary]:
    """Union dictionary for a string-valued case/if/coalesce: every
    branch is either a literal or an expression with a known dictionary;
    branch codes remap into the union at compile time (the compiler's
    _compile_string_case must build the SAME dictionary — cached by
    branch identity so both see one object)."""
    parts = []
    key_parts = []
    for b in _string_case_branches(e):
        if isinstance(b, Literal):
            parts.append(("lit", b.value))
            key_parts.append(("L", b.value))
        else:
            d = expr_dictionary(b, dictionaries)
            if d is None:
                return None
            parts.append(("dict", d))
            key_parts.append(("D", id(d)))
    key = ("$case",) + tuple(key_parts)
    if key not in _DERIVED_DICTS:
        values: list = []
        seen: dict = {}
        for kind, v in parts:
            vals = [v] if kind == "lit" else v.values
            for val in vals:
                if val is not None and val not in seen:
                    seen[val] = len(values)
                    values.append(val)
        d = Dictionary(values if values else [""])
        # pin the branch dictionaries in the value tuple: the key uses
        # their id()s, and a GC'd-then-reallocated Dictionary must not
        # hit a stale entry (same contract as the transform-dict cache)
        pins = tuple(v for kind, v in parts if kind == "dict")
        _DERIVED_DICTS[key] = (pins, d, [False] * len(d.values))
    return _DERIVED_DICTS[key][1]


def _transform_column(e: "Call") -> Optional[Expr]:
    """The single string-typed non-literal argument of a transform."""
    cols = [a for a in e.args if not isinstance(a, Literal)]
    if len(cols) != 1:
        return None
    return cols[0]


def _transform_null_lut(e: "Call", dictionaries) -> Optional["jnp.ndarray"]:
    """Per-code validity for a derived dictionary (False where the
    transform yielded NULL); None when no entry is null."""
    col = _transform_column(e)
    inner = expr_dictionary(col, dictionaries)
    tf = _string_transform(e)
    if inner is None or tf is None:
        return None
    _, tkey = tf
    key = (id(inner),) + tkey
    entry = _DERIVED_DICTS.get(key)
    if entry is None or not any(entry[2]):
        return None
    return jnp.asarray([not n for n in entry[2]])


def _hll_from_hash(h: jax.Array, fn: str, P: int = None) -> jax.Array:
    """Shared HLL tail over a mixed uint64 hash lane: bucket = top P
    bits; rho = leading-zero count of the remainder + 1 (sentinel bit
    caps it)."""
    if P is None:
        P = ExprCompiler.HLL_P
    if fn == "hll_bucket":
        return (h >> jnp.uint64(64 - P)).astype(jnp.int64)
    rest = (h << jnp.uint64(P)) | jnp.uint64(1 << (P - 1))
    clz = jnp.zeros(h.shape, dtype=jnp.uint64)
    x = rest
    for shift in (32, 16, 8, 4, 2, 1):
        empty = x < (jnp.uint64(1) << jnp.uint64(64 - shift))
        clz = clz + jnp.where(empty, jnp.uint64(shift), jnp.uint64(0))
        x = jnp.where(empty, x << jnp.uint64(shift), x)
    return (clz + jnp.uint64(1)).astype(jnp.int64)


def _mix_u64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer over uint64 lanes (device hash)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _rescale(data: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    if to_scale < from_scale:
        return data // (10 ** (from_scale - to_scale))
    return data


def _to_double(data: jax.Array, t: Type) -> jax.Array:
    if t.is_long_decimal:
        from presto_tpu.ops import decimal128 as d128

        return d128.to_double(data, t.scale)
    if t.is_decimal:
        return data.astype(jnp.float64) / (10.0 ** t.scale)
    return data.astype(jnp.float64)


def _to_long_limbs(data: jax.Array, t: Type, from_scale: int, to_scale: int,
                   limbs: int = 2) -> jax.Array:
    """Coerce a short/long decimal (or integer) column to long-decimal
    limbs at the target scale (``limbs`` = 5 for decimal(37..38))."""
    from presto_tpu.ops import decimal128 as d128

    if t.is_long_decimal:
        cur = data
        if limbs == 5 and data.shape[-1] == 2:
            cur = d128.widen(cur)
        return d128.rescale(cur, from_scale, to_scale)
    return d128.rescale(d128.from_int64(data.astype(jnp.int64), limbs=limbs),
                        from_scale, to_scale)


def _decimal_limbs(*types) -> int:
    """Limb width covering every decimal operand (5 once any p > 36)."""
    return 5 if any(t.is_decimal and (t.precision or 0) > 36
                    for t in types) else 2


def _where_rows(cond: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-mask select that broadcasts over per-value trailing dims
    (long-decimal limbs)."""
    if a.ndim > cond.ndim:
        cond = cond.reshape(cond.shape + (1,) * (a.ndim - cond.ndim))
    return jnp.where(cond, a, b)


def _trunc_div(a: jax.Array, b: jax.Array) -> jax.Array:
    """SQL integer division truncates toward zero (Presto semantics),
    unlike Python/jnp floor division."""
    bs = jnp.where(b == 0, 1, b)
    q = jnp.abs(a) // jnp.abs(bs)
    return jnp.where((a < 0) ^ (bs < 0), -q, q)


# -- two's-complement overflow detection ------------------------------------
# jnp integer ops wrap like C; the reference's checked bytecode raises
# ARITHMETIC_OVERFLOW instead (operator/scalar/MathFunctions.java uses
# Math.addExact and friends).  Jitted kernels can't raise, so wrapped
# lanes are detected post-hoc and NULLed — the same documented-deviation
# family as division-by-zero -> NULL.  The static analyzer
# (analysis/kernel_soundness.py) reports where these guards can fire.

def _ovf_add(a: jax.Array, b: jax.Array, r: jax.Array) -> jax.Array:
    """r = a + b wrapped iff operands share a sign the result lost."""
    return ((a ^ r) & (b ^ r)) < 0


def _ovf_sub(a: jax.Array, b: jax.Array, r: jax.Array) -> jax.Array:
    """r = a - b wrapped iff operands differ in sign and r flipped."""
    return ((a ^ b) & (a ^ r)) < 0


def _ovf_mul(a: jax.Array, b: jax.Array, r: jax.Array) -> jax.Array:
    """r = a * b wrapped iff floor-dividing the result back misses b or
    leaves a remainder (any nonzero deviation is a multiple of 2^width,
    far above |a|).  The -1 * INT_MIN corner is pinned separately: there
    the check division itself wraps and reports exact."""
    imin = jnp.iinfo(r.dtype).min
    den = jnp.where(a == 0, 1, a)
    q = r // den
    exact = (r - q * den == 0) & (q == b)
    return ((a != 0) & jnp.logical_not(exact)) | ((a == -1) & (b == imin))


def _ovf_neg(d: jax.Array) -> jax.Array:
    """-INT_MIN / |INT_MIN| have no representation and wrap in place."""
    return d == jnp.iinfo(d.dtype).min


def _rescale_guard(data: jax.Array, from_scale: int,
                   to_scale: int) -> Tuple[jax.Array, jax.Array]:
    """`_rescale` plus a wrap mask: up-scaling multiplies by 10^k, so
    any |value| beyond int64_max // 10^k wraps before the arithmetic it
    feeds even runs (down-scaling only shrinks — never wraps)."""
    if to_scale > from_scale:
        f = 10 ** (to_scale - from_scale)
        lim = jnp.iinfo(jnp.int64).max // f
        return data * f, (data > lim) | (data < -lim)
    return _rescale(data, from_scale, to_scale), jnp.zeros(data.shape, jnp.bool_)


def _trunc_mod(a: jax.Array, b: jax.Array) -> jax.Array:
    """SQL mod takes the sign of the dividend."""
    bs = jnp.where(b == 0, 1, b)
    r = jnp.abs(a) % jnp.abs(bs)
    return jnp.where(a < 0, -r, r)


def _like_to_regex(pattern: str) -> "re.Pattern":
    # SQL LIKE: % = any run, _ = any single char
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


class ExprCompiler:
    """Compiles expressions against a fixed input schema (types +
    dictionaries), mirroring how the reference compiles per plan node."""

    def __init__(self, input_types: Sequence[Type], dictionaries: Sequence[Optional[Dictionary]]):
        self.input_types = list(input_types)
        self.dictionaries = list(dictionaries)

    @classmethod
    def for_page(cls, page: Page) -> "ExprCompiler":
        return cls([b.type for b in page.blocks], [b.dictionary for b in page.blocks])

    # ------------------------------------------------------------------
    def compile(self, expr: Expr) -> CompiledExpr:
        if isinstance(expr, ColumnRef):
            i = expr.index
            return lambda page: (page.blocks[i].data, page.blocks[i].valid)

        if isinstance(expr, Literal):
            return self._compile_literal(expr)

        assert isinstance(expr, Call), expr
        fn = expr.fn
        if fn == "try":
            # runtime identity: trappable errors already NULL their
            # lanes engine-wide; the node only marks the subtree as
            # TRY-sanctioned for the kernel-soundness tier
            return self.compile(expr.args[0])
        if fn == "row_construct":
            fns = [self.compile(a) for a in expr.args]
            rt = expr.type

            def run_row_construct(page, fns=fns, rt=rt):
                from presto_tpu.ops import container as ct

                pairs = [f(page) for f in fns]
                out = ct.construct_row([d for d, _ in pairs],
                                       [v for _, v in pairs], rt)
                return out, page.row_mask

            return run_row_construct
        if fn == "row_field":
            base_f = self.compile(expr.args[0])
            rt = expr.args[0].type
            i = int(expr.args[1].value)

            def run_row_field(page, base_f=base_f, rt=rt, i=i):
                from presto_tpu.ops import container as ct

                d, v = base_f(page)
                out, nn = ct.row_field(d, rt, i)
                return out, v & nn

            return run_row_field
        if fn == "retype_row":
            # CAST(row AS ROW(name type, ...)): names are metadata on
            # the type; the storage matrix passes through unchanged
            base_f = self.compile(expr.args[0])

            def run_retype_row(page, base_f=base_f):
                return base_f(page)

            return run_retype_row
        if fn in _CONTAINER_FNS:
            return self._compile_container(expr)
        if fn in _GEO_FNS:
            return self._compile_geo(expr)
        if fn in ("regress", "classify"):
            return self._compile_ml(expr)
        if fn in ("and", "or"):
            return self._compile_logic(expr)
        if fn == "not":
            (a,) = [self.compile(x) for x in expr.args]

            def run_not(page):
                d, v = a(page)
                return jnp.logical_not(d), v

            return run_not
        if fn in ("is_null", "not_null"):
            (a,) = [self.compile(x) for x in expr.args]
            want_null = fn == "is_null"

            def run_isnull(page):
                _, v = a(page)
                d = jnp.logical_not(v) if want_null else v
                return d, jnp.ones_like(v)

            return run_isnull
        if fn == "like":
            return self._compile_like(expr)
        if fn == "in":
            return self._compile_in(expr)
        if fn == "between":
            lo = Call(type=BOOLEAN, fn="ge", args=(expr.args[0], expr.args[1]))
            hi = Call(type=BOOLEAN, fn="le", args=(expr.args[0], expr.args[2]))
            return self.compile(Call(type=BOOLEAN, fn="and", args=(lo, hi)))
        if fn in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._compile_cmp(expr)
        if fn in ("add", "sub", "mul", "div", "mod"):
            return self._compile_arith(expr)
        if fn == "neg":
            (a,) = [self.compile(x) for x in expr.args]
            if expr.type.is_long_decimal:
                from presto_tpu.ops import decimal128 as d128

                return lambda page: ((lambda dv: (d128.neg(dv[0]), dv[1]))(a(page)))

            def run_neg(page):
                d, v = a(page)
                if jnp.issubdtype(d.dtype, jnp.integer):
                    # -INT_MIN wraps in place; NULL that lane (deviation:
                    # the reference raises ARITHMETIC_OVERFLOW)
                    v = v & jnp.logical_not(_ovf_neg(d))
                return -d, v

            return run_neg
        if fn in ("year", "month", "day"):
            return self._compile_datepart(expr)
        if fn == "date_add_days":
            a, b = [self.compile(x) for x in expr.args]

            def run_dadd(page):
                (da, va), (db, vb) = a(page), b(page)
                return (da + db).astype(jnp.int32), va & vb

            return run_dadd
        if fn == "if":
            if self._is_dict_string_case(expr):
                return self._compile_string_case(expr)
            out_t = expr.type
            c = self.compile(expr.args[0])
            t = self._compile_operand(expr.args[1], out_t)
            f = self._compile_operand(expr.args[2], out_t)
            tt, ft = expr.args[1].type, expr.args[2].type

            def run_if(page):
                (dc, vc), (dt, vt), (df, vf) = c(page), t(page), f(page)
                dt2 = self._coerce(dt, tt, out_t)
                df2 = self._coerce(df, ft, out_t)
                cond = dc & vc
                return _where_rows(cond, dt2, df2), jnp.where(cond, vt, vf)

            return run_if
        if fn == "case":
            if self._is_dict_string_case(expr):
                return self._compile_string_case(expr)
            return self._compile_case(expr)
        if fn == "coalesce":
            if self._is_dict_string_case(expr):
                return self._compile_string_case(expr)
            out_t = expr.type
            parts = [(self._compile_operand(x, out_t), x.type) for x in expr.args]

            def run_coalesce(page):
                data = None
                valid = None
                for cf, t in parts:
                    d, v = cf(page)
                    d = self._coerce(d, t, out_t)
                    if data is None:
                        data, valid = d, v
                    else:
                        take = jnp.logical_not(valid) & v
                        data = _where_rows(take, d, data)
                        valid = valid | v
                return data, valid

            return run_coalesce
        if fn in ("cast_double", "cast_bigint") \
                and expr.args[0].type.is_raw_string:
            raise ValueError(
                f"{fn} is unsupported over raw varchar columns "
                "(dictionary varchar parses via a value LUT)")
        if fn in ("cast_double", "cast_bigint") \
                and expr.args[0].type.is_string:
            # varchar -> number: parse the dictionary values host-side,
            # one device gather; unparseable -> NULL (deviation: the
            # reference raises)
            return self._compile_string_number_cast(expr)
        if fn == "cast_double":
            (a,) = [self.compile(x) for x in expr.args]
            t = expr.args[0].type
            return lambda page: ((lambda dv: (_to_double(dv[0], t), dv[1]))(a(page)))
        if fn == "cast_bigint":
            (a,) = [self.compile(x) for x in expr.args]
            t = expr.args[0].type

            def run_cast_bigint(page):
                d, v = a(page)
                if t.is_long_decimal:
                    return self._coerce(d, t, BIGINT_T), v
                if t.is_decimal and t.scale:
                    # HALF_UP, matching the reference's
                    # DecimalCasts.shortDecimalToBigint (2.5 -> 3,
                    # -2.5 -> -3); floor q plus remainder vote, with the
                    # negative side tipping strictly past the midpoint
                    s = 10 ** t.scale
                    q = d // s
                    r = d - q * s
                    up = jnp.where(d >= 0, r * 2 >= s, r * 2 > s)
                    d = q + up.astype(d.dtype)
                return d.astype(jnp.int64), v

            return run_cast_bigint
        if fn in ("cast_real", "cast_smallint", "cast_tinyint"):
            (a,) = [self.compile(x) for x in expr.args]
            t = expr.args[0].type
            target = {"cast_real": jnp.float32, "cast_smallint": jnp.int16,
                      "cast_tinyint": jnp.int8}[fn]

            def run_cast_narrow(page):
                d, v = a(page)
                if t.is_long_decimal:
                    # collapse the two-limb matrix through the shared
                    # coercion first (as cast_bigint does)
                    d = (self._coerce(d, t, DOUBLE) if fn == "cast_real"
                         else self._coerce(d, t, BIGINT_T))
                elif t.is_decimal:
                    d = d / (10.0 ** t.scale) if fn == "cast_real" \
                        else d // (10 ** t.scale)
                if fn == "cast_real":
                    return d.astype(target), v
                # out-of-range values NULL instead of wrapping
                # (documented deviation: the reference raises
                # INVALID_CAST_ARGUMENT); the range test runs at the
                # wide dtype, before the narrowing astype can lie
                info = jnp.iinfo(target)
                wide = d.astype(jnp.int64)
                fits = (wide >= info.min) & (wide <= info.max)
                return wide.astype(target), v & fits

            return run_cast_narrow
        if fn in ("cast_char", "cast_varbinary"):
            # metadata-only re-typing: dictionary codes / byte matrices
            # pass through unchanged
            a = self.compile(expr.args[0])
            return lambda page: a(page)
        if fn == "cast_time":
            (a,) = [self.compile(x) for x in expr.args]
            t = expr.args[0].type
            if not (t.name in ("timestamp", "time")):
                raise ValueError(f"cannot cast {t} to time")

            def run_cast_time(page):
                d, v = a(page)
                if t.name == "timestamp":
                    d = jnp.mod(d, MICROS_PER_DAY)  # time-of-day part
                return d.astype(jnp.int64), v

            return run_cast_time
        if fn in STRING_TRANSFORM_FNS:
            if fn == "concat" and any(
                a.type.is_raw_string for a in expr.args if not isinstance(a, Literal)
            ):
                return self._compile_raw_concat(expr)
            _rc = _transform_column(expr)
            if _rc is not None and _rc.type.is_raw_string:
                return self._compile_raw_transform(expr)
            # dictionary codes pass through unchanged; the *values* are
            # transformed host-side once (see _dict_of) — the device
            # never touches bytes (DictionaryAwarePageProjection analog).
            # Transforms that can yield NULL fold a per-code LUT into
            # validity.
            col = _transform_column(expr)
            if col is None or _string_transform(expr) is None:
                # never silently pass raw codes through an underivable
                # transform — that would surface codes as values
                raise KeyError(f"cannot compile string transform {expr}")
            # force derived-dict materialization so the null LUT exists
            if expr_dictionary(expr, self.dictionaries) is None:
                raise ValueError(f"no dictionary for string transform {expr}")
            null_lut = _transform_null_lut(expr, self.dictionaries)
            inner_f = self.compile(col)
            if null_lut is None:
                return inner_f

            def run_derived(page):
                d, v = inner_f(page)
                return d, v & null_lut[jnp.clip(d, 0, null_lut.shape[0] - 1)]

            return run_derived
        if fn in ("length", "strpos", "codepoint", "json_array_length",
                  "url_extract_port", "from_base", "date_parse",
                  "from_iso8601_date", "levenshtein_distance",
                  "hamming_distance", "json_size"):
            if expr.args[0].type.is_raw_string:
                if fn not in ("length", "strpos", "codepoint"):
                    raise ValueError(
                        f"{fn} is unsupported over raw varchar columns "
                        "(dictionary varchar runs it as a value LUT)")
                return self._compile_raw_int_fn(expr)
            return self._compile_string_lut_fn(expr)
        if fn in ("crc32", "xxhash64"):
            return self._compile_binary_hash(expr)
        if fn == "date_format":
            return self._compile_date_format(expr)
        if fn in ("last_day_of_month", "year_of_week"):
            return self._compile_datepart(expr)
        if fn in ("regexp_like", "starts_with", "ends_with", "is_json_scalar"):
            if expr.args[0].type.is_raw_string:
                return self._compile_raw_bool(expr)
            return self._compile_string_bool_lut(expr)
        if fn in ("hll_bucket", "hll_rho"):
            return self._compile_hll(expr)
        if fn == "cast_decimal":
            (a,) = [self.compile(x) for x in expr.args[:1]]
            t0 = expr.args[0].type
            out_t = expr.type

            def run_cast_decimal(page):
                d, v = a(page)
                if t0.name == "double":
                    if out_t.is_long_decimal:
                        # scale in limb space: hi/lo split of the scaled
                        # float stays within int64 for any p<=36 value
                        from presto_tpu.ops import decimal128 as d128

                        scaled = jnp.round(d * (10.0 ** out_t.scale))
                        hi = jnp.floor(scaled / float(d128.BASE))
                        lo = scaled - hi * float(d128.BASE)
                        two = d128.normalize(hi.astype(jnp.int64),
                                             lo.astype(jnp.int64))
                        if (out_t.precision or 0) > 36:
                            # float64 carries < 54 bits anyway; the
                            # 2-limb path is exact for every float
                            return d128.widen(two), v
                        return two, v
                    return jnp.round(d * (10.0 ** out_t.scale)).astype(jnp.int64), v
                return self._coerce(d, t0, out_t), v

            return run_cast_decimal
        if fn in ("abs", "sign", "sqrt", "cbrt", "exp", "ln", "log10", "log2",
                  "power", "pow", "ceil", "ceiling", "floor", "round",
                  "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
                  "sinh", "cosh", "tanh", "degrees", "radians", "truncate",
                  "width_bucket", "is_nan", "is_finite", "is_infinite"):
            return self._compile_math(expr)
        if fn in ("bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
                  "bitwise_shift_left", "bitwise_shift_right", "bit_count"):
            return self._compile_bitwise(expr)
        if fn in ("greatest", "least"):
            return self._compile_greatest_least(expr)
        if fn == "nullif":
            ta, tb = expr.args[0].type, expr.args[1].type
            a = self.compile(expr.args[0])
            b = self._compile_operand(expr.args[1], ta)
            if ta.is_raw_string:
                from presto_tpu.ops import rawstring as rs

                def run_nullif_raw(page):
                    (da, va), (db, vb) = a(page), b(page)
                    _, eq_ = rs.compare(da, db)
                    return da, va & jnp.logical_not(va & vb & eq_)

                return run_nullif_raw

            def run_nullif(page):
                (da, va), (db, vb) = a(page), b(page)
                da2, db2 = self._align_pair(da, ta, db, tb)
                eq_ = va & vb & (da2 == db2)
                return da, va & jnp.logical_not(eq_)

            return run_nullif
        if fn in ("day_of_week", "day_of_year", "quarter", "week",
                  "hour", "minute", "second", "millisecond"):
            return self._compile_datepart(expr)
        if fn in ("ts_add_micros", "ts_add_months", "date_add_months",
                  "cast_timestamp", "cast_date", "to_unixtime", "from_unixtime",
                  "date_trunc", "date_add", "date_diff"):
            return self._compile_datetime(expr)
        raise KeyError(f"cannot compile {expr}")

    def _compile_string_lut_fn(self, expr: Call) -> CompiledExpr:
        """String scalar -> int via a host-computed LUT over the
        dictionary, one device gather (length, strpos, codepoint,
        json_array_length, url_extract_port). None values null out."""
        colref = expr.args[0]
        if expr.fn in ("levenshtein_distance", "hamming_distance") \
                and isinstance(colref, Literal):
            colref = expr.args[1]  # literal may sit on either side
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        fn = expr.fn
        if any(isinstance(a, Literal) and a.value is None
               for a in expr.args):
            # a NULL parameter argument (either side for the symmetric
            # distance fns) nulls the whole column out
            def run_null(page):
                dd, v = cf(page)
                return jnp.zeros_like(dd, dtype=jnp.int64), v & False

            return run_null
        if fn == "length":
            lut_vals = [len(v) for v in d.values]
        elif fn == "strpos":  # strpos(col, needle_literal): 1-based, 0 = miss
            sub = expr.args[1]
            assert isinstance(sub, Literal), "strpos needle must be a literal"
            lut_vals = [v.find(sub.value) + 1 for v in d.values]
        elif fn == "codepoint":
            lut_vals = [ord(v[0]) if v else None for v in d.values]
        elif fn == "json_array_length":
            import json as _json

            def jal(v):
                try:
                    got = _json.loads(v)
                except Exception:
                    return None
                return len(got) if isinstance(got, list) else None

            lut_vals = [jal(v) for v in d.values]
        elif fn == "json_size":
            path = expr.args[1].value

            def jsize(v, path=path):
                found, got = _json_path_lookup(v, path)
                if not found:
                    return None
                return len(got) if isinstance(got, (dict, list)) else 0

            lut_vals = [jsize(v) for v in d.values]
        elif fn == "from_base":
            radix = int(expr.args[1].value)

            def fb(v, radix=radix):
                try:
                    return int(v, radix)
                except Exception:
                    return None

            lut_vals = [fb(v) for v in d.values]
        elif fn == "date_parse":
            fmt = expr.args[1].value
            lut_vals = [mysql_datetime_micros(v, fmt) for v in d.values]
        elif fn == "from_iso8601_date":
            lut_vals = [iso_date_days(v) for v in d.values]
        elif fn in ("levenshtein_distance", "hamming_distance"):
            other = expr.args[1] if isinstance(expr.args[1], Literal) \
                else expr.args[0]
            if not isinstance(other, Literal) or other.value is None:
                raise ValueError(f"{fn} needs one literal argument "
                                 "(column x column would need a cross "
                                 "product of dictionaries)")
            lit = other.value
            if fn == "hamming_distance":
                lut_vals = [
                    sum(a != b for a, b in zip(v, lit))
                    if len(v) == len(lit) else None  # deviation: ref raises
                    for v in d.values]
            else:
                lut_vals = [_levenshtein(v, lit) for v in d.values]
        else:  # url_extract_port
            from urllib.parse import urlparse

            def port(v):
                try:
                    return urlparse(v).port
                except Exception:
                    return None

            lut_vals = [port(v) for v in d.values]
        nulls = [v is None for v in lut_vals]
        lut = jnp.asarray([0 if v is None else v for v in lut_vals], dtype=jnp.int64)
        vlut = None if not any(nulls) else jnp.asarray([not n for n in nulls])

        def run_lut(page):
            dd, v = cf(page)
            c = jnp.clip(dd, 0, lut.shape[0] - 1)
            if vlut is not None:
                v = v & vlut[c]
            return lut[c], v

        return run_lut

    def _compile_string_bool_lut(self, expr: Call) -> CompiledExpr:
        """String predicate via a host-computed boolean LUT over the
        dictionary (regexp_like, starts_with, ends_with, is_json_scalar)."""
        colref = expr.args[0]
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        fn = expr.fn
        if fn == "regexp_like":
            rx = re.compile(expr.args[1].value)
            pred = lambda v: rx.search(v) is not None
        elif fn == "starts_with":
            prefix = expr.args[1].value
            pred = lambda v: v.startswith(prefix)
        elif fn == "ends_with":
            suffix = expr.args[1].value
            pred = lambda v: v.endswith(suffix)
        else:  # is_json_scalar
            import json as _json

            def pred(v):
                try:
                    return not isinstance(_json.loads(v), (dict, list))
                except Exception:
                    return False

        lut = jnp.asarray(d.lut(pred))

        def run_blut(page):
            dd, v = cf(page)
            return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

        return run_blut

    def _compile_string_number_cast(self, expr: Call) -> CompiledExpr:
        colref = expr.args[0]
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        to_double = expr.fn == "cast_double"
        vals = [parse_number_strict(v, to_double) for v in d.values]
        dtype = jnp.float64 if to_double else jnp.int64
        lut = jnp.asarray([0 if x is None else x for x in vals], dtype=dtype)
        vlut = jnp.asarray([x is not None for x in vals])

        def run_str_cast(page):
            dd, v = cf(page)
            c = jnp.clip(dd, 0, lut.shape[0] - 1)
            return lut[c], v & vlut[c]

        return run_str_cast

    # (id(inner dict), delim, cap) -> (inner ref, parts Dictionary,
    # np code matrix, np lengths)
    _SPLIT_CACHE: dict = {}

    @classmethod
    def split_parts(cls, d, delim: str, cap: int):
        """Derived artifacts of split(col, delim): the union dictionary
        of every value's parts plus a (n_codes, 1+cap) array-matrix LUT
        of part codes — one device gather per page
        (StringFunctions.java#split realized dictionary-side)."""
        key = (id(d), delim, cap)
        got = cls._SPLIT_CACHE.get(key)
        if got is not None:
            return got[1], got[2]
        parts_index: dict = {}
        values: list = []

        def code_of(p):
            c = parts_index.get(p)
            if c is None:
                c = parts_index[p] = len(values)
                values.append(p)
            return c

        import numpy as np

        lut = np.zeros((len(d.values), 1 + cap), dtype=np.int32)
        for i, v in enumerate(d.values):
            # limit semantics: the last element keeps the unsplit
            # remainder (StringFunctions.java#split's limit contract —
            # the slot capacity acts as the limit, losslessly)
            ps = v.split(delim, cap - 1)
            lut[i, 0] = len(ps)
            for j, p in enumerate(ps):
                lut[i, 1 + j] = code_of(p)
        pd = Dictionary(values or [""])
        cls._SPLIT_CACHE[key] = (d, pd, lut)
        return pd, lut

    def _compile_split(self, expr: Call) -> CompiledExpr:
        colref = expr.args[0]
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        delim = expr.args[1]
        if not isinstance(delim, Literal) or delim.value is None:
            raise ValueError("split delimiter must be a literal")
        cap = expr.type.max_elems
        _, lut_np = self.split_parts(d, delim.value, cap)
        lut = jnp.asarray(lut_np)

        def run_split(page):
            dd, v = cf(page)
            c = jnp.clip(dd, 0, lut.shape[0] - 1)
            return lut[c].astype(expr.type.np_dtype), v

        return run_split

    def _compile_binary_hash(self, expr: Call) -> CompiledExpr:
        """crc32 / xxhash64 of to_utf8(varchar): hashed host-side over
        the dictionary values, one device gather
        (VarbinaryFunctions.java#crc32/#xxhash64).  Only the
        to_utf8(string) composition is supported — general varbinary
        lanes would hash bytes on device."""
        inner = expr.args[0]
        if not (isinstance(inner, Call) and inner.fn == "to_utf8"):
            raise ValueError(f"{expr.fn} supports to_utf8(varchar) "
                             "arguments only")
        colref = inner.args[0]
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        if expr.fn == "crc32":
            import zlib

            vals = [zlib.crc32(v.encode()) for v in d.values]
        else:
            vals = [xxh64_signed(v.encode()) for v in d.values]
        lut = jnp.asarray(vals, dtype=jnp.int64)

        def run_hash(page):
            dd, v = cf(page)
            return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

        return run_hash

    # date_format dictionaries are pure functions of (fmt, day range) —
    # cache them across queries
    _DATE_FMT_CACHE: dict = {}
    #: formatted-day dictionary range: 1900-01-01 .. 2100-01-01
    DATE_FMT_BASE = -25567
    DATE_FMT_SPAN = 73049

    @classmethod
    def date_format_dictionary(cls, fmt: str) -> "Dictionary":
        """The domain dictionary for date_format(date_col, fmt): one
        formatted string per epoch day over a 1900..2100 range, codes =
        day - base.  TPU-first: the format never touches the device —
        dates become dictionary codes with one subtract."""
        got = cls._DATE_FMT_CACHE.get(fmt)
        if got is not None:
            return got
        import datetime as _dt

        py_fmt = _mysql_to_strftime(fmt)
        if any(c in _MYSQL_TIME_CODES
               for c in re.findall(r"%(.)", fmt)):
            raise ValueError(
                "date_format supports date-valued columns (time-of-day "
                "format codes need the timestamp's full domain)")
        base = _dt.date(1900, 1, 1)
        values = [(base + _dt.timedelta(days=i)).strftime(py_fmt)
                  for i in range(cls.DATE_FMT_SPAN)]
        d = Dictionary(values)
        cls._DATE_FMT_CACHE[fmt] = d
        return d

    def _compile_date_format(self, expr: Call) -> CompiledExpr:
        if expr.args[0].type.name not in ("date", "timestamp"):
            raise ValueError("date_format requires a date argument")
        fmt = expr.args[1]
        if not isinstance(fmt, Literal) or fmt.value is None:
            raise ValueError("date_format format must be a literal")
        self.date_format_dictionary(fmt.value)  # validate fmt eagerly
        a = self.compile(expr.args[0])
        is_ts = expr.args[0].type.name == "timestamp"

        def run_date_format(page):
            d, v = a(page)
            days = (d.astype(jnp.int64) // MICROS_PER_DAY) if is_ts \
                else d.astype(jnp.int64)
            code = days - self.DATE_FMT_BASE
            inrange = (code >= 0) & (code < self.DATE_FMT_SPAN)
            return jnp.clip(code, 0, self.DATE_FMT_SPAN - 1).astype(
                jnp.int32), v & inrange

        return run_date_format

    # HLL sketch primitives (reference:
    # operator/aggregation/ApproximateCountDistinctAggregations.java +
    # airlift HyperLogLog; here integer device math, m = 4096 buckets)
    HLL_P = 12
    HLL_M = 1 << 12

    def _compile_hll(self, expr: Call) -> CompiledExpr:
        colref = expr.args[0]
        # optional second literal argument: register-index width P
        # (approx_set's value sketches use a smaller m than
        # approx_distinct's internal rewrite)
        P = (int(expr.args[1].value) if len(expr.args) > 1
             else ExprCompiler.HLL_P)
        cf = self.compile(colref)
        t = colref.type
        fn = expr.fn
        canon_lut = None
        if t.is_raw_string:
            from presto_tpu.ops.rawstring import hash_bytes

            def run_raw_hll(page):
                d, v = cf(page)
                h = _mix_u64(hash_bytes(d).astype(jnp.uint64))
                return _hll_from_hash(h, fn, P), v

            return run_raw_hll
        if t.is_string:
            # canonicalize codes to value ids so transforms that map
            # many codes to one value (substr/upper/...) count distinct
            # VALUES, not distinct source codes
            d = expr_dictionary(colref, self.dictionaries)
            if d is None:
                raise ValueError(f"no dictionary for string column {colref}")
            canon: dict = {}
            canon_lut = jnp.asarray(
                [canon.setdefault(v, len(canon)) for v in d.values],
                dtype=jnp.int64)

        def run_hll(page):
            d, v = cf(page)
            if t.name == "double":
                lane = jax.lax.bitcast_convert_type(d, jnp.int64)
            elif canon_lut is not None:
                lane = canon_lut[jnp.clip(d, 0, canon_lut.shape[0] - 1)]
            else:
                lane = d.astype(jnp.int64)
            h = _mix_u64(lane.astype(jnp.uint64))
            return _hll_from_hash(h, fn, P), v

        return run_hll

    def _compile_ml(self, expr: Call) -> CompiledExpr:
        """regress(model, features) / classify(model, features) —
        models are ARRAY(double) values from learn_regressor /
        learn_classifier (presto-ml's model type realized as plain
        arrays, so inference is pure device math)."""
        from presto_tpu.ops import container as ct

        model_e, feats_e = expr.args
        mf = self.compile(model_e)
        ff = self.compile(feats_e)
        mt, ft = model_e.type, feats_e.type
        if not (mt.is_array and ft.is_array):
            raise ValueError(f"{expr.fn} expects (model array, features array)")
        k = ft.max_elems

        def feats_matrix(fd):
            slots = ct.elem_slots(fd, ft)
            return jnp.where(ct.elem_null_mask(slots), 0.0,
                             slots.astype(jnp.float64))

        if expr.fn == "regress":

            def run_regress(page):
                (md, mv), (fd, fv) = mf(page), ff(page)
                w = ct.elem_slots(md, mt).astype(jnp.float64)
                x = feats_matrix(fd)
                pred = jnp.sum(w[:, :k] * x, axis=1) + w[:, k]
                return pred, mv & fv

            return run_regress

        from presto_tpu.ops.aggregate import ML_MAX_CLASSES

        C = ML_MAX_CLASSES

        def run_classify(page):
            (md, mv), (fd, fv) = mf(page), ff(page)
            m = ct.elem_slots(md, mt).astype(jnp.float64)
            x = feats_matrix(fd)
            n = x.shape[0]
            prior = m[:, 1 : 1 + C]
            mean = m[:, 1 + C : 1 + C + C * k].reshape(n, C, k)
            var = jnp.maximum(m[:, 1 + C + C * k : 1 + C + 2 * C * k]
                              .reshape(n, C, k), 1e-12)
            ll = jnp.log(jnp.maximum(prior, 1e-12)) + jnp.sum(
                -0.5 * jnp.log(2 * jnp.pi * var)
                - (x[:, None, :] - mean) ** 2 / (2 * var), axis=2)
            return jnp.argmax(ll, axis=1).astype(jnp.int64), mv & fv

        return run_classify

    def _compile_geo(self, expr: Call) -> CompiledExpr:
        """ST_* functions (presto-geospatial GeoFunctions.java).  WKT
        geometries ride dictionary varchar: host parse per distinct
        value, device kernels per row (geo.py)."""
        from presto_tpu import geo

        fn = expr.fn
        if fn == "st_geometryfromtext":
            arg = expr.args[0]
            if isinstance(arg, Literal) and arg.value is not None:
                geo.parse_wkt(str(arg.value))  # fail at compile, not per row
            return self.compile(arg)
        if fn == "st_point":
            raise ValueError(
                "ST_Point is only usable inside ST_Distance / ST_Contains")
        if fn in ("st_area", "st_x", "st_y"):
            host = {"st_area": geo.st_area, "st_x": geo.st_x, "st_y": geo.st_y}[fn]
            return self._geo_float_lut(expr.args[0], host)
        if fn == "st_distance":
            ax, ay = self._point_accessor(expr.args[0])
            bx, by = self._point_accessor(expr.args[1])

            def run_dist(page):
                (x1, v1), (y1, vy1) = ax(page), ay(page)
                (x2, v2), (y2, vy2) = bx(page), by(page)
                return (geo.point_distance(x1, y1, x2, y2),
                        v1 & vy1 & v2 & vy2)

            return run_dist
        assert fn == "st_contains"
        garg = _unwrap_geomtext(expr.args[0])
        px, py = self._point_accessor(expr.args[1])
        if isinstance(garg, Literal):
            g = geo.parse_wkt(str(garg.value))

            def run_contains_lit(page):
                (x, vx), (y, vy) = px(page), py(page)
                hit = geo.bbox_mask(g.bbox, x, y) & geo.points_in_geometry(g, x, y)
                return hit, vx & vy

            return run_contains_lit
        # dictionary-coded geometry column: one fused PIP per distinct
        # geometry, selected by code (the spatial-join inner kernel)
        d = self._dict_of(garg)
        if d is None:
            raise ValueError("ST_Contains geometry must be a WKT literal or "
                             "dictionary varchar column")
        cf = self.compile(garg)
        geoms = []
        for v in d.values:
            try:
                geoms.append(geo.parse_wkt(v))
            except Exception:
                geoms.append(None)

        def run_contains_col(page):
            (code, vg) = cf(page)
            (x, vx), (y, vy) = px(page), py(page)
            hit = jnp.zeros(x.shape[0], dtype=jnp.bool_)
            ok = jnp.zeros(x.shape[0], dtype=jnp.bool_)
            for gi, g in enumerate(geoms):
                sel = code == gi
                if g is None:
                    continue
                ok = ok | sel
                ghit = geo.bbox_mask(g.bbox, x, y) & geo.points_in_geometry(g, x, y)
                hit = jnp.where(sel, ghit, hit)
            return hit, vg & vx & vy & ok

        return run_contains_col

    def _geo_float_lut(self, arg: Expr, host) -> CompiledExpr:
        """varchar WKT -> float via host LUT over the dictionary."""
        arg = _unwrap_geomtext(arg)
        if isinstance(arg, Literal):
            val = host(str(arg.value)) if arg.value is not None else None

            def run_const(page):
                n = page.capacity
                return (jnp.full(n, 0.0 if val is None else float(val)),
                        jnp.full(n, val is not None))

            return run_const
        d = self._dict_of(arg)
        if d is None:
            raise ValueError("geometry argument needs a WKT literal or "
                             "dictionary varchar column")
        cf = self.compile(arg)
        vals = []
        for v in d.values:
            try:
                vals.append(host(v))
            except Exception:
                vals.append(None)
        lut = jnp.asarray([0.0 if v is None else float(v) for v in vals])
        vlut = jnp.asarray([v is not None for v in vals])

        def run_lut(page):
            code, v = cf(page)
            c = jnp.clip(code, 0, lut.shape[0] - 1)
            return lut[c], v & vlut[c]

        return run_lut

    def _point_accessor(self, e: Expr):
        """-> (x_fn, y_fn) compiled accessors for a point operand:
        ST_Point(x, y) call, WKT literal, or dictionary point column."""
        e = _unwrap_geomtext(e)
        if isinstance(e, Call) and e.fn == "st_point":
            xa = self.compile(e.args[0])
            ya = self.compile(e.args[1])
            tx, ty = e.args[0].type, e.args[1].type

            def run_x(page):
                data, v = xa(page)
                return _to_double(data, tx), v

            def run_y(page):
                data, v = ya(page)
                return _to_double(data, ty), v

            return run_x, run_y
        from presto_tpu import geo

        return (self._geo_float_lut(e, geo.st_x),
                self._geo_float_lut(e, geo.st_y))

    def _compile_container(self, expr: Call) -> CompiledExpr:
        """ARRAY/MAP functions -> masked trailing-axis vector kernels
        (ops/container.py; reference operator/scalar/ArrayFunctions,
        MapKeys, MapValues, ElementAt, CardinalityFunction)."""
        from presto_tpu.ops import container as ct

        fn = expr.fn
        out_t = expr.type
        if fn == "array_construct":
            elem_t = out_t.element
            if elem_t is not None and elem_t.is_string \
                    and all(isinstance(a, Literal) for a in expr.args):
                # all-literal string array (the binder rejects any
                # other string-array construction): elements become
                # codes in the shared derived dictionary; the channel/
                # unnest layer re-attaches it via expr_dictionary
                dic = literal_array_dictionary(
                    [a.value for a in expr.args if a.value is not None])
                codes = [(dic.code_of(a.value) if a.value is not None
                          else 0, a.value is not None) for a in expr.args]

                def run_construct_lit(page):
                    n = page.capacity
                    datas = [jnp.full((n,), c, jnp.int64) for c, _ in codes]
                    valids = [jnp.full((n,), ok, jnp.bool_)
                              for _, ok in codes]
                    return (ct.construct_array(datas, valids, out_t),
                            jnp.ones(n, jnp.bool_))

                return run_construct_lit
            parts = [(self._compile_operand(a, elem_t), a.type) for a in expr.args]

            def run_construct(page):
                datas, valids = [], []
                for cf, t in parts:
                    d, v = cf(page)
                    datas.append(self._coerce(d, t, elem_t))
                    valids.append(v)
                n = page.capacity
                return ct.construct_array(datas, valids, out_t), jnp.ones(n, jnp.bool_)

            return run_construct
        if fn in ("map", "map_construct"):
            k = self.compile(expr.args[0])
            v = self.compile(expr.args[1])
            kt, vt = expr.args[0].type, expr.args[1].type

            def run_map(page):
                (kd, kv), (vd, vv) = k(page), v(page)
                return ct.construct_map(kd, kt, vd, vt, out_t), kv & vv

            return run_map
        if fn == "sequence":
            lo = int(expr.args[0].value)
            step = int(expr.args[2].value) if len(expr.args) > 2 else 1
            n = out_t.max_elems
            row = jnp.concatenate([
                jnp.asarray([n], dtype=jnp.int64),
                lo + step * jnp.arange(n, dtype=jnp.int64),
            ])

            def run_seq(page):
                cap = page.capacity
                return (jnp.broadcast_to(row[None, :], (cap, n + 1)),
                        jnp.ones(cap, jnp.bool_))

            return run_seq
        if fn == "repeat":
            val = self.compile(expr.args[0])
            n = out_t.max_elems
            count = int(expr.args[1].value)
            storage = out_t.np_dtype

            def run_repeat(page):
                d, v = val(page)
                sent = ct._null_const(storage)
                elems = jnp.where(v[:, None], d.astype(storage)[:, None],
                                  sent)
                body = jnp.broadcast_to(elems, (page.capacity, n))
                length = jnp.full((page.capacity, 1), float(count)
                                  if storage.kind == "f" else count,
                                  dtype=storage)
                return (jnp.concatenate([length, body], axis=1),
                        jnp.ones(page.capacity, jnp.bool_))

            return run_repeat
        if fn == "array_concat":
            a = self.compile(expr.args[0])
            b = self.compile(expr.args[1])
            ta, tb = expr.args[0].type, expr.args[1].type

            def run_cat(page):
                (da, va), (db, vb) = a(page), b(page)
                return ct.concat_arrays(da, ta, db, tb, out_t), va & vb

            return run_cat

        arg0 = self.compile(expr.args[0])
        t0 = expr.args[0].type
        if fn in ("subscript", "element_at"):
            idx = self.compile(expr.args[1])

            def run_sub(page):
                (d, v), (di, vi) = arg0(page), idx(page)
                out, ov = ct.subscript(d, t0, di, vi)
                return out.astype(out_t.np_dtype), v & ov

            return run_sub
        if fn == "cardinality":
            t0 = expr.args[0].type
            if t0.is_hll:
                # HLL estimate with linear-counting small-range
                # correction (same estimator family as hll_merge);
                # slots 0..count-1 of the value half hold the rho of
                # each populated register
                m = t0.max_elems
                alpha = 0.7213 / (1.0 + 1.079 / m)

                def run_hll_card(page):
                    d, v = arg0(page)
                    cnt = jnp.clip(d[:, 0].astype(jnp.int64), 0, m)
                    rho = d[:, 1 + m: 1 + 2 * m].astype(jnp.float64)
                    j = jnp.arange(m, dtype=jnp.int64)[None, :]
                    present = j < cnt[:, None]
                    inv = jnp.where(present, jnp.exp2(-rho), 0.0).sum(axis=1)
                    zeros = (m - cnt).astype(jnp.float64)
                    raw = alpha * m * m / jnp.maximum(inv + zeros, 1e-12)
                    lc = m * jnp.log(m / jnp.maximum(zeros, 1.0))
                    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), lc, raw)
                    return jnp.round(est).astype(jnp.int64), v

                return run_hll_card
            if t0.name == "setdigest":
                # KMV estimator: exact below K slots; else
                # (K-1) / (fraction of hash space below the K-th
                # smallest hash)
                K = t0.max_elems

                def run_kmv_card(page):
                    d, v = arg0(page)
                    ln = jnp.maximum(d[:, 0].astype(jnp.int64), 0)
                    kth = d[:, K].astype(jnp.float64)  # largest stored
                    span = kth - float(jnp.iinfo(jnp.int64).min)
                    frac = jnp.maximum(span, 1.0) / 2.0 ** 64
                    est = jnp.round((K - 1) / frac).astype(jnp.int64)
                    return jnp.where(ln < K, ln, jnp.maximum(est, ln)), v

                return run_kmv_card

            def run_card(page):
                d, v = arg0(page)
                return ct.cardinality(d), v

            return run_card
        if fn in ("jaccard_index", "intersection_cardinality") \
                and t0.name == "setdigest":
            # KMV minhash comparison (SetDigestFunctions.java): over the
            # K smallest distinct hashes of the UNION, jaccard = the
            # fraction present in both digests; intersection = jaccard
            # x the union's KMV cardinality estimate.  A hash appearing
            # in both digests shows up as an adjacent duplicate in the
            # per-row sorted concat (hashes are distinct WITHIN one
            # digest).
            K = t0.max_elems
            argb = self.compile(expr.args[1])
            imin = float(jnp.iinfo(jnp.int64).min)

            def run_setdigest_pair(page):
                (da, va), (db, vb) = arg0(page), argb(page)
                la = jnp.clip(da[:, 0].astype(jnp.int64), 0, K)
                lb = jnp.clip(db[:, 0].astype(jnp.int64), 0, K)
                j = jnp.arange(K, dtype=jnp.int64)[None, :]
                big = jnp.iinfo(jnp.int64).max
                ha = jnp.where(j < la[:, None],
                               da[:, 1:1 + K].astype(jnp.int64), big)
                hb = jnp.where(j < lb[:, None],
                               db[:, 1:1 + K].astype(jnp.int64), big)
                m = jnp.sort(jnp.concatenate([ha, hb], axis=1), axis=1)
                live = m < big
                firsts = jnp.concatenate(
                    [jnp.ones_like(m[:, :1], jnp.bool_),
                     m[:, 1:] != m[:, :-1]], axis=1) & live
                nxt_dup = jnp.concatenate(
                    [m[:, 1:] == m[:, :-1],
                     jnp.zeros_like(m[:, :1], jnp.bool_)], axis=1)
                rank = jnp.cumsum(firsts.astype(jnp.int64), axis=1) - 1
                in_s = firsts & (rank < K)
                inter = jnp.sum((in_s & nxt_dup).astype(jnp.int64), axis=1)
                s_size = jnp.sum(in_s.astype(jnp.int64), axis=1)
                jac = inter.astype(jnp.float64) / jnp.maximum(s_size, 1)
                ok = va & vb
                if fn == "jaccard_index":
                    return jac, ok
                # union KMV estimate from the merged distinct hashes
                distinct_total = jnp.sum(firsts.astype(jnp.int64), axis=1)
                kth = jnp.max(jnp.where(in_s, m, jnp.iinfo(jnp.int64).min),
                              axis=1).astype(jnp.float64)
                frac = jnp.maximum(kth - imin, 1.0) / 2.0 ** 64
                union_est = jnp.where(
                    distinct_total < K, distinct_total,
                    jnp.round((K - 1) / frac).astype(jnp.int64))
                return (jnp.round(jac * union_est).astype(jnp.int64), ok)

            return run_setdigest_pair
        if fn == "hash_counts" and t0.name == "setdigest":
            # the digest IS [len, hashes.., counts..] — identical to the
            # map(bigint,bigint) layout; retype in place
            def run_hash_counts(page):
                d, v = arg0(page)
                return d.astype(out_t.np_dtype), v

            return run_hash_counts
        if fn in ("contains", "array_position"):
            x = self.compile(expr.args[1])
            kern = ct.contains if fn == "contains" else ct.array_position

            def run_ct(page):
                (d, v), (xd, xv) = arg0(page), x(page)
                out, ov = kern(d, t0, xd, xv)
                return out, v & ov

            return run_ct
        if fn in ("array_min", "array_max", "array_sum", "array_average"):

            def run_red(page):
                d, v = arg0(page)
                out, nonempty = ct.array_reduce(d, t0, fn)
                return out.astype(out_t.np_dtype), v & nonempty

            return run_red
        if fn in ("array_sort", "array_distinct"):
            kern = ct.array_sort if fn == "array_sort" else ct.array_distinct

            def run_tf(page):
                d, v = arg0(page)
                return kern(d, t0), v

            return run_tf
        if fn in ("map_keys", "map_values"):
            kern = ct.map_keys_array if fn == "map_keys" else ct.map_values_array

            def run_mk(page):
                d, v = arg0(page)
                return kern(d, t0, out_t), v

            return run_mk
        if fn in ("array_transform", "array_filter", "any_match",
                  "all_match", "none_match"):
            return self._compile_array_lambda(expr, arg0, t0)
        if fn in ("array_intersect", "array_union", "array_except"):
            b_f = self.compile(expr.args[1])
            tb = expr.args[1].type
            kern = {"array_intersect": ct.array_intersect,
                    "array_union": ct.array_union,
                    "array_except": ct.array_except}[fn]

            def run_setop(page):
                (d, v), (bd, bv) = arg0(page), b_f(page)
                return kern(d, t0, bd, tb, out_t), v & bv

            return run_setop
        if fn == "arrays_overlap":
            b_f = self.compile(expr.args[1])
            tb = expr.args[1].type

            def run_overlap(page):
                (d, v), (bd, bv) = arg0(page), b_f(page)
                out, ov = ct.arrays_overlap(d, t0, bd, tb)
                return out, v & bv & ov

            return run_overlap
        if fn == "array_remove":
            x_f = self.compile(expr.args[1])

            def run_remove(page):
                (d, v), (xd, xv) = arg0(page), x_f(page)
                return ct.array_remove(d, t0, xd), v & xv

            return run_remove
        if fn == "map_concat":
            b_f = self.compile(expr.args[1])
            tb = expr.args[1].type

            def run_mconcat(page):
                (d, v), (bd, bv) = arg0(page), b_f(page)
                return ct.map_concat(d, t0, bd, tb, out_t), v & bv

            return run_mconcat
        if fn in ("map_filter", "transform_keys", "transform_values"):
            return self._compile_map_lambda(expr, arg0, t0)
        if fn == "zip_with":
            return self._compile_zip_with(expr)
        if fn == "split":
            return self._compile_split(expr)
        if fn == "reduce":
            return self._compile_reduce(expr)
        if fn == "slice":
            start_e, len_e = expr.args[1], expr.args[2]
            if not (isinstance(start_e, Literal) and isinstance(len_e, Literal)):
                raise ValueError("slice() start/length must be literals")
            start = int(start_e.value)
            ln = int(len_e.value)

            def run_slice(page):
                d, v = arg0(page)
                return ct.slice_array(d, t0, start, ln), v

            return run_slice
        raise KeyError(fn)

    def _compile_map_lambda(self, expr: Call, m_f, t0: Type) -> CompiledExpr:
        """Two-parameter lambdas over map entries (MapFilterFunction /
        MapTransformKey/ValueFunction): both entry halves flatten into
        TWO appended virtual channels and the body evaluates once over
        the entry lanes — the array-lambda design with a (k, v) pair."""
        from presto_tpu.ops import container as ct
        from presto_tpu.page import Block as _Block, Page as _Page

        fn = expr.fn
        lam = expr.args[1]
        body = lam.body
        k_slot, v_slot = lam.params[0].slot, lam.params[1].slot
        out_t = expr.type
        M = t0.max_elems
        kt, vt = t0.key_element, t0.element

        def run(page):
            d, v = m_f(page)
            ks = ct.map_key_slots(d, t0)
            vs = ct.map_value_slots(d, t0)
            live = ct.slot_mask(d, M)
            k_ok = live & ~ct.elem_null_mask(ks)
            v_ok = live & ~ct.elem_null_mask(vs)
            cap = page.capacity
            rep_blocks = tuple(
                _Block(jnp.repeat(b.data, M, axis=0), jnp.repeat(b.valid, M),
                       b.type, b.dictionary)
                for b in page.blocks)
            lam_k = _Block(ks.reshape(cap * M).astype(kt.np_dtype),
                           k_ok.reshape(cap * M), kt)
            lam_v = _Block(vs.reshape(cap * M).astype(vt.np_dtype),
                           v_ok.reshape(cap * M), vt)
            epage = _Page(rep_blocks + (lam_k, lam_v),
                          jnp.repeat(page.row_mask, M))
            nb = len(page.blocks)
            body2 = _subst_lambda_vars(body, {k_slot: nb, v_slot: nb + 1})
            bd, bv = ExprCompiler.for_page(epage).compile(body2)(epage)
            bd2 = bd.reshape(cap, M)
            bv2 = bv.reshape(cap, M)
            storage = out_t.np_dtype
            sent = ct._null_const(storage)
            n_live = ct.lengths(d)
            if fn == "map_filter":
                keep = live & bv2 & bd2.astype(jnp.bool_)
                return ct.compact_entry_pairs(ks, vs, keep, M, storage), v
            if fn == "transform_values":
                newv = jnp.where(live & bv2, bd2.astype(storage), sent)
                out = jnp.concatenate(
                    [n_live[:, None].astype(storage),
                     ks.astype(storage), newv], axis=1)
                return out, v
            # transform_keys: entries whose new key is NULL drop, and
            # duplicate new keys keep the FIRST entry (deviations: the
            # reference raises on both — deduping keeps device lookups
            # and host decodes agreeing)
            newk = bd2.astype(storage)
            keep0 = live & bv2
            eq = newk[:, :, None] == newk[:, None, :]
            earlier = jnp.triu(jnp.ones((M, M), jnp.bool_), 1)  # [i, j] = i<j
            dup = jnp.any(eq & keep0[:, :, None] & earlier[None], axis=1)
            keep = keep0 & ~dup
            return ct.compact_entry_pairs(newk, vs, keep, M, storage), v

        return run

    def _compile_zip_with(self, expr: Call) -> CompiledExpr:
        """zip_with(a1, a2, (x, y) -> body): lanes align by index, the
        shorter array's missing lanes bind NULL (ZipWithFunction), and
        the body evaluates once over max-capacity flattened lanes."""
        from presto_tpu.ops import container as ct
        from presto_tpu.page import Block as _Block, Page as _Page

        a1_f = self.compile(expr.args[0])
        a2_f = self.compile(expr.args[1])
        t1, t2 = expr.args[0].type, expr.args[1].type
        lam = expr.args[2]
        body = lam.body
        x_slot, y_slot = lam.params[0].slot, lam.params[1].slot
        out_t = expr.type
        M = out_t.max_elems

        def pad_slots(slots, m):
            if m >= M:
                return slots[:, :M]
            pad = jnp.full((slots.shape[0], M - m),
                           ct._null_const(slots.dtype), slots.dtype)
            return jnp.concatenate([slots, pad], axis=1)

        def run(page):
            (d1, v1), (d2, v2) = a1_f(page), a2_f(page)
            s1 = pad_slots(ct.elem_slots(d1, t1), t1.max_elems)
            s2 = pad_slots(ct.elem_slots(d2, t2), t2.max_elems)
            l1, l2 = ct.lengths(d1), ct.lengths(d2)
            j = jnp.arange(M)[None, :]
            x_ok = (j < l1[:, None]) & ~ct.elem_null_mask(s1)
            y_ok = (j < l2[:, None]) & ~ct.elem_null_mask(s2)
            lout = jnp.maximum(l1, l2)
            live = j < lout[:, None]
            cap = page.capacity
            rep_blocks = tuple(
                _Block(jnp.repeat(b.data, M, axis=0), jnp.repeat(b.valid, M),
                       b.type, b.dictionary)
                for b in page.blocks)
            lam_x = _Block(s1.reshape(cap * M).astype(t1.element.np_dtype),
                           x_ok.reshape(cap * M), t1.element)
            lam_y = _Block(s2.reshape(cap * M).astype(t2.element.np_dtype),
                           y_ok.reshape(cap * M), t2.element)
            epage = _Page(rep_blocks + (lam_x, lam_y),
                          jnp.repeat(page.row_mask, M))
            nb = len(page.blocks)
            body2 = _subst_lambda_vars(body, {x_slot: nb, y_slot: nb + 1})
            bd, bv = ExprCompiler.for_page(epage).compile(body2)(epage)
            storage = out_t.np_dtype
            sent = ct._null_const(storage)
            vals = jnp.where(live & bv.reshape(cap, M),
                             bd.reshape(cap, M).astype(storage), sent)
            out = jnp.concatenate(
                [lout[:, None].astype(storage), vals], axis=1)
            return out, v1 & v2

        return run

    def _compile_reduce(self, expr: Call) -> CompiledExpr:
        """reduce(arr, init, (s, x) -> comb, s -> out): the combiner
        unrolls over the static slot capacity — M body evaluations over
        full columns, XLA-fused; NULL elements bind as NULL
        (ReduceFunction)."""
        from presto_tpu.ops import container as ct
        from presto_tpu.page import Block as _Block, Page as _Page

        arr_f = self.compile(expr.args[0])
        init_f = self.compile(expr.args[1])
        t0 = expr.args[0].type
        st = expr.args[1].type
        comb_lam, out_lam = expr.args[2], expr.args[3]
        comb, out_body = comb_lam.body, out_lam.body
        s_slot, x_slot = comb_lam.params[0].slot, comb_lam.params[1].slot
        o_slot = out_lam.params[0].slot
        out_t = expr.type
        M = t0.max_elems

        def run(page):
            d, v = arr_f(page)
            sd, sv = init_f(page)
            sd = jnp.broadcast_to(sd, (page.capacity,)).astype(st.np_dtype)
            sv = jnp.broadcast_to(sv, (page.capacity,))
            slots = ct.elem_slots(d, t0)
            live = ct.slot_mask(d, M)
            nulls = ct.elem_null_mask(slots)
            nb = len(page.blocks)
            for i in range(M):
                elem = _Block(slots[:, i].astype(t0.element.np_dtype),
                              live[:, i] & ~nulls[:, i], t0.element)
                state = _Block(sd, sv, st)
                epage = _Page(page.blocks + (state, elem), page.row_mask)
                body2 = _subst_lambda_vars(comb, {s_slot: nb, x_slot: nb + 1})
                bd, bv = ExprCompiler.for_page(epage).compile(body2)(epage)
                has = live[:, i]
                sd = jnp.where(has, bd.astype(st.np_dtype), sd)
                sv = jnp.where(has, bv, sv)
            state = _Block(sd, sv, st)
            epage = _Page(page.blocks + (state,), page.row_mask)
            body3 = _subst_lambda_vars(out_body, {o_slot: nb})
            od, ov = ExprCompiler.for_page(epage).compile(body3)(epage)
            return od.astype(out_t.np_dtype), v & ov

        return run

    def _compile_array_lambda(self, expr: Call, arr_f, t0: Type) -> CompiledExpr:
        """Lambda functions over arrays (LambdaBytecodeGenerator +
        ArrayTransformFunction/ArrayFilterFunction analogs): the body
        evaluates ONCE over the flattened element lanes — rows repeat M
        times so outer-column references broadcast, and the lambda
        variable becomes an appended virtual channel.  Shapes stay
        static; XLA fuses the whole thing."""
        from presto_tpu.expr.ir import LambdaVar
        from presto_tpu.ops import container as ct
        from presto_tpu.page import Block as _Block, Page as _Page

        fn = expr.fn
        lam = expr.args[1]
        body, lam_slot = lam.body, lam.params[0].slot
        out_t = expr.type
        M = t0.max_elems
        elem_t = t0.element

        def substitute(e, var_index):
            return _subst_lambda_vars(e, {lam_slot: var_index})

        def run(page):
            d, v = arr_f(page)
            slots = ct.elem_slots(d, t0)
            live = ct.slot_mask(d, M)
            elem_ok = live & ~ct.elem_null_mask(slots)
            cap = page.capacity
            flat = slots.reshape(cap * M).astype(elem_t.np_dtype)
            rep_blocks = tuple(
                _Block(jnp.repeat(b.data, M, axis=0), jnp.repeat(b.valid, M),
                       b.type, b.dictionary)
                for b in page.blocks
            )
            lam = _Block(flat, elem_ok.reshape(cap * M), elem_t)
            epage = _Page(rep_blocks + (lam,), jnp.repeat(page.row_mask, M))
            body2 = substitute(body, len(page.blocks))
            bd, bv = ExprCompiler.for_page(epage).compile(body2)(epage)
            bd2 = bd.reshape(cap, M)
            bv2 = bv.reshape(cap, M)
            n_live = ct.lengths(d)

            if fn == "array_transform":
                storage = out_t.np_dtype
                sent = ct._null_const(storage)
                vals = jnp.where(live & bv2, bd2.astype(storage), sent)
                out = jnp.concatenate(
                    [n_live[:, None].astype(storage), vals], axis=1)
                return out, v
            if fn == "array_filter":
                keep = live & bv2 & bd2.astype(jnp.bool_)
                order = jnp.argsort(~keep, axis=1, stable=True)
                comp = jnp.take_along_axis(slots, order, axis=1)
                nkeep = jnp.sum(keep.astype(jnp.int64), axis=1)
                j = jnp.arange(M)[None, :]
                storage = t0.np_dtype
                sent = ct._null_const(storage)
                out_vals = jnp.where(j < nkeep[:, None], comp, sent)
                out = jnp.concatenate(
                    [nkeep[:, None].astype(storage), out_vals], axis=1)
                return out, v
            hit = live & bv2 & bd2.astype(jnp.bool_)
            if fn == "any_match":
                return jnp.any(hit, axis=1), v
            if fn == "none_match":
                return ~jnp.any(hit, axis=1), v
            # all_match: vacuously true on empty arrays; a null lambda
            # result counts false (deviation from 3-valued logic)
            ok = jnp.where(live, hit, True)
            return jnp.all(ok, axis=1), v

        return run

    def _compile_math(self, expr: Call) -> CompiledExpr:
        fn = expr.fn
        a = self.compile(expr.args[0])
        ta = expr.args[0].type

        if ta.is_long_decimal:
            from presto_tpu.ops import decimal128 as d128

            if fn == "abs":
                def run_labs(page):
                    d, v = a(page)
                    neg = d[..., 0] < 0
                    return _where_rows(neg, d128.neg(d), d), v

                return run_labs
            if fn == "sign":
                def run_lsign(page):
                    d, v = a(page)
                    hi = d[..., 0]
                    nonzero = jnp.any(d != 0, axis=-1)
                    s = jnp.where(hi < 0, -1,
                                  jnp.where(nonzero, 1, 0))
                    return s.astype(jnp.int64), v

                return run_lsign
            # silently-wrong elementwise limb math is worse than an error
            raise ValueError(f"{fn} on long decimals unsupported (cast first)")

        if fn in ("power", "pow", "atan2"):
            b = self.compile(expr.args[1])
            tb = expr.args[1].type
            op = jnp.power if fn in ("power", "pow") else jnp.arctan2

            def run_pow(page):
                (da, va), (db, vb) = a(page), b(page)
                return op(_to_double(da, ta), _to_double(db, tb)), va & vb

            return run_pow

        if fn == "width_bucket":
            args = [self.compile(x) for x in expr.args]
            ts = [x.type for x in expr.args]

            def run_wb(page):
                (x, vx), (lo, vlo), (hi, vhi), (n, vn) = [f(page) for f in args]
                xd = _to_double(x, ts[0])
                lod = _to_double(lo, ts[1])
                hid = _to_double(hi, ts[2])
                nb = n.astype(jnp.int64)
                frac = (xd - lod) / jnp.where(hid == lod, 1.0, hid - lod)
                b = jnp.floor(frac * nb.astype(jnp.float64)).astype(jnp.int64) + 1
                b = jnp.clip(b, 0, nb + 1)
                return b, vx & vlo & vhi & vn

            return run_wb

        if fn == "round" and len(expr.args) > 1:
            digits = expr.args[1].value
        else:
            digits = 0

        def run_math(page):
            da, va = a(page)
            if fn == "abs":
                if jnp.issubdtype(da.dtype, jnp.integer):
                    # |INT_MIN| wraps in place; NULL that lane
                    # (deviation: the reference raises)
                    va = va & jnp.logical_not(_ovf_neg(da))
                return jnp.abs(da), va
            if fn == "sign":
                return jnp.sign(_to_double(da, ta)).astype(jnp.int64), va
            if fn in _UNARY_DOUBLE_FNS:
                return _UNARY_DOUBLE_FNS[fn](_to_double(da, ta)), va
            if fn == "truncate":
                x = _to_double(da, ta)
                return jnp.trunc(x), va
            if fn in ("ceil", "ceiling", "floor"):
                up = fn in ("ceil", "ceiling")
                if ta.is_decimal:
                    # scaled-int ceil/floor: // floors for any sign
                    s = 10 ** ta.scale
                    q = (da + (s - 1)) // s if up else da // s
                    return q.astype(jnp.int64), va
                if ta.name == "double":
                    return (jnp.ceil(da) if up else jnp.floor(da)), va
                return da, va
            if fn == "round":
                if ta.is_decimal:
                    drop = ta.scale - min(digits, ta.scale)
                    if drop <= 0:
                        return da, va
                    p = 10 ** drop
                    half = p // 2
                    q = jnp.where(da >= 0, (da + half) // p, -((-da + half) // p))
                    return q, va
                if ta.name == "double":
                    m = 10.0 ** digits
                    x = da * m
                    r = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))
                    return r / m, va
                return da, va
            raise KeyError(fn)

        return run_math

    def _compile_operand(self, e: Expr, out_t: Type) -> CompiledExpr:
        """Compile an argument in the context of a raw-string result:
        dictionary-typed string literals encode to byte rows."""
        if out_t.is_raw_string and isinstance(e, Literal) and e.type.is_string \
                and not e.type.is_raw_string:
            from presto_tpu.ops import rawstring as rs

            width = out_t.value_shape[0]
            lit = rs.encode_literal(str(e.value), width)
            null = e.value is None

            def run_rawlit(page):
                n = page.capacity
                return (jnp.broadcast_to(lit[None, :], (n, width)),
                        jnp.zeros(n, jnp.bool_) if null else jnp.ones(n, jnp.bool_))

            return run_rawlit
        return self.compile(e)

    def _compile_bitwise(self, expr: Call) -> CompiledExpr:
        """Two's-complement bitwise scalars over int64 lanes
        (operator/scalar/BitwiseFunctions.java).  Shifts and bit_count
        take a literal `bits` width and operate on the value's low
        `bits` as an unsigned field (the reference's contract)."""
        fn = expr.fn
        fns = [self.compile(a) for a in expr.args
               if not (fn in ("bitwise_shift_left", "bitwise_shift_right",
                              "bit_count") and a is expr.args[-1])]
        bits = None
        if fn in ("bitwise_shift_left", "bitwise_shift_right", "bit_count"):
            blit = expr.args[-1]
            if not isinstance(blit, Literal) or blit.value is None:
                raise ValueError(f"{fn} bits must be a literal")
            bits = int(blit.value)
            if not 2 <= bits <= 64:
                raise ValueError(f"{fn} bits must be in [2, 64]")

        def run_bitwise(page):
            vals = [f(page) for f in fns]
            v = vals[0][1]
            for _, vv in vals[1:]:
                v = v & vv
            a = vals[0][0].astype(jnp.int64)
            if fn == "bitwise_not":
                return ~a, v
            if fn in ("bitwise_and", "bitwise_or", "bitwise_xor"):
                b = vals[1][0].astype(jnp.int64)
                out = {"bitwise_and": a & b, "bitwise_or": a | b,
                       "bitwise_xor": a ^ b}[fn]
                return out, v
            ua = a.astype(jnp.uint64)
            if bits < 64:
                ua = ua & jnp.uint64((1 << bits) - 1)
            if fn == "bit_count":
                return jax.lax.population_count(ua).astype(jnp.int64), v
            # Java shift semantics (the reference's engine): the shift
            # amount wraps mod 64, so shift 64 is a no-op and -1 acts
            # as 63 — mask, don't clamp
            s = (vals[1][0].astype(jnp.int64) & 63).astype(jnp.uint64)
            out = jnp.left_shift(ua, s) if fn == "bitwise_shift_left" \
                else jnp.right_shift(ua, s)
            if bits < 64:
                out = out & jnp.uint64((1 << bits) - 1)
            return out.astype(jnp.int64), v

        return run_bitwise

    def _compile_greatest_least(self, expr: Call) -> CompiledExpr:
        out_t = expr.type
        parts = [(self._compile_operand(x, out_t), x.type) for x in expr.args]
        take_max = expr.fn == "greatest"

        def run_gl(page):
            data = None
            valid = None
            for cf, t in parts:
                d, v = cf(page)
                d = self._coerce(d, t, out_t)
                if data is None:
                    data, valid = d, v
                elif out_t.is_long_decimal:
                    from presto_tpu.ops import decimal128 as d128

                    lt, _, _ = d128.compare(d, data)
                    take_d = ~lt if take_max else lt  # ties keep either
                    data = _where_rows(take_d, d, data)
                    valid = valid & v
                elif out_t.is_raw_string:
                    from presto_tpu.ops import rawstring as rs

                    lt, eq = rs.compare(d, data)
                    take_d = ~(lt | eq) if take_max else lt
                    data = _where_rows(take_d, d, data)
                    valid = valid & v
                else:
                    data = jnp.maximum(data, d) if take_max else jnp.minimum(data, d)
                    valid = valid & v  # NULL if any argument is NULL (Presto)
            return data, valid

        return run_gl

    # ------------------------------------------------------------------
    def _compile_literal(self, expr: Literal) -> CompiledExpr:
        t = expr.type
        if t.is_string and expr.value is not None:
            # projected constant: code 0 of the literal's singleton
            # dictionary (expr_dictionary supplies the mapping)
            def run_const_str(page):
                n = page.capacity
                return (jnp.zeros(n, dtype=jnp.int32),
                        jnp.ones(n, dtype=jnp.bool_))

            return run_const_str
        val = expr.value
        if val is None:

            def run_null(page):
                n = page.capacity
                return (
                    jnp.zeros((n,) + t.value_shape, dtype=t.np_dtype),
                    jnp.zeros(n, dtype=jnp.bool_),
                )

            return run_null

        if t.is_long_decimal:
            from presto_tpu.ops.decimal128 import encode_py

            limbs = encode_py([int(val)], 1,
                              limbs=expr.type.value_shape[0])[0]

            width = expr.type.value_shape[0]

            def run_llit(page):
                n = page.capacity
                return (
                    jnp.broadcast_to(jnp.asarray(limbs), (n, width)),
                    jnp.ones(n, dtype=jnp.bool_),
                )

            return run_llit

        def run_lit(page):
            n = page.capacity
            return (
                jnp.full(n, val, dtype=t.np_dtype),
                jnp.ones(n, dtype=jnp.bool_),
            )

        return run_lit

    def _compile_logic(self, expr: Call) -> CompiledExpr:
        a, b = [self.compile(x) for x in expr.args]
        is_and = expr.fn == "and"

        def run_logic(page):
            (da, va), (db, vb) = a(page), b(page)
            if is_and:
                # false AND anything = false; else null if any null
                false_a = va & jnp.logical_not(da)
                false_b = vb & jnp.logical_not(db)
                definite_false = false_a | false_b
                valid = (va & vb) | definite_false
                data = jnp.logical_not(definite_false) & da & db
            else:
                true_a = va & da
                true_b = vb & db
                definite_true = true_a | true_b
                valid = (va & vb) | definite_true
                data = definite_true | (da | db)
            return data, valid

        return run_logic

    def _string_code(self, column: Expr, s: str) -> int:
        d = self._dict_of(column)
        if d is None:
            raise ValueError(f"no dictionary for string column {column}")
        return d.code_of(s)

    def _dict_of(self, e: Expr) -> Optional[Dictionary]:
        return expr_dictionary(e, self.dictionaries)

    def _compile_cmp(self, expr: Call) -> CompiledExpr:
        lhs, rhs = expr.args
        # string comparison -> dictionary codes (eq/ne direct; ordered
        # comparisons use a host-side rank LUT since codes aren't sorted)
        if lhs.type.is_string or rhs.type.is_string:
            return self._compile_string_cmp(expr)
        a, b = self.compile(lhs), self.compile(rhs)
        ta, tb = lhs.type, rhs.type
        op = expr.fn

        if (ta.is_long_decimal or tb.is_long_decimal) \
                and "double" not in (ta.name, tb.name):
            # (a double operand compares in double space via _align_pair)
            from presto_tpu.ops import decimal128 as d128

            s = max(ta.scale if ta.is_decimal else 0, tb.scale if tb.is_decimal else 0)

            def run_lcmp(page):
                (da, va), (db, vb) = a(page), b(page)
                w = _decimal_limbs(ta, tb)
                la = _to_long_limbs(da, ta, ta.scale if ta.is_decimal else 0,
                                    s, limbs=w)
                lb = _to_long_limbs(db, tb, tb.scale if tb.is_decimal else 0,
                                    s, limbs=w)
                lt, eq, gt = d128.compare(la, lb)
                d = {"eq": eq, "ne": ~eq, "lt": lt, "le": lt | eq,
                     "gt": gt, "ge": gt | eq}[op]
                return d, va & vb

            return run_lcmp

        def run_cmp(page):
            (da, va), (db, vb) = a(page), b(page)
            da, db = self._align_pair(da, ta, db, tb)
            d = {
                "eq": lambda: da == db,
                "ne": lambda: da != db,
                "lt": lambda: da < db,
                "le": lambda: da <= db,
                "gt": lambda: da > db,
                "ge": lambda: da >= db,
            }[op]()
            return d, va & vb

        return run_cmp

    def _compile_string_cmp(self, expr: Call) -> CompiledExpr:
        lhs, rhs = expr.args
        op = expr.fn
        if lhs.type.is_raw_string or rhs.type.is_raw_string:
            return self._compile_raw_cmp(expr)
        if isinstance(rhs, Literal):
            colref, s = lhs, rhs.value
        elif isinstance(lhs, Literal):
            colref, s = rhs, lhs.value
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
        else:
            # col-col string compare: only eq/ne on same dictionary
            a, b = self.compile(lhs), self.compile(rhs)
            da_ = self._dict_of(lhs)
            db_ = self._dict_of(rhs)
            if da_ is None or db_ is None or op not in ("eq", "ne"):
                # ordered col-col comparison would need a merged
                # collation — unsupported, not silently wrong
                raise ValueError(
                    f"string column {op} comparison unsupported")
            # canonical-value-id comparison: both sides' codes map to a
            # shared value-id space host-side (the DictionaryBlock
            # id-remap analog). Robust to duplicate values in derived
            # dictionaries (upper/substr map many codes to one value).
            canon: dict = {}
            lut_a = jnp.asarray(
                [canon.setdefault(v, len(canon)) for v in da_.values],
                dtype=jnp.int32)
            lut_b = jnp.asarray(
                [canon.setdefault(v, len(canon)) for v in db_.values],
                dtype=jnp.int32)

            def run_cc(page):
                (da, va), (db, vb) = a(page), b(page)
                ca = lut_a[jnp.clip(da, 0, lut_a.shape[0] - 1)]
                cb = lut_b[jnp.clip(db, 0, lut_b.shape[0] - 1)]
                d = (ca == cb) if op == "eq" else (ca != cb)
                return d, va & vb

            return run_cc
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if op in ("eq", "ne"):
            # LUT, not code equality: derived dictionaries (substr) may
            # map many codes to the same value
            if d is None:
                raise ValueError(f"no dictionary for string column {colref}")
            want_eq = op == "eq"
            lut = jnp.asarray(d.lut(lambda v: (v == s) == want_eq))

            def run_eq(page):
                dd, v = cf(page)
                return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

            return run_eq
        # ordered: LUT of predicate over dictionary values
        import operator as _op

        cmpf = {"lt": _op.lt, "le": _op.le, "gt": _op.gt, "ge": _op.ge}[op]
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        lut = jnp.asarray(d.lut(lambda v: cmpf(v, s)))

        def run_ord(page):
            dd, v = cf(page)
            return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

        return run_ord

    # ------------------------------------------------------------------
    # raw (non-dictionary) varchar paths
    # ------------------------------------------------------------------

    def _compile_raw_cmp(self, expr: Call) -> CompiledExpr:
        from presto_tpu.ops import rawstring as rs

        lhs, rhs = expr.args
        op = expr.fn
        if isinstance(rhs, Literal):
            col, lit = lhs, rhs
        elif isinstance(lhs, Literal):
            col, lit = rhs, lhs
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
        else:
            if not (lhs.type.is_raw_string and rhs.type.is_raw_string):
                raise ValueError("raw-vs-dictionary string comparison unsupported")
            a, b = self.compile(lhs), self.compile(rhs)

            def run_rcc(page):
                (da, va), (db, vb) = a(page), b(page)
                lt, eq = rs.compare(da, db)
                d = {"eq": eq, "ne": ~eq, "lt": lt, "le": lt | eq,
                     "gt": ~(lt | eq), "ge": ~lt}[op]
                return d, va & vb

            return run_rcc
        cf = self.compile(col)
        width = col.type.value_shape[0]
        lit_bytes = rs.encode_literal(str(lit.value), max(width, len(str(lit.value).encode())))

        def run_rcl(page):
            d, v = cf(page)
            lt, eq = rs.compare(d, lit_bytes[None, :])
            out = {"eq": eq, "ne": ~eq, "lt": lt, "le": lt | eq,
                   "gt": ~(lt | eq), "ge": ~lt}[op]
            return out, v

        return run_rcl

    def _compile_raw_transform(self, expr: Call) -> CompiledExpr:
        """Value transforms on raw varchar: substr/upper/lower run on
        device; everything else reuses the host transform through a
        per-page callback."""
        from presto_tpu.ops import rawstring as rs

        fn = expr.fn
        col = _transform_column(expr)
        if col is None:
            raise KeyError(f"cannot compile string transform {expr}")
        cf = self.compile(col)
        if fn == "substr":
            start = int(expr.args[1].value)
            length = int(expr.args[2].value) if len(expr.args) > 2 else None
            return lambda page: ((lambda dv: (rs.substr_chars(dv[0], start, length), dv[1]))(cf(page)))
        if fn in ("upper", "lower"):
            up = fn == "upper"
            return lambda page: ((lambda dv: (rs.change_case(dv[0], up), dv[1]))(cf(page)))
        tf = _string_transform(expr)
        if tf is None:
            raise KeyError(f"cannot compile string transform {expr}")
        f, _ = tf
        width = expr.type.value_shape[0]

        def run_cb(page):
            d, v = cf(page)

            def cb(arr):
                vals = [f(s) for s in rs.decode_strings(arr)]
                data = rs.encode_strings(["" if x is None else x for x in vals], width)
                nulls = np.asarray([x is None for x in vals], dtype=np.bool_)
                return data, nulls

            out, nulls = jax.pure_callback(
                cb,
                (jax.ShapeDtypeStruct(d.shape[:-1] + (width,), jnp.uint8),
                 jax.ShapeDtypeStruct(d.shape[:-1], jnp.bool_)),
                d, vmap_method="sequential",
            )
            return out, v & ~nulls

        return run_cb

    def _compile_raw_bool(self, expr: Call) -> CompiledExpr:
        """LIKE/regexp_like/starts_with/ends_with on raw varchar via the
        host-predicate callback."""
        from presto_tpu.ops import rawstring as rs

        fn = expr.fn
        colref = expr.args[0]
        cf = self.compile(colref)
        if fn == "like":
            rx = _like_to_regex(expr.args[1].value)
            pred = lambda s: rx.match(s) is not None
        elif fn == "regexp_like":
            rx = re.compile(expr.args[1].value)
            pred = lambda s: rx.search(s) is not None
        elif fn == "starts_with":
            prefix = expr.args[1].value
            pred = lambda s: s.startswith(prefix)
        else:
            suffix = expr.args[1].value
            pred = lambda s: s.endswith(suffix)
        runner = rs.host_predicate(pred)

        def run_rb(page):
            d, v = cf(page)
            return runner(d), v

        return run_rb

    def _compile_raw_int_fn(self, expr: Call) -> CompiledExpr:
        from presto_tpu.ops import rawstring as rs

        fn = expr.fn
        cf = self.compile(expr.args[0])
        if fn == "length":
            # code points, matching the dictionary path (byte counts
            # diverge on non-ASCII; rs.lengths stays the internal
            # byte-level helper)
            runner_pred = len
        elif fn == "strpos":
            needle = expr.args[1].value
            runner_pred = lambda s: s.find(needle) + 1
        elif fn == "codepoint":
            runner_pred = lambda s: ord(s[0]) if s else 0
        else:
            raise KeyError(fn)

        def run_ri(page):
            d, v = cf(page)

            def cb(arr):
                return np.asarray([runner_pred(s) for s in rs.decode_strings(arr)],
                                  dtype=np.int64)

            out = jax.pure_callback(
                cb, jax.ShapeDtypeStruct(d.shape[:-1], jnp.int64), d,
                vmap_method="sequential",
            )
            return out, v

        return run_ri

    def _compile_raw_concat(self, expr: Call) -> CompiledExpr:
        from presto_tpu.ops import rawstring as rs

        parts = []
        for a in expr.args:
            if isinstance(a, Literal):
                lit = rs.encode_literal(str(a.value), len(str(a.value).encode()) or 1)
                parts.append(("lit", lit))
            elif a.type.is_raw_string:
                parts.append(("col", self.compile(a)))
            else:
                raise ValueError("concat mixes raw and dictionary strings")

        def run_rcat(page):
            data = None
            valid = None
            for kind, p in parts:
                if kind == "lit":
                    d = jnp.broadcast_to(p[None, :], (page.capacity, p.shape[0]))
                    v = jnp.ones(page.capacity, dtype=jnp.bool_)
                else:
                    d, v = p(page)
                if data is None:
                    data, valid = d, v
                else:
                    data = rs.concat(data, d)
                    valid = valid & v
            return data, valid

        return run_rcat

    def _compile_like(self, expr: Call) -> CompiledExpr:
        colref, pat = expr.args
        assert isinstance(pat, Literal), "LIKE pattern must be a literal"
        if colref.type.is_raw_string:
            return self._compile_raw_bool(expr)
        cf = self.compile(colref)
        d = self._dict_of(colref)
        if d is None:
            raise ValueError(f"no dictionary for string column {colref}")
        rx = _like_to_regex(pat.value)
        lut = jnp.asarray(d.lut(lambda v: rx.match(v) is not None))

        def run_like(page):
            dd, v = cf(page)
            return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

        return run_like

    def _compile_in(self, expr: Call) -> CompiledExpr:
        colref = expr.args[0]
        values = expr.args[1:]
        cf = self.compile(colref)
        if colref.type.is_raw_string:
            from presto_tpu.ops import rawstring as rs

            lits = [rs.encode_literal(
                str(v.value),
                max(colref.type.value_shape[0], len(str(v.value).encode())))
                for v in values]

            def run_in_raw(page):
                d, v = cf(page)
                hit = jnp.zeros(page.capacity, dtype=jnp.bool_)
                for lb in lits:
                    _, eq = rs.compare(d, lb[None, :])
                    hit = hit | eq
                return hit, v

            return run_in_raw
        if colref.type.is_string:
            d = self._dict_of(colref)
            if d is None:
                raise ValueError(f"no dictionary for string column {colref}")
            wanted = {v.value for v in values}
            lut = jnp.asarray(d.lut(lambda s: s in wanted))

            def run_in_str(page):
                dd, v = cf(page)
                return lut[jnp.clip(dd, 0, lut.shape[0] - 1)], v

            return run_in_str
        lits = [v.value for v in values]

        def run_in(page):
            dd, v = cf(page)
            hit = jnp.zeros(dd.shape, dtype=jnp.bool_)
            for c in lits:
                hit = hit | (dd == c)
            return hit, v

        return run_in

    def _compile_arith(self, expr: Call) -> CompiledExpr:
        lhs, rhs = expr.args
        a, b = self.compile(lhs), self.compile(rhs)
        ta, tb, tr = lhs.type, rhs.type, expr.type
        op = expr.fn

        def run_arith(page):
            (da, va), (db, vb) = a(page), b(page)
            valid = va & vb
            if tr.name == "real":
                da2 = _to_double(da, ta).astype(jnp.float32)
                db2 = _to_double(db, tb).astype(jnp.float32)
                d = {
                    "add": lambda: da2 + db2,
                    "sub": lambda: da2 - db2,
                    "mul": lambda: da2 * db2,
                    "div": lambda: da2 / jnp.where(db2 == 0, 1.0, db2),
                    "mod": lambda: jnp.mod(da2, jnp.where(db2 == 0, 1.0, db2)),
                }[op]()
                if op in ("div", "mod"):
                    valid = valid & (db2 != 0)
                return d, valid
            if tr.name == "double":
                da2, db2 = _to_double(da, ta), _to_double(db, tb)
                d = {
                    "add": lambda: da2 + db2,
                    "sub": lambda: da2 - db2,
                    "mul": lambda: da2 * db2,
                    "div": lambda: da2 / jnp.where(db2 == 0, 1.0, db2),
                    "mod": lambda: jnp.mod(da2, jnp.where(db2 == 0, 1.0, db2)),
                }[op]()
                if op in ("div", "mod"):
                    valid = valid & (db2 != 0)
                return d, valid
            if tr.is_long_decimal:
                from presto_tpu.ops import decimal128 as d128

                sa = ta.scale if ta.is_decimal else 0
                sb = tb.scale if tb.is_decimal else 0
                if op == "mul":
                    # long x short: exact (result scale = sa + sb);
                    # long x long products exceed p=36
                    if ta.is_long_decimal and not tb.is_long_decimal:
                        return d128.mul_long_short(da, db.astype(jnp.int64)), valid
                    if tb.is_long_decimal and not ta.is_long_decimal:
                        return d128.mul_long_short(db, da.astype(jnp.int64)), valid
                    raise ValueError("long-decimal x long-decimal mul unsupported")
                w = _decimal_limbs(ta, tb, tr)
                da2 = _to_long_limbs(da, ta, sa, tr.scale, limbs=w)
                db2 = _to_long_limbs(db, tb, sb, tr.scale, limbs=w)
                d = {
                    "add": lambda: d128.add(da2, db2),
                    "sub": lambda: d128.sub(da2, db2),
                }.get(op)
                if d is None:
                    raise ValueError(f"long-decimal {op} unsupported")
                return d(), valid
            if tr.is_decimal:
                sa = ta.scale if ta.is_decimal else 0
                sb = tb.scale if tb.is_decimal else 0
                da2 = da.astype(jnp.int64)
                db2 = db.astype(jnp.int64)
                if op == "mul":
                    d = da2 * db2  # scale sa+sb == tr.scale
                    valid = valid & jnp.logical_not(_ovf_mul(da2, db2, d))
                else:
                    da2, oa = _rescale_guard(da2, sa, tr.scale)
                    db2, ob = _rescale_guard(db2, sb, tr.scale)
                    valid = valid & jnp.logical_not(oa | ob)
                    d = {
                        "add": lambda: da2 + db2,
                        "sub": lambda: da2 - db2,
                        "mod": lambda: _trunc_mod(da2, db2),
                    }[op]()
                    if op == "add":
                        valid = valid & jnp.logical_not(_ovf_add(da2, db2, d))
                    elif op == "sub":
                        valid = valid & jnp.logical_not(_ovf_sub(da2, db2, d))
                    elif op == "mod":
                        valid = valid & (db2 != 0)
                return d, valid
            # integer arithmetic (SQL truncating div/mod); wrapped
            # add/sub/mul lanes NULL (deviation: reference raises)
            d = {
                "add": lambda: da + db,
                "sub": lambda: da - db,
                "mul": lambda: da * db,
                "div": lambda: _trunc_div(da, db),
                "mod": lambda: _trunc_mod(da, db),
            }[op]()
            if op == "add":
                valid = valid & jnp.logical_not(_ovf_add(da, db, d))
            elif op == "sub":
                valid = valid & jnp.logical_not(_ovf_sub(da, db, d))
            elif op == "mul":
                valid = valid & jnp.logical_not(_ovf_mul(da, db, d))
            elif op == "div":
                imin = jnp.iinfo(d.dtype).min
                valid = valid & (db != 0) \
                    & jnp.logical_not((da == imin) & (db == -1))
            elif op == "mod":
                valid = valid & (db != 0)
            return d, valid

        return run_arith

    def _compile_datepart(self, expr: Call) -> CompiledExpr:
        (a,) = [self.compile(x) for x in expr.args]
        part = expr.fn
        is_ts = expr.args[0].type.name == "timestamp"

        def run_datepart(page):
            d, v = a(page)
            if is_ts:
                micros = d.astype(jnp.int64)
                days = micros // MICROS_PER_DAY
                tod = micros - days * MICROS_PER_DAY
                if part in ("hour", "minute", "second", "millisecond"):
                    out = {
                        "hour": tod // 3_600_000_000,
                        "minute": (tod // 60_000_000) % 60,
                        "second": (tod // 1_000_000) % 60,
                        "millisecond": (tod // 1_000) % 1000,
                    }[part]
                    return out.astype(jnp.int64), v
            else:
                days = d.astype(jnp.int64)
                if part in ("hour", "minute", "second", "millisecond"):
                    return jnp.zeros_like(days), v
            y, m, day = _civil_from_days(days)
            if part in ("year", "month", "day"):
                out = {"year": y, "month": m, "day": day}[part]
            elif part == "quarter":
                out = (m - 1) // 3 + 1
            elif part == "day_of_week":
                # ISO: Monday=1..Sunday=7; 1970-01-01 was a Thursday
                out = (days + 3) % 7 + 1
            elif part == "day_of_year":
                jan1 = days - _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(day))
                out = jan1 + 1
            elif part in ("week", "year_of_week"):
                # ISO 8601: the week containing a date's Thursday
                # belongs to the Thursday's civil year (the reference's
                # Joda weekOfWeekyear/weekyear)
                th = days - (days + 3) % 7 + 3
                y_th, _, _ = _civil_from_days(th)
                if part == "year_of_week":
                    out = y_th
                else:
                    jan1 = _days_from_civil(
                        y_th, jnp.ones_like(m), jnp.ones_like(day))
                    out = (th - jan1) // 7 + 1
            elif part == "last_day_of_month":
                nxt_y = jnp.where(m == 12, y + 1, y)
                nxt_m = jnp.where(m == 12, 1, m + 1)
                out = _days_from_civil(nxt_y, nxt_m, jnp.ones_like(day)) - 1
            else:
                raise KeyError(part)
            return out.astype(jnp.int64), v

        return run_datepart

    def _compile_datetime(self, expr: Call) -> CompiledExpr:
        """Timestamp/date kernels (reference: operator/scalar/DateTimeFunctions.java;
        here vectorized integer civil-calendar math on device).

        Deviation from the reference's Joda-based date_diff('month'|'year'):
        this engine counts calendar-field differences ((y2*12+m2)-(y1*12+m1)),
        not complete elapsed periods."""
        fn = expr.fn

        if fn in ("date_trunc", "date_add", "date_diff"):
            unit_lit = expr.args[0]
            if not isinstance(unit_lit, Literal):
                raise KeyError(f"{fn}: unit must be a literal")
            unit = str(unit_lit.value).lower().rstrip("s")
            arg_fs = [self.compile(x) for x in expr.args[1:]]
            arg_ts = [x.type for x in expr.args[1:]]
            if fn == "date_trunc":
                return self._datetime_trunc(unit, arg_fs[0], arg_ts[0])
            if fn == "date_add":
                return self._datetime_add(unit, arg_fs[0], arg_fs[1], arg_ts[1])
            return self._datetime_diff(unit, arg_fs, arg_ts)

        (afn,) = [self.compile(x) for x in expr.args[:1]]
        t0 = expr.args[0].type
        if fn == "cast_timestamp":
            def run(page):
                d, v = afn(page)
                if t0.name == "date":
                    return d.astype(jnp.int64) * MICROS_PER_DAY, v
                return d.astype(jnp.int64), v
            return run
        if fn == "cast_date":
            def run(page):
                d, v = afn(page)
                if t0.name == "timestamp":
                    return (d.astype(jnp.int64) // MICROS_PER_DAY).astype(jnp.int32), v
                return d.astype(jnp.int32), v
            return run
        if fn == "to_unixtime":
            def run(page):
                d, v = afn(page)
                micros = d.astype(jnp.float64)
                if t0.name == "date":
                    micros = micros * MICROS_PER_DAY
                return micros / 1e6, v
            return run
        if fn == "from_unixtime":
            def run(page):
                d, v = afn(page)
                return (_to_double(d, t0) * 1e6).astype(jnp.int64), v
            return run
        if fn == "ts_add_micros":
            bfn = self.compile(expr.args[1])
            def run(page):
                (da, va), (db, vb) = afn(page), bfn(page)
                return da.astype(jnp.int64) + db.astype(jnp.int64), va & vb
            return run
        if fn in ("ts_add_months", "date_add_months"):
            bfn = self.compile(expr.args[1])
            if fn == "ts_add_months":
                def run(page):
                    (da, va), (db, vb) = afn(page), bfn(page)
                    micros = da.astype(jnp.int64)
                    days = micros // MICROS_PER_DAY
                    tod = micros - days * MICROS_PER_DAY
                    return _add_months(days, db) * MICROS_PER_DAY + tod, va & vb
            else:
                def run(page):
                    (da, va), (db, vb) = afn(page), bfn(page)
                    return _add_months(da.astype(jnp.int64), db).astype(jnp.int32), va & vb
            return run
        raise KeyError(fn)

    def _datetime_trunc(self, unit: str, f, t: Type) -> CompiledExpr:
        is_ts = t.name == "timestamp"

        def run_trunc(page):
            d, v = f(page)
            if is_ts:
                micros = d.astype(jnp.int64)
                step = {"second": 1_000_000, "minute": 60_000_000,
                        "hour": 3_600_000_000, "day": MICROS_PER_DAY}.get(unit)
                if step is not None:
                    return (micros // step) * step, v
                days = micros // MICROS_PER_DAY
            else:
                days = d.astype(jnp.int64)
                if unit in ("second", "minute", "hour", "day"):
                    return d, v
            y, m, _day = _civil_from_days(days)
            one = jnp.ones_like(m)
            if unit == "week":
                dow = (days + 3) % 7  # Monday=0
                out_days = days - dow
            elif unit == "month":
                out_days = _days_from_civil(y, m, one)
            elif unit == "quarter":
                qm = ((m - 1) // 3) * 3 + 1
                out_days = _days_from_civil(y, qm, one)
            elif unit == "year":
                out_days = _days_from_civil(y, one, one)
            else:
                raise KeyError(f"date_trunc unit {unit}")
            if is_ts:
                return out_days * MICROS_PER_DAY, v
            return out_days.astype(jnp.int32), v

        return run_trunc

    def _datetime_add(self, unit: str, nf, xf, t: Type) -> CompiledExpr:
        is_ts = t.name == "timestamp"
        micros_per = {"millisecond": 1_000, "second": 1_000_000,
                      "minute": 60_000_000, "hour": 3_600_000_000,
                      "day": MICROS_PER_DAY, "week": 7 * MICROS_PER_DAY}

        def run_add(page):
            (dn, vn), (dx, vx) = nf(page), xf(page)
            valid = vn & vx
            n = dn.astype(jnp.int64)
            if is_ts:
                micros = dx.astype(jnp.int64)
                if unit in micros_per:
                    return micros + n * micros_per[unit], valid
                days = micros // MICROS_PER_DAY
                tod = micros - days * MICROS_PER_DAY
                months = n * (12 if unit == "year" else 3 if unit == "quarter" else 1)
                return _add_months(days, months) * MICROS_PER_DAY + tod, valid
            days = dx.astype(jnp.int64)
            if unit == "day":
                return (days + n).astype(jnp.int32), valid
            if unit == "week":
                return (days + 7 * n).astype(jnp.int32), valid
            if unit in ("month", "quarter", "year"):
                months = n * (12 if unit == "year" else 3 if unit == "quarter" else 1)
                return _add_months(days, months).astype(jnp.int32), valid
            raise KeyError(f"date_add unit {unit} on date")

        return run_add

    def _datetime_diff(self, unit: str, fs, ts_) -> CompiledExpr:
        micros_per = {"millisecond": 1_000, "second": 1_000_000,
                      "minute": 60_000_000, "hour": 3_600_000_000,
                      "day": MICROS_PER_DAY, "week": 7 * MICROS_PER_DAY}

        def to_micros(d, t):
            d = d.astype(jnp.int64)
            return d * MICROS_PER_DAY if t.name == "date" else d

        def run_diff(page):
            (d1, v1), (d2, v2) = fs[0](page), fs[1](page)
            valid = v1 & v2
            m1, m2 = to_micros(d1, ts_[0]), to_micros(d2, ts_[1])
            if unit in micros_per:
                return _trunc_div(m2 - m1, jnp.asarray(micros_per[unit], jnp.int64)), valid
            y1, mo1, _ = _civil_from_days(m1 // MICROS_PER_DAY)
            y2, mo2, _ = _civil_from_days(m2 // MICROS_PER_DAY)
            months = (y2 * 12 + mo2) - (y1 * 12 + mo1)
            if unit == "month":
                out = months
            elif unit == "quarter":
                out = _trunc_div(months, jnp.asarray(3, months.dtype))
            elif unit == "year":
                out = y2 - y1
            else:
                raise KeyError(f"date_diff unit {unit}")
            return out.astype(jnp.int64), valid

        return run_diff

    def _is_dict_string_case(self, expr: Call) -> bool:
        t = expr.type
        return (getattr(t, "is_string", False)
                and not getattr(t, "is_raw_string", False))

    def _compile_string_case(self, expr: Call) -> CompiledExpr:
        """case/if/coalesce producing dictionary varchar: each branch's
        codes remap into the union dictionary (merged_string_dictionary
        — the channel metadata layer attaches the same object), so
        SELECT CASE ... THEN 'big' ELSE 'small' END decodes correctly
        instead of emitting branch-local code 0s."""
        merged = merged_string_dictionary(expr, self.dictionaries)
        if merged is None:
            raise ValueError(
                "string-valued case/if/coalesce branch has no resolvable "
                "dictionary")
        index = {v: i for i, v in enumerate(merged.values)}

        def branch_fn(b: Expr) -> CompiledExpr:
            if isinstance(b, Literal):
                code = index.get(b.value, 0)
                ok = b.value is not None

                def run_lit(page, code=code, ok=ok):
                    n = page.capacity
                    return (jnp.full(n, code, dtype=jnp.int32),
                            jnp.full(n, ok, dtype=jnp.bool_))

                return run_lit
            inner = self.compile(b)
            bdict = expr_dictionary(b, self.dictionaries)
            lut = jnp.asarray(
                [index.get(v, 0) for v in bdict.values], dtype=jnp.int32)

            def run_remap(page, inner=inner, lut=lut):
                d, v = inner(page)
                codes = jnp.clip(d.astype(jnp.int32), 0, lut.shape[0] - 1)
                return lut[codes], v

            return run_remap

        if expr.fn == "coalesce":
            parts = [branch_fn(b) for b in expr.args]

            def run_coalesce_s(page):
                data = valid = None
                for f in parts:
                    d, v = f(page)
                    if data is None:
                        data, valid = d, v
                    else:
                        data = _where_rows(jnp.logical_not(valid), d, data)
                        valid = valid | v
                return data, valid

            return run_coalesce_s

        if expr.fn == "if":
            c = self.compile(expr.args[0])
            t_f = branch_fn(expr.args[1])
            f_f = branch_fn(expr.args[2])

            def run_if_s(page):
                (dc, vc), (dt, vt), (df, vf) = c(page), t_f(page), f_f(page)
                cond = dc & vc
                return _where_rows(cond, dt, df), jnp.where(cond, vt, vf)

            return run_if_s

        # case: [when1, then1, ..., else]
        args = expr.args
        pairs = [(self.compile(args[i]), branch_fn(args[i + 1]))
                 for i in range(0, len(args) - 1, 2)]
        else_f = branch_fn(args[-1])

        def run_case_s(page):
            data, valid = else_f(page)
            taken = jnp.zeros(page.capacity, dtype=jnp.bool_)
            for wf, tf in pairs:
                wd, wv = wf(page)
                td, tv = tf(page)
                cond = wd & wv & jnp.logical_not(taken)
                data = _where_rows(cond, td, data)
                valid = jnp.where(cond, tv, valid)
                taken = taken | (wd & wv)
            return data, valid

        return run_case_s

    def _compile_case(self, expr: Call) -> CompiledExpr:
        # args = [when1, then1, when2, then2, ..., else]
        args = expr.args
        out_t = expr.type
        pairs = [(self.compile(args[i]),
                  self._compile_operand(args[i + 1], out_t), args[i + 1].type)
                 for i in range(0, len(args) - 1, 2)]
        else_f = self._compile_operand(args[-1], out_t)
        else_t = args[-1].type

        def run_case(page):
            data, valid = else_f(page)
            data = self._coerce(data, else_t, out_t)
            taken = jnp.zeros(page.capacity, dtype=jnp.bool_)
            for wf, tf, tt in pairs:
                (wd, wv) = wf(page)
                (td, tv) = tf(page)
                td = self._coerce(td, tt, out_t)
                cond = wd & wv & jnp.logical_not(taken)
                data = _where_rows(cond, td, data)
                valid = jnp.where(cond, tv, valid)
                taken = taken | (wd & wv)
            return data, valid

        return run_case

    # ------------------------------------------------------------------
    def _align_pair(self, da, ta: Type, db, tb: Type):
        """Coerce a comparison pair to a common representation."""
        if ta.name == "double" or tb.name == "double":
            return _to_double(da, ta), _to_double(db, tb)
        if ta.name == "real" or tb.name == "real":
            # REAL op decimal/integer runs in float32 (REAL result type)
            return (_to_double(da, ta).astype(jnp.float32),
                    _to_double(db, tb).astype(jnp.float32))
        if {ta.name, tb.name} == {"date", "timestamp"}:
            if ta.name == "date":
                return da.astype(jnp.int64) * MICROS_PER_DAY, db
            return da, db.astype(jnp.int64) * MICROS_PER_DAY
        if ta.is_decimal or tb.is_decimal:
            sa = ta.scale if ta.is_decimal else 0
            sb = tb.scale if tb.is_decimal else 0
            s = max(sa, sb)
            return _rescale(da.astype(jnp.int64), sa, s), _rescale(
                db.astype(jnp.int64), sb, s
            )
        return da, db

    def _coerce(self, data, from_t: Type, to_t: Type):
        if from_t == to_t:
            return data
        if to_t.name == "timestamp" and from_t.name == "date":
            return data.astype(jnp.int64) * MICROS_PER_DAY
        if to_t.name == "double":
            return _to_double(data, from_t)
        if to_t.is_long_decimal:
            fs = from_t.scale if from_t.is_decimal else 0
            return _to_long_limbs(data, from_t, fs, to_t.scale,
                                  limbs=to_t.value_shape[0])
        if to_t.is_decimal:
            if from_t.is_long_decimal:
                from presto_tpu.ops import decimal128 as d128

                limbs = d128.rescale(data, from_t.scale, to_t.scale)
                return _narrow_to_int64(limbs)
            fs = from_t.scale if from_t.is_decimal else 0
            return _rescale(data.astype(jnp.int64), fs, to_t.scale)
        if to_t.name == "bigint":
            if from_t.is_long_decimal:
                from presto_tpu.ops import decimal128 as d128

                limbs = d128.rescale(data, from_t.scale or 0, 0)
                return _narrow_to_int64(limbs)  # exact in range
            return data.astype(jnp.int64)
        return data


def _narrow_to_int64(limbs: jax.Array) -> jax.Array:
    """Collapse limb vectors to a single int64 (exact only when the
    value fits — same contract as the reference's narrowing casts)."""
    from presto_tpu.ops import decimal128 as d128

    if limbs.shape[-1] == 2:
        return limbs[..., 0] * d128.BASE + limbs[..., 1]
    acc = limbs[..., 0]
    for i in range(1, limbs.shape[-1]):
        acc = acc * d128._B9 + limbs[..., i]
    return acc


def _unwrap_geomtext(e: Expr) -> Expr:
    """ST_GeometryFromText is representation-transparent (WKT in, WKT
    out): peel it so accessors see the underlying literal/column."""
    while isinstance(e, Call) and e.fn == "st_geometryfromtext":
        e = e.args[0]
    return e


def _civil_from_days(z: jax.Array):
    """Epoch days -> (year, month, day). Howard Hinnant's public-domain
    civil_from_days algorithm, integer-only so it vectorizes on TPU."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _add_months(days: jax.Array, n: jax.Array) -> jax.Array:
    """Shift epoch days by n calendar months, clamping the day-of-month
    (2020-01-31 + 1 month = 2020-02-29)."""
    # built per-trace (a cached jnp constant would leak tracers); XLA
    # constant-folds it.
    month_len = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                            dtype=jnp.int64)
    y, m, d = _civil_from_days(days)
    months = y * 12 + (m - 1) + n.astype(y.dtype)
    y2 = months // 12
    m2 = months % 12 + 1
    leap = (y2 % 4 == 0) & ((y2 % 100 != 0) | (y2 % 400 == 0))
    mlen = month_len[m2 - 1] + ((m2 == 2) & leap)
    d2 = jnp.minimum(d, mlen)
    return _days_from_civil(y2, m2, d2)


def _days_from_civil(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    """(year, month, day) -> epoch days (inverse of _civil_from_days,
    same public-domain algorithm)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# -- module-level helpers ----------------------------------------------------

def compile_expr(expr: Expr, page_or_types, dictionaries=None) -> CompiledExpr:
    if isinstance(page_or_types, Page):
        c = ExprCompiler.for_page(page_or_types)
    else:
        c = ExprCompiler(page_or_types, dictionaries or [None] * len(page_or_types))
    return c.compile(expr)


def compile_filter(expr: Expr, page_or_types, dictionaries=None):
    """Compile a predicate to ``page -> bool mask`` (NULL -> excluded),
    the PageFilter analog."""
    f = compile_expr(expr, page_or_types, dictionaries)

    def run(page: Page) -> jax.Array:
        d, v = f(page)
        return d & v & page.row_mask

    return run
