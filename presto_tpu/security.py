"""Access control.

Reference analog: ``security/AccessControlManager.java`` +
``FileBasedSystemAccessControl`` (rule-list policies) and the
ConnectorAccessControl SPI.  Checks run against the tables a plan
actually touches, before execution.
"""

from __future__ import annotations

import fnmatch
import re
from typing import List, Optional, Tuple


class AccessDeniedError(Exception):
    def __init__(self, user: str, action: str, table: str):
        super().__init__(f"Access Denied: user {user} cannot {action} table {table}")
        self.user = user
        self.action = action
        self.table = table


class AccessControl:
    """Default: allow everything (AllowAllAccessControl)."""

    def check_can_select(self, user: str, table: str) -> None:
        pass

    def check_can_write(self, user: str, table: str) -> None:
        pass

    # per-operation refinements default to the coarse write check
    def check_can_insert(self, user: str, table: str) -> None:
        self.check_can_write(user, table)

    def check_can_delete(self, user: str, table: str) -> None:
        self.check_can_write(user, table)


class GrantingAccessControl(AccessControl):
    """Mutable grants table driven by SQL GRANT/REVOKE (the
    AccessControlManager grant surface + ConnectorAccessControl's
    grantTablePrivileges role).  ``admins`` keep every privilege;
    everyone else needs an explicit grant per table."""

    def __init__(self, admins=("presto",)):
        self.admins = set(admins)
        self.grants: dict = {}  # (user, table) -> set of privileges

    def grant(self, grantee: str, table: str, privileges) -> None:
        self.grants.setdefault((grantee, table), set()).update(privileges)

    def revoke(self, grantee: str, table: str, privileges) -> None:
        s = self.grants.get((grantee, table))
        if s is not None:
            s.difference_update(privileges)

    def _has(self, user: str, table: str, priv: str) -> bool:
        if user in self.admins:
            return True
        return priv in self.grants.get((user, table), ())

    def check_can_grant(self, user: str) -> None:
        if user not in self.admins:
            raise AccessDeniedError(user, "grant privileges on", "*")

    def check_can_select(self, user: str, table: str) -> None:
        if not self._has(user, table, "select"):
            raise AccessDeniedError(user, "select from", table)

    def check_can_insert(self, user: str, table: str) -> None:
        if not self._has(user, table, "insert"):
            raise AccessDeniedError(user, "insert into", table)

    def check_can_delete(self, user: str, table: str) -> None:
        if not self._has(user, table, "delete"):
            raise AccessDeniedError(user, "delete from", table)

    def check_can_write(self, user: str, table: str) -> None:
        # coarse check (CTAS/rename/drop): any write privilege
        if not (self._has(user, table, "insert")
                or self._has(user, table, "delete")):
            raise AccessDeniedError(user, "write to", table)


class RuleBasedAccessControl(AccessControl):
    """First-match rule list: (user glob, table glob, allow, writable)
    — the file-based system access control's model."""

    def __init__(self, rules: List[Tuple[str, str, bool, bool]]):
        self.rules = rules

    def _find(self, user: str, table: str) -> Optional[Tuple[bool, bool]]:
        for user_pat, table_pat, allow, writable in self.rules:
            if fnmatch.fnmatch(user, user_pat) and fnmatch.fnmatch(table, table_pat):
                return allow, writable
        return None

    def check_can_select(self, user: str, table: str) -> None:
        hit = self._find(user, table)
        if hit is None or not hit[0]:
            raise AccessDeniedError(user, "select from", table)

    def check_can_write(self, user: str, table: str) -> None:
        hit = self._find(user, table)
        if hit is None or not hit[0] or not hit[1]:
            raise AccessDeniedError(user, "write to", table)


def scan_tables(plan) -> List[str]:
    """All table names a plan reads."""
    from presto_tpu.planner.plan import TableScanNode

    out: List[str] = []

    def walk(node):
        if isinstance(node, TableScanNode):
            out.append(node.handle.table)
        for s in node.sources:
            walk(s)

    walk(plan)
    return out


# ---------------------------------------------------------------------------
# authentication (server/security/ + presto-password-authenticators)
# ---------------------------------------------------------------------------

class AuthenticationError(Exception):
    pass


class PasswordAuthenticator:
    """SPI: authenticate(user, password) -> None or raise
    (spi/security/PasswordAuthenticator.java)."""

    def authenticate(self, user: str, password: str) -> None:  # pragma: no cover
        raise NotImplementedError


class FilePasswordAuthenticator(PasswordAuthenticator):
    """user:salted-sha256 lines (the file password authenticator's
    model; htpasswd-style)."""

    def __init__(self, entries=None, path: str = None):
        import hashlib

        self._hash = hashlib.sha256
        self.users = {}
        if path is not None:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        user, salted = line.split(":", 1)
                        salt, digest = salted.split("$", 1)
                        self.users[user] = (salt, digest)
        for user, password in (entries or {}).items():
            salt = "s0"
            self.users[user] = (salt, self._digest(salt, password))

    def _digest(self, salt: str, password: str) -> str:
        return self._hash((salt + password).encode()).hexdigest()

    def authenticate(self, user: str, password: str) -> None:
        got = self.users.get(user)
        if got is None or self._digest(got[0], password) != got[1]:
            raise AuthenticationError(f"invalid credentials for {user}")


class TokenAuthenticator:
    """HMAC-signed ticket authentication — the second mechanism slot
    the reference fills with Kerberos
    (server/security/KerberosAuthenticator.java: the coordinator
    verifies a ticket issued by a trusted authority; here the authority
    is a shared-secret HMAC signer, the infrastructure-free analog).

    Ticket format: ``user.expiry_epoch.hex(hmac_sha256(secret,
    user.expiry))`` — self-describing, stateless verification."""

    def __init__(self, secret: str):
        self._secret = secret.encode()

    def _sig(self, payload: str) -> str:
        import hashlib
        import hmac

        return hmac.new(self._secret, payload.encode(),
                        hashlib.sha256).hexdigest()

    def issue(self, user: str, ttl_seconds: int = 3600) -> str:
        import time

        # epoch arithmetic by design: the exp claim is wall-clock time
        exp = int(time.time()) + ttl_seconds
        payload = f"{user}.{exp}"
        return f"{payload}.{self._sig(payload)}"

    def authenticate_token(self, token: str) -> str:
        """Returns the authenticated user, or raises."""
        import hmac as _hmac
        import time

        parts = token.rsplit(".", 2)
        if len(parts) != 3:
            raise AuthenticationError("malformed token")
        user, exp_s, sig = parts
        if not _hmac.compare_digest(sig, self._sig(f"{user}.{exp_s}")):
            raise AuthenticationError("bad token signature")
        try:
            exp = int(exp_s)
        except ValueError:
            raise AuthenticationError("malformed token expiry")
        if exp < time.time():
            raise AuthenticationError("token expired")
        return user


class AuthenticatorChain:
    """Ordered authentication mechanisms; the first that accepts wins
    (the reference's http-server.authentication.type=password,kerberos
    list semantics).  Password mechanisms serve the Basic leg, token
    mechanisms the Bearer leg."""

    def __init__(self, *mechanisms):
        self.mechanisms = list(mechanisms)

    def authenticate(self, user: str, password: str) -> None:
        last: Exception = AuthenticationError("no password mechanism")
        for m in self.mechanisms:
            if hasattr(m, "authenticate"):
                try:
                    return m.authenticate(user, password)
                except AuthenticationError as e:
                    last = e
        raise last

    def authenticate_token(self, token: str) -> str:
        last: Exception = AuthenticationError("no token mechanism")
        for m in self.mechanisms:
            if hasattr(m, "authenticate_token"):
                try:
                    return m.authenticate_token(token)
                except AuthenticationError as e:
                    last = e
        raise last


def parse_bearer_auth(header: str):
    """'Bearer <token>' -> token or None."""
    if not header.startswith("Bearer "):
        return None
    return header[len("Bearer "):].strip() or None


def parse_basic_auth(header: str):
    """'Basic base64(user:pass)' -> (user, password) or None."""
    import base64

    if not header.startswith("Basic "):
        return None
    try:
        raw = base64.b64decode(header[len("Basic "):]).decode()
        user, _, password = raw.partition(":")
        return user, password
    except Exception:
        return None
