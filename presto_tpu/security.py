"""Access control.

Reference analog: ``security/AccessControlManager.java`` +
``FileBasedSystemAccessControl`` (rule-list policies) and the
ConnectorAccessControl SPI.  Checks run against the tables a plan
actually touches, before execution.
"""

from __future__ import annotations

import fnmatch
import re
from typing import List, Optional, Tuple


class AccessDeniedError(Exception):
    def __init__(self, user: str, action: str, table: str):
        super().__init__(f"Access Denied: user {user} cannot {action} table {table}")
        self.user = user
        self.action = action
        self.table = table


class AccessControl:
    """Default: allow everything (AllowAllAccessControl)."""

    def check_can_select(self, user: str, table: str) -> None:
        pass

    def check_can_write(self, user: str, table: str) -> None:
        pass


class RuleBasedAccessControl(AccessControl):
    """First-match rule list: (user glob, table glob, allow, writable)
    — the file-based system access control's model."""

    def __init__(self, rules: List[Tuple[str, str, bool, bool]]):
        self.rules = rules

    def _find(self, user: str, table: str) -> Optional[Tuple[bool, bool]]:
        for user_pat, table_pat, allow, writable in self.rules:
            if fnmatch.fnmatch(user, user_pat) and fnmatch.fnmatch(table, table_pat):
                return allow, writable
        return None

    def check_can_select(self, user: str, table: str) -> None:
        hit = self._find(user, table)
        if hit is None or not hit[0]:
            raise AccessDeniedError(user, "select from", table)

    def check_can_write(self, user: str, table: str) -> None:
        hit = self._find(user, table)
        if hit is None or not hit[0] or not hit[1]:
            raise AccessDeniedError(user, "write to", table)


def scan_tables(plan) -> List[str]:
    """All table names a plan reads."""
    from presto_tpu.planner.plan import TableScanNode

    out: List[str] = []

    def walk(node):
        if isinstance(node, TableScanNode):
            out.append(node.handle.table)
        for s in node.sources:
            walk(s)

    walk(plan)
    return out
