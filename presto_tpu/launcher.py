"""Server launcher: start a coordinator or worker from an etc/ directory.

Reference analog: ``presto-server``'s bin/launcher + PrestoServer.java
bootstrap (role selection via config.properties ``coordinator=true``,
catalogs from etc/catalog/*.properties).  Usage:

  python -m presto_tpu.launcher run --etc etc/            # foreground
  python -m presto_tpu.launcher run --etc etc/ --port 8080

A coordinator serves the V1 statement protocol (server/coordinator.py);
a worker serves the task protocol (server/worker.py).  Workers register
with the coordinator via ``discovery.uri`` the way reference workers
announce to airlift discovery.
"""

from __future__ import annotations

import argparse
import signal
import sys


def build_from_etc(etc_dir: str, port: int = 0):
    from presto_tpu.config import EngineConfig
    from presto_tpu.runner import QueryRunner

    cfg = EngineConfig.from_etc(etc_dir)
    catalog = cfg.build_catalog()
    # persistent XLA program cache: a restarted coordinator/worker
    # rehydrates compiled query programs from disk instead of paying
    # the cold-start compile tax again (exec/programs.py)
    from presto_tpu.exec.programs import maybe_enable_persistent_cache

    maybe_enable_persistent_cache(cfg)
    # observability wiring: query.trace-dir turns tracing on and drops
    # one Chrome-trace JSON per query; query.log-path attaches the
    # JSONL query-log EventListener (docs/observability.md)
    from presto_tpu import obs

    obs.maybe_enable_trace_dir(cfg)
    # deterministic fault injection (testing_faults.py): inert unless
    # the PRESTO_TPU_FAULTS/_FAULT_SEED env pair arms it — the chaos
    # legs' entry point, a no-op in production
    from presto_tpu.testing_faults import arm_from_env

    arm_from_env()
    port = port or cfg.int("http-server.http.port", 0)
    # serving-tier cache budget (query.result-cache-bytes overrides
    # the PRESTO_TPU_RESULT_CACHE_BYTES / 64 MiB process default)
    from presto_tpu.serving.cache import set_result_cache_bytes

    set_result_cache_bytes(cfg.result_cache_bytes(0))
    if cfg.bool("coordinator", True):
        from presto_tpu.server.coordinator import CoordinatorServer

        runner = QueryRunner(catalog, session=cfg.build_session())
        log_path = cfg.query_log_path()
        if log_path:
            runner.events.add(obs.QueryLogListener(log_path))
        # coordinator.worker-uris (comma-separated) feeds the worker
        # plane: the failure detector's heartbeats, /v1/worker +
        # system_runtime_workers + the web-UI worker list, the memory
        # manager's remote polls and system_metrics' per-node rows —
        # without it a launcher-built coordinator has no fleet to watch
        worker_uris = [u.strip()
                       for u in cfg.str("coordinator.worker-uris",
                                        "").split(",") if u.strip()]
        server = CoordinatorServer(
            runner, port=port, worker_uris=worker_uris,
            # query.max-execution-time / query.max-queued-time: the
            # deadline plane (docs/fault-tolerance.md; the deadline is
            # opt-in, the queue bound replaces the hard-coded 600s)
            max_execution_time=cfg.max_execution_time(),
            max_queued_time=cfg.max_queued_time(),
            # serving-tier admission knobs (docs/serving.md): memory
            # gate fraction + default projection for unseen statements
            admission_memory_fraction=cfg.admission_memory_fraction(),
            admission_reserve_bytes=cfg.admission_reserve_bytes())
        role = "coordinator"
    else:
        from presto_tpu.memory import default_memory_pool
        from presto_tpu.server.worker import WorkerServer

        # the process HBM pool: gives a deployed worker the memory
        # accounting surfaces (/v1/info breakdown, memory.pool_* gauges
        # on /v1/metrics) the coordinator's killer and the metrics
        # plane read
        server = WorkerServer(
            catalog,
            port=port,
            buffer_bytes=cfg.int("task.buffer-bytes", 64 << 20),
            memory_pool=default_memory_pool(),
            # morsel split scheduler width for fragment scans (0 =
            # process default from PRESTO_TPU_TASK_CONCURRENCY)
            task_concurrency=cfg.int("query.task-concurrency", 0) or None,
        )
        role = "worker"
    return server, role, cfg


def _var_paths(etc_dir: str):
    import os

    var = os.path.join(etc_dir, "var")
    os.makedirs(os.path.join(var, "log"), exist_ok=True)
    return (os.path.join(var, "launcher.pid"),
            os.path.join(var, "log", "server.log"))


def _read_pid(pidfile: str):
    import os

    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return None
    try:
        os.kill(pid, 0)  # alive?
    except ProcessLookupError:
        return None
    except PermissionError:
        pass  # EPERM: alive, owned by another user
    return pid


def daemon_start(etc_dir: str, port: int = 0) -> int:
    """bin/launcher ``start``: detach a ``run`` child, record its pid
    (the reference launcher's pidfile + var/log/server.log contract)."""
    import os
    import subprocess

    pidfile, logfile = _var_paths(etc_dir)
    pid = _read_pid(pidfile)
    if pid is not None:
        print(f"already running as {pid}")
        return pid
    cmd = [sys.executable, "-m", "presto_tpu.launcher", "run",
           "--etc", etc_dir]
    if port:
        cmd += ["--port", str(port)]
    with open(logfile, "ab") as log:
        child = subprocess.Popen(cmd, stdout=log, stderr=log,
                                 start_new_session=True,
                                 cwd=os.getcwd())
    with open(pidfile, "w") as f:
        f.write(str(child.pid))
    print(f"started as {child.pid}")
    return child.pid


def daemon_stop(etc_dir: str, timeout: float = 30.0) -> bool:
    """bin/launcher ``stop``: SIGTERM then wait (the server drains)."""
    import os
    import time

    pidfile, _ = _var_paths(etc_dir)
    pid = _read_pid(pidfile)
    if pid is None:
        print("not running")
        return True
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:  # exited between check and signal
        os.unlink(pidfile)
        print("stopped")
        return True
    except PermissionError:
        # recycled pid now owned by another user: never signal it
        print(f"pid {pid} is not ours (stale pidfile?); not signalling")
        return False
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            os.unlink(pidfile)
            print("stopped")
            return True
        time.sleep(0.1)
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass  # exited in the last poll window
    os.unlink(pidfile)
    print("killed")
    return False


def daemon_status(etc_dir: str):
    pidfile, _ = _var_paths(etc_dir)
    pid = _read_pid(pidfile)
    print(f"running as {pid}" if pid else "not running")
    return pid


def main(argv=None):
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # jax is pre-imported at interpreter startup in this image
        # (axon platform plugin), so the env var alone can be too late;
        # jax.config still works until the backend first initializes
        # (same stanza as bench.py / tests/conftest.py)
        import jax

        jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(prog="presto_tpu.launcher", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run", help="run the server in the foreground")
    run.add_argument("--etc", required=True, help="etc/ config directory")
    run.add_argument("--port", type=int, default=0)
    for name in ("start", "stop", "restart", "status"):
        p = sub.add_parser(name, help=f"daemon {name} (pidfile under etc/var)")
        p.add_argument("--etc", required=True)
        if name in ("start", "restart"):
            p.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)

    if args.cmd == "start":
        daemon_start(args.etc, args.port)
        return
    if args.cmd == "stop":
        daemon_stop(args.etc)
        return
    if args.cmd == "restart":
        daemon_stop(args.etc)
        daemon_start(args.etc, args.port)
        return
    if args.cmd == "status":
        daemon_status(args.etc)
        return

    server, role, cfg = build_from_etc(args.etc, args.port)
    server.start()
    uri = server.uri
    print(f"{role} listening at {uri}", flush=True)

    stop = {"flag": False}

    def on_term(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_term)
    signal.signal(signal.SIGTERM, on_term)
    import time

    while not stop["flag"]:
        time.sleep(0.2)
    # workers drain (finish running tasks) before exiting
    if hasattr(server, "drain"):
        server.drain(timeout=cfg.int("shutdown.grace-seconds", 30))
    else:
        server.stop()
    print(f"{role} stopped", flush=True)


if __name__ == "__main__":
    main()
