"""Server launcher: start a coordinator or worker from an etc/ directory.

Reference analog: ``presto-server``'s bin/launcher + PrestoServer.java
bootstrap (role selection via config.properties ``coordinator=true``,
catalogs from etc/catalog/*.properties).  Usage:

  python -m presto_tpu.launcher run --etc etc/            # foreground
  python -m presto_tpu.launcher run --etc etc/ --port 8080

A coordinator serves the V1 statement protocol (server/coordinator.py);
a worker serves the task protocol (server/worker.py).  Workers register
with the coordinator via ``discovery.uri`` the way reference workers
announce to airlift discovery.
"""

from __future__ import annotations

import argparse
import signal
import sys


def build_from_etc(etc_dir: str, port: int = 0):
    from presto_tpu.config import EngineConfig
    from presto_tpu.runner import QueryRunner

    cfg = EngineConfig.from_etc(etc_dir)
    catalog = cfg.build_catalog()
    port = port or cfg.int("http-server.http.port", 0)
    if cfg.bool("coordinator", True):
        from presto_tpu.server.coordinator import CoordinatorServer

        runner = QueryRunner(catalog, session=cfg.build_session())
        server = CoordinatorServer(runner, port=port)
        role = "coordinator"
    else:
        from presto_tpu.server.worker import WorkerServer

        server = WorkerServer(
            catalog,
            port=port,
            buffer_bytes=cfg.int("task.buffer-bytes", 64 << 20),
        )
        role = "worker"
    return server, role, cfg


def main(argv=None):
    ap = argparse.ArgumentParser(prog="presto_tpu.launcher", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run", help="run the server in the foreground")
    run.add_argument("--etc", required=True, help="etc/ config directory")
    run.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)

    server, role, cfg = build_from_etc(args.etc, args.port)
    server.start()
    uri = server.uri
    print(f"{role} listening at {uri}", flush=True)

    stop = {"flag": False}

    def on_term(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_term)
    signal.signal(signal.SIGTERM, on_term)
    import time

    while not stop["flag"]:
        time.sleep(0.2)
    # workers drain (finish running tasks) before exiting
    if hasattr(server, "drain"):
        server.drain(timeout=cfg.int("shutdown.grace-seconds", 30))
    else:
        server.stop()
    print(f"{role} stopped", flush=True)


if __name__ == "__main__":
    main()
