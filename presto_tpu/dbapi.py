"""PEP 249 (DB-API 2.0) driver over the REST protocol.

Reference analog: ``presto-jdbc`` — the standard database-driver
surface (Connection/Cursor here instead of JDBC's Connection/Statement/
ResultSet) speaking ``presto-client``'s V1 statement protocol
underneath (client.py's StatementClient).

    import presto_tpu.dbapi as dbapi
    conn = dbapi.connect("http://127.0.0.1:8080")
    cur = conn.cursor()
    cur.execute("select count(*) from lineitem")
    print(cur.fetchall())
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from presto_tpu.client import StatementClient

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


def connect(uri: str) -> "Connection":
    return Connection(uri)


class Connection:
    def __init__(self, uri: str):
        self._client = StatementClient(uri)
        self._closed = False

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self._client)

    def close(self) -> None:
        self._closed = True

    # autocommit engine: commit/rollback are no-ops (the reference's
    # JDBC driver behaves the same outside explicit transactions)
    def commit(self) -> None:
        pass

    def rollback(self) -> None:
        raise DatabaseError("transactions are not supported")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _quote(value: Any) -> str:
    if value is None:
        return "null"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    s = str(value).replace("'", "''")
    return f"'{s}'"


def _substitute(operation: str, parameters: Sequence[Any]) -> str:
    """qmark substitution that skips ? inside quoted strings."""
    out = []
    it = iter(parameters)
    used = 0
    i = 0
    n = len(operation)
    while i < n:
        ch = operation[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if operation[j] == "'":
                    if j + 1 < n and operation[j + 1] == "'":  # escaped ''
                        j += 2
                        continue
                    break
                j += 1
            out.append(operation[i : j + 1])
            i = j + 1
            continue
        if ch == "?":
            try:
                out.append(_quote(next(it)))
            except StopIteration:
                raise ProgrammingError(
                    f"statement has more placeholders than the "
                    f"{len(parameters)} parameters given") from None
            used += 1
            i += 1
            continue
        out.append(ch)
        i += 1
    if used != len(parameters):
        raise ProgrammingError(
            f"statement has {used} placeholders, "
            f"{len(parameters)} parameters given")
    return "".join(out)


class Cursor:
    arraysize = 1

    def __init__(self, client: StatementClient):
        self._client = client
        self._rows: Optional[List[tuple]] = None
        self._pos = 0
        self.description: Optional[List[tuple]] = None
        self.rowcount = -1

    def execute(self, operation: str, parameters: Optional[Sequence[Any]] = None) -> "Cursor":
        if parameters:
            operation = _substitute(operation, parameters)
        try:
            columns, rows = self._client.execute(operation)
        except Exception as e:
            raise DatabaseError(str(e)) from e
        self._rows = rows
        self._pos = 0
        self.rowcount = len(rows)
        self.description = [
            (c.get("name"), c.get("type"), None, None, None, None, None)
            for c in columns
        ]
        return self

    def executemany(self, operation: str, seq_of_parameters) -> "Cursor":
        for p in seq_of_parameters:
            self.execute(operation, p)
        return self

    def fetchone(self) -> Optional[tuple]:
        self._check()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[tuple]:
        self._check()
        n = size or self.arraysize
        out = self._rows[self._pos : self._pos + n]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[tuple]:
        self._check()
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def _check(self):
        if self._rows is None:
            raise ProgrammingError("no result set: call execute() first")

    def close(self) -> None:
        self._rows = None

    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass

    def __iter__(self):
        self._check()
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row
