"""Cooperative task executor: fixed thread pool + multilevel feedback
queue with time quanta.

Reference analog: ``execution/executor/TaskExecutor.java:75`` (fixed
runner threads, 1s quanta), ``MultilevelSplitQueue.java:41`` (priority
levels by cumulative CPU: 0/1/10/60/300s, 2x level weighting) and
``PrioritizedSplitRunner.java`` (yieldable split work).  Work items
here are page-granularity generators: a runner thread drives one item
for up to a quantum, then re-enqueues it at the level its cumulative
runtime has earned — long-running queries sink to lower-priority
levels so short interactive work stays responsive, exactly the
reference's fairness mechanism.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Iterator, List, Optional

from presto_tpu.sync import named_condition, named_lock

# cumulative-seconds thresholds of the levels (TaskExecutor's 0/1/10/60/300)
LEVEL_THRESHOLDS = (0.0, 1.0, 10.0, 60.0, 300.0)
# each level gets half the scheduling weight of the one above
LEVEL_WEIGHT = 2.0


def _level_of(cpu_seconds: float) -> int:
    lvl = 0
    for i, t in enumerate(LEVEL_THRESHOLDS):
        if cpu_seconds >= t:
            lvl = i
    return lvl


class TaskHandle:
    """One submitted task: a generator of work steps + accounting."""

    _seq_lock = named_lock("executor.TaskHandle._seq_lock")
    _seq = 0

    def __init__(self, work: Iterator, on_done: Optional[Callable] = None,
                 on_error: Optional[Callable] = None):
        self.work = work
        self.on_done = on_done
        self.on_error = on_error
        self.cpu = 0.0
        self.steps = 0
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.canceled = False
        with TaskHandle._seq_lock:
            TaskHandle._seq += 1
            self.seq = TaskHandle._seq

    @property
    def level(self) -> int:
        return _level_of(self.cpu)

    def cancel(self) -> None:
        self.canceled = True

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class TaskExecutor:
    """Fixed pool of runner threads over a multilevel feedback queue.

    ``submit`` takes a zero-arg-step generator; each ``next()`` is one
    cooperative step (process one page).  A runner drives a task until
    its quantum expires, accumulates its cpu time, and re-enqueues it
    at the earned level; lower levels are picked with exponentially
    decayed frequency (MultilevelSplitQueue's 2x weighting via a
    virtual-time priority)."""

    def __init__(self, num_threads: int = 4, quantum: float = 0.1):
        self.quantum = quantum
        self._heap: List = []  # (virtual_priority, seq, handle)
        self._lock = named_lock("executor.TaskExecutor._lock")
        self._available = named_condition("executor.TaskExecutor._lock",
                                          self._lock)
        self._shutdown = False
        self.completed_tasks = 0
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"task-runner-{i}")
            for i in range(num_threads)
        ]
        for t in self._threads:
            t.start()

    # -- submission ---------------------------------------------------------
    def submit(self, work: Iterator, on_done: Optional[Callable] = None,
               on_error: Optional[Callable] = None) -> TaskHandle:
        h = TaskHandle(work, on_done, on_error)
        self._enqueue(h)
        return h

    def _priority(self, h: TaskHandle) -> float:
        # virtual time: cpu scaled up by the level weight — deeper
        # levels accumulate priority faster, so they run less often
        return h.cpu * (LEVEL_WEIGHT ** h.level)

    def _enqueue(self, h: TaskHandle) -> None:
        with self._available:
            heapq.heappush(self._heap, (self._priority(h), h.seq, h))
            self._available.notify()

    # -- runner loop --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._available:
                while not self._heap and not self._shutdown:
                    self._available.wait()
                if self._shutdown and not self._heap:
                    return
                _, _, h = heapq.heappop(self._heap)
            self._process(h)

    def _process(self, h: TaskHandle) -> None:
        if h.canceled:
            self._finish(h, None)
            return
        start = time.monotonic()
        try:
            while True:
                next(h.work)
                h.steps += 1
                elapsed = time.monotonic() - start
                if elapsed >= self.quantum or h.canceled:
                    h.cpu += elapsed
                    self._enqueue(h)
                    return
        except StopIteration:
            h.cpu += time.monotonic() - start
            self._finish(h, None)
        except BaseException as e:
            h.cpu += time.monotonic() - start
            self._finish(h, e)

    def _finish(self, h: TaskHandle, error: Optional[BaseException]) -> None:
        h.error = error
        # concurrent runner threads finish tasks at once: an unlocked
        # += here loses counts (sanitizer shared-state-race)
        with self._lock:
            self.completed_tasks += 1
        h.done.set()
        cb = h.on_error if error is not None else h.on_done
        if cb is not None:
            try:
                cb(h) if error is None else cb(h, error)
            except Exception:
                pass

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, wait: bool = True) -> None:
        with self._available:
            self._shutdown = True
            self._available.notify_all()
        if wait:
            for t in self._threads:
                t.join()
