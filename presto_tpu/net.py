"""Shared transient-HTTP plane: error classification + bounded retry.

Reference analog: ``server/remotetask/RequestErrorTracker.java`` +
``Backoff.java`` — every coordinator↔worker RPC in the reference rides
one shared error tracker that distinguishes *transient* transport
faults (retried with exponential backoff, eventually blamed on the
node) from *deterministic* query errors (propagated immediately,
never retried, never poisoning the node).  This module is that shared
plane for the engine's urllib call sites: ``WorkerClient``,
``shuffle_client``, ``cluster_memory``, and the coordinator's
metrics/memory polls all classify and retry through here, so the
transient/deterministic boundary cannot drift between tiers.

Classification contract (docs/fault-tolerance.md):

* transient — connection refused/reset, DNS, socket timeouts, HTTP
  5xx (handler crash / gateway / draining worker), page-integrity
  (CRC) failures.  Retryable: the work is a pure function of its
  fragment, so recomputation is safe (worker task create is
  idempotent by task id) and failover can move it to a survivor.
* deterministic — HTTP 4xx (the request is wrong) and any error whose
  text carries a query-error marker (``BindError``,
  ``GroupCapacityExceeded``, type errors...).  Task-protocol query
  errors travel as task-error payloads (``TaskPullFailed`` ->
  ``TaskFailed``), not bare HTTP status.  A retry recomputes the same
  failure; blaming the worker would poison failover.  These must
  NEVER be retried.

Every classified failure increments the pre-registered
``net.errors_<reason>`` counter; every retry sleep increments
``retry.http_total`` (obs/metrics.py catalog).
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Iterable, Optional, Tuple
from presto_tpu.sync import named_lock

_log = logging.getLogger("presto_tpu.net")

#: classified failure reasons (each has a pre-registered
#: ``net.errors_<reason>`` counter in the metrics catalog)
REASONS = ("refused", "timeout", "http", "protocol", "other")

#: error-text markers that mean a deterministic QUERY error even when
#: it arrives wrapped in transport-level exception text — these must
#: never be retried (the BindError/GroupCapacityExceeded class)
DETERMINISTIC_MARKERS = (
    "BindError", "GroupCapacityExceeded", "TypeError", "ValueError",
    "PlanValidationError",
)


class PageIntegrityError(Exception):
    """A pulled page failed its CRC check: the bytes were damaged in
    flight or by a faulty producer.  Transient by classification — the
    fragment is pure, so re-pulling/recomputing is always safe."""


def classify_reason(exc: BaseException) -> str:
    """Map an exception from an HTTP call site to one of REASONS."""
    if isinstance(exc, urllib.error.HTTPError):
        return "http"
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return "timeout"
    if isinstance(exc, ConnectionRefusedError):
        return "refused"
    if isinstance(exc, urllib.error.URLError):
        reason = getattr(exc, "reason", None)
        if isinstance(reason, BaseException):
            return classify_reason(reason)
        return "protocol"
    if isinstance(exc, (ConnectionError, OSError)):
        return "refused" if "refused" in str(exc).lower() else "protocol"
    if isinstance(exc, (PageIntegrityError, http.client.HTTPException)):
        return "protocol"
    return "other"


def is_transient(exc: BaseException) -> bool:
    """True when retrying the call could succeed (transport fault),
    False for deterministic query errors that travel with the data."""
    if isinstance(exc, urllib.error.HTTPError):
        # marker check covers the STATUS TEXT only (str(HTTPError)
        # includes the reason phrase, not the body — task-protocol
        # query errors travel as task-error payloads, TaskPullFailed,
        # and are classified before ever reaching here)
        if any(m in str(exc) for m in DETERMINISTIC_MARKERS):
            return False
        # 5xx = the WORKER (or a proxy in front of it) is in trouble —
        # 500 handler crash, 502/504 gateway, 503 draining: transient,
        # so failover can move the work.  Deterministic query errors in
        # the task protocol travel as task-error payloads
        # (TaskPullFailed), not bare HTTP status.  4xx = the REQUEST is
        # wrong: deterministic.
        return exc.code >= 500
    if isinstance(exc, PageIntegrityError):
        return True
    if isinstance(exc, http.client.HTTPException):
        # half-written responses from a dying peer (RemoteDisconnected,
        # IncompleteRead, BadStatusLine): node faults, not query errors
        return True
    if isinstance(exc, (urllib.error.URLError, ConnectionError,
                        socket.timeout, TimeoutError, OSError)):
        text = str(exc)
        return not any(m in text for m in DETERMINISTIC_MARKERS)
    return False


def count_error(exc: BaseException, site: Optional[str] = None) -> str:
    """Increment the per-reason error counter (and the per-site one
    when ``site`` names a pre-registered ``<site>`` counter); returns
    the reason label for the caller's own logging."""
    from presto_tpu.obs import METRICS

    reason = classify_reason(exc)
    METRICS.counter(f"net.errors_{reason}").inc()  # metrics: allow
    if site is not None:
        METRICS.counter(site).inc()
    return reason


def http_retry(
    fn: Callable[[], Any],
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 2.0,
    jitter: float = 0.25,
    site: Optional[str] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
) -> Any:
    """Call ``fn`` retrying *transient* failures with exponential
    backoff + jitter; deterministic errors propagate immediately.  The
    last transient failure re-raises after the budget is spent.

    ``site`` optionally names a pre-registered per-site error counter
    (e.g. ``worker.ping_errors``); ``rng`` makes the jitter schedule
    reproducible under the fault-injection harness."""
    from presto_tpu.obs import METRICS

    rng = rng or random
    last: Optional[BaseException] = None
    for attempt in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:
            count_error(e, site=site)
            if not is_transient(e) or attempt + 1 >= max(attempts, 1):
                raise
            last = e
            METRICS.counter("retry.http_total").inc()
            delay = min(base_delay * (2 ** attempt), max_delay)
            sleep(delay * (1.0 + jitter * rng.random()))
    raise last  # pragma: no cover - loop always returns or raises


def request_bytes(
    url: str,
    timeout: float,
    data: Optional[bytes] = None,
    method: Optional[str] = None,
    headers: Optional[Dict[str, str]] = None,
    attempts: int = 1,
    site: Optional[str] = None,
) -> Tuple[bytes, Dict[str, str]]:
    """One classified HTTP request returning (body, headers).  With
    ``attempts > 1`` transient failures retry through http_retry."""

    def call() -> Tuple[bytes, Dict[str, str]]:
        req = urllib.request.Request(url, data=data, headers=headers or {},
                                     method=method)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read(), dict(resp.headers.items())

    if attempts <= 1:
        try:
            return call()
        except Exception as e:
            count_error(e, site=site)
            raise
    return http_retry(call, attempts=attempts, site=site)


class PollHealth:
    """Availability log for periodic pollers: one warning when a
    target STARTS failing, one info when it recovers — never a line
    per poll (the satellite contract for the old blind
    ``except: pass`` swallows).  Counting stays per-poll via the
    classified counters."""

    def __init__(self, what: str, log: Optional[logging.Logger] = None):
        self.what = what
        self._log = log or _log
        self._ok: Dict[str, bool] = {}

    def succeeded(self, target: str) -> None:
        if self._ok.get(target) is False:
            self._log.info("%s poll of %s recovered", self.what, target)
        self._ok[target] = True

    def failed(self, target: str, exc: BaseException) -> str:
        # counting happened at the request site (request_json's
        # ``site=`` counter); this is ONLY the transition log
        reason = classify_reason(exc)
        if self._ok.get(target, True):
            self._log.warning("%s poll of %s failing (%s: %s)",
                              self.what, target, reason, exc)
        self._ok[target] = False
        return reason


def poll_each(
    targets: Iterable[str],
    fetch: Callable[[str], Any],
    health: Optional[PollHealth] = None,
    join_timeout: float = 2.5,
) -> Dict[str, Any]:
    """Concurrently call ``fetch(target)`` for every target (the
    RemoteNodeMemory poll-fan pattern shared by the coordinator's
    metrics/memory polls and the cluster memory manager) and return
    ``{target: result}`` for the successes.  A failing target is
    simply absent — its error was classified/counted by the fetch's
    own request site and transition-logged via ``health``; one hung
    socket cannot stretch the cycle past ``join_timeout``."""
    out: Dict[str, Any] = {}
    lock = named_lock("net.poll_each.lock")

    def run(target: str) -> None:
        try:
            value = fetch(target)
            with lock:
                out[target] = value
            if health is not None:
                health.succeeded(target)
        except Exception as e:
            if health is not None:
                health.failed(target, e)

    threads = [threading.Thread(target=run, args=(t,), daemon=True,
                                name=f"net-poll-{i}")
               for i, t in enumerate(targets)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=join_timeout)
    return out


def request_json(
    url: str,
    timeout: float,
    data: Optional[dict] = None,
    method: Optional[str] = None,
    headers: Optional[Dict[str, str]] = None,
    attempts: int = 1,
    site: Optional[str] = None,
) -> Any:
    """request_bytes + JSON decode (the control-plane shape every
    poll/info/task-status call uses)."""
    body = None
    hdrs = dict(headers or {})
    if data is not None:
        body = json.dumps(data).encode()
        hdrs.setdefault("Content-Type", "application/json")
    raw, _ = request_bytes(url, timeout=timeout, data=body, method=method,
                           headers=hdrs, attempts=attempts, site=site)
    return json.loads(raw.decode())
