"""Warehouse connector: a directory of partitioned PCF files behind a
file-based metastore — presto-hive's architectural slot
(``presto-hive/.../HiveMetadata.java`` table/partition metadata,
``BackgroundHiveSplitLoader.java`` partition-to-split expansion,
partition pruning via TupleDomain) re-designed for this engine:

    root/<table>/_metastore.json          table schema + partition list
    root/<table>/<p>=<v>[/...]/part-*.pcf one columnar file per write
                                          per partition

TPU framing: partition columns never materialize in the files — each
split serves them as CONSTANT blocks, and the engine's existing
split-stats pruning (``exec/local.py`` TupleDomain over
``split_stats``) prunes whole partitions and individual stripes through
one mechanism.  Writes go through the standard duck-typed write SPI
(create_table/append_pages/drop_table), so CTAS/INSERT/DROP and the
transaction manager's staged-publish protocol work unchanged; the
metastore file is replaced atomically (tmp + rename) so readers never
observe a half-written table.
"""

from __future__ import annotations

import json
import os
import shutil
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Block, Dictionary, Page
from presto_tpu.storage.pcf import PcfFile, _type_str, write_pcf
from presto_tpu.types import Type, parse_type

_META = "_metastore.json"


class WarehouseConnector:
    """Directory-of-PCF warehouse with partitioned tables."""

    #: CTAS WITH (...) properties are accepted (runner gate)
    supports_table_properties = True

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._files: Dict[str, PcfFile] = {}
        self._meta_cache: Dict[str, dict] = {}
        self._splits_cache: Dict[str, list] = {}

    # -- metastore ----------------------------------------------------------
    def _meta_path(self, table: str) -> str:
        return os.path.join(self.root, table, _META)

    def _meta(self, table: str) -> dict:
        m = self._meta_cache.get(table)
        if m is None:
            with open(self._meta_path(table)) as f:
                m = json.load(f)
            self._meta_cache[table] = m
        return m

    def _write_meta(self, table: str, meta: dict) -> None:
        path = self._meta_path(table)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic publish (HiveMetadata commit)
        self._meta_cache[table] = meta
        self._splits_cache.pop(table, None)

    def _pcf(self, table: str, rel: str) -> PcfFile:
        key = f"{table}//{rel}"
        if key not in self._files:
            self._files[key] = PcfFile(os.path.join(self.root, table, rel))
        return self._files[key]

    # -- read SPI -----------------------------------------------------------
    def table_names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.exists(os.path.join(self.root, d, _META)))

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        m = self._meta(table)
        return [(c, parse_type(t)) for c, t in m["schema"]]

    def partition_columns(self, table: str) -> List[str]:
        return list(self._meta(table).get("partitioned_by", []))

    def partitions(self, table: str) -> List[dict]:
        """Partition-value dicts, one per DISTINCT partition (SHOW
        PARTITIONS / HiveMetadata.listPartitionNames).  The metastore
        keeps one entry per FILE, so appends into an existing partition
        add entries — dedup on values, first-seen order."""
        seen = set()
        out = []
        for p in self._meta(table)["partitions"]:
            key = tuple(sorted(p["values"].items()))
            if key not in seen:
                seen.add(key)
                out.append(dict(p["values"]))
        return out

    def open_dictionary_columns(self, table: str) -> set:
        """Partition columns accept NEW string values on INSERT (their
        'dictionary' is just the metastore's partition-value list, not
        a closed file dictionary) — dynamic partitioning."""
        return set(self.partition_columns(table))

    def _splits(self, table: str) -> List[tuple]:
        """[(partition_index, relative_file, stripe)] — one split per
        stripe of every partition file (the split expansion of
        BackgroundHiveSplitLoader)."""
        cached = self._splits_cache.get(table)
        if cached is not None:
            return cached
        m = self._meta(table)
        out = []
        for pi, part in enumerate(m["partitions"]):
            f = self._pcf(table, part["file"])
            for s in range(f.num_stripes):
                out.append((pi, part["file"], s))
        self._splits_cache[table] = out
        return out

    def num_splits(self, table: str) -> int:
        return len(self._splits(table))

    def row_count(self, table: str) -> int:
        return sum(int(p["rows"]) for p in self._meta(table)["partitions"])

    def table_version(self, table: str):
        """Monotonically increasing data version, persisted in the
        metastore and bumped on every committed write — the serving
        tier's cache-invalidation token (serving/cache.py).  Paired
        with the table's incarnation id so a drop + recreate can never
        alias an old incarnation's counter (old metastores without the
        fields read as version 0 of incarnation '')."""
        m = self._meta(table)
        return (m.get("table_id", ""), int(m.get("version", 0)))

    def _pvalue_dict(self, table: str, col: str) -> Dictionary:
        """Table-level dictionary for a VARCHAR partition column: the
        ordered distinct partition values."""
        m = self._meta(table)
        vals: List[str] = []
        for part in m["partitions"]:
            v = part["values"][col]
            if v not in vals:
                vals.append(v)
        return Dictionary(vals or [""])

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        m = self._meta(table)
        if column in m.get("partitioned_by", []):
            t = dict(self.schema(table))[column]
            if t.is_string and not t.is_raw_string:
                return self._pvalue_dict(table, column)
            return None
        parts = m["partitions"]
        if not parts:
            return None
        return self._pcf(table, parts[0]["file"]).dictionary_for(column)

    def column_domain(self, table: str, column: str):
        t = dict(self.schema(table))[column]
        if t.is_string and not t.is_raw_string:
            d = self.dictionary_for(table, column)
            return (0, len(d) - 1) if d is not None else None
        return None

    def split_stats(self, table: str, split: int):
        """Stripe min/max stats + partition values as point stats — the
        engine's TupleDomain pruning rejects whole partitions (partition
        pruning) and non-matching stripes (stripe pruning) uniformly."""
        pi, rel, stripe = self._splits(table)[split]
        stats = dict(self._pcf(table, rel).stripe_stats(stripe))
        m = self._meta(table)
        part = m["partitions"][pi]
        schema = dict(self.schema(table))
        for col in m.get("partitioned_by", []):
            v = part["values"][col]
            t = schema[col]
            if t.is_string and not t.is_raw_string:
                code = self._pvalue_dict(table, col).values.index(v)
                stats[col] = (code, code)
            else:
                stats[col] = (v, v)
        return stats

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        pi, rel, stripe = self._splits(table)[split]
        m = self._meta(table)
        part = m["partitions"][pi]
        pcols = m.get("partitioned_by", [])
        schema = self.schema(table)
        data_cols = [c for c, _ in schema if c not in pcols]
        page = self._pcf(table, rel).read_stripe(
            stripe, columns=data_cols, capacity=capacity)
        cap = page.capacity
        by_name = dict(zip(data_cols, page.blocks))
        blocks = []
        for col, t in schema:
            if col not in pcols:
                blocks.append(by_name[col])
                continue
            # constant partition-value block (never stored in the file)
            v = part["values"][col]
            if t.is_string and not t.is_raw_string:
                d = self._pvalue_dict(table, col)
                code = d.values.index(v)
                data = np.full(cap, code, dtype=np.int32)
                blocks.append(Block(data, np.asarray(page.row_mask), t, d))
            else:
                if t.is_decimal and not t.is_long_decimal:
                    v = int(v)
                data = np.full((cap,) + t.value_shape, v, dtype=t.np_dtype)
                blocks.append(Block(data, np.asarray(page.row_mask), t))
        return Page(tuple(blocks), page.row_mask)

    # -- write SPI ----------------------------------------------------------
    def create_table(self, name: str, schema, pages: Sequence[Page],
                     domains=None, primary_key=None, sort_order=None,
                     bucketing=None,
                     properties: Optional[dict] = None) -> None:
        props = properties or {}
        pby = props.get("partitioned_by", [])
        if isinstance(pby, str):
            pby = [pby]
        pby = list(pby)
        exists = os.path.exists(self._meta_path(name))
        if exists:
            # replace (the DELETE-by-rewrite path re-creates the table
            # with the survivor rows): keep the existing partitioning
            if not pby:
                pby = self.partition_columns(name)
            self.drop_table(name)
        cols = [c for c, _ in schema]
        types = dict(schema)
        for p in pby:
            if p not in cols:
                raise ValueError(f"partition column {p!r} not in schema")
            t = types[p]
            ok = (t.is_integerlike or t.name == "boolean"
                  or (t.is_decimal and not t.is_long_decimal)
                  or (t.is_string and not t.is_raw_string))
            if not ok:
                raise ValueError(
                    f"partition column {p!r} has unsupported type {t!r} "
                    "(integer-like, short decimal, boolean, or dictionary "
                    "varchar only)")
        tdir = os.path.join(self.root, name)
        os.makedirs(tdir, exist_ok=True)
        meta = {
            "schema": [[c, _type_str(t)] for c, t in schema],
            "partitioned_by": pby,
            "partitions": [],
            "table_id": uuid.uuid4().hex[:12],
            "version": 0,
        }
        self._append(name, meta, schema, pages)
        meta["version"] = int(meta.get("version", 0)) + 1
        self._write_meta(name, meta)

    def append_pages(self, name: str, pages: Sequence[Page]) -> None:
        meta = self._meta(name)
        schema = self.schema(name)
        self._append(name, meta, schema, pages)
        meta["version"] = int(meta.get("version", 0)) + 1
        self._write_meta(name, meta)

    def drop_table(self, name: str) -> None:
        shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)
        self._files = {k: v for k, v in self._files.items()
                       if not k.startswith(f"{name}//")}
        self._meta_cache.pop(name, None)
        self._splits_cache.pop(name, None)

    def rename_table(self, name: str, new_name: str) -> None:
        dst = os.path.join(self.root, new_name)
        if os.path.exists(dst):
            raise ValueError(f"warehouse table {new_name} already exists")
        os.rename(os.path.join(self.root, name), dst)
        self._files = {k: v for k, v in self._files.items()
                       if not k.startswith(f"{name}//")}
        self._meta_cache.pop(name, None)
        self._splits_cache.pop(name, None)

    # -- transactions (staged writes; ConnectorTransactionHandle) -----------
    def begin_transaction(self):
        return _WarehouseTx()

    def stage(self, tx: "_WarehouseTx", op: str, *args, **kwargs) -> None:
        tx.ops.append((op, args, kwargs))

    def commit_transaction(self, tx: "_WarehouseTx") -> None:
        for op, args, kwargs in tx.ops:
            getattr(self, op)(*args, **kwargs)
        tx.ops.clear()

    def rollback_transaction(self, tx: "_WarehouseTx") -> None:
        tx.ops.clear()

    # -- partitioned write --------------------------------------------------
    def _append(self, name: str, meta: dict, schema, pages) -> None:
        pby = meta.get("partitioned_by", [])
        cols = [c for c, _ in schema]
        data_schema = [(c, t) for c, t in schema if c not in pby]
        groups = self._split_by_partition(schema, pby, pages)
        for values, gpages in groups:
            rows = sum(int(np.asarray(p.row_mask).sum()) for p in gpages)
            if rows == 0:
                continue
            rel_dir = "/".join(f"{c}={values[c]}" for c in pby)
            os.makedirs(os.path.join(self.root, name, rel_dir), exist_ok=True)
            rel = (f"{rel_dir}/" if rel_dir else "") + \
                f"part-{uuid.uuid4().hex[:12]}.pcf"
            keep = [cols.index(c) for c, _ in data_schema]
            dpages = [Page(tuple(p.blocks[i] for i in keep), p.row_mask)
                      for p in gpages]
            write_pcf(os.path.join(self.root, name, rel), data_schema, dpages)
            meta["partitions"].append(
                {"values": values, "file": rel, "rows": rows})

    def _split_by_partition(self, schema, pby: List[str], pages):
        """[(values_dict, [pages-with-only-matching-rows])]."""
        if not pby:
            return [({}, list(pages))]
        cols = [c for c, _ in schema]
        out: Dict[tuple, list] = {}
        order: List[tuple] = []
        for page in pages:
            keyed = []  # (column name, codes array, block)
            for c in pby:
                b = page.blocks[cols.index(c)]
                keyed.append((c, np.asarray(b.data), b))
            mask = np.asarray(page.row_mask)
            live = np.nonzero(mask)[0]
            if live.size == 0:
                continue
            combo = np.stack([a[live] for _, a, _ in keyed], axis=1)
            for vals in np.unique(combo, axis=0):
                sel = np.zeros_like(mask)
                sel[live[(combo == vals[None, :]).all(axis=1)]] = True
                values = {}
                for (c, _, b), v in zip(keyed, vals):
                    if b.type.is_string and b.dictionary is not None:
                        values[c] = b.dictionary.values[int(v)]
                    else:
                        values[c] = int(v)
                key = tuple(sorted(values.items()))
                if key not in out:
                    out[key] = []
                    order.append(key)
                out[key].append(Page(page.blocks, np.asarray(page.row_mask) & sel))
        return [(dict(k), out[k]) for k in order]


class _WarehouseTx:
    """Staged write list (ConnectorTransactionHandle analog)."""

    def __init__(self):
        self.ops: list = []
