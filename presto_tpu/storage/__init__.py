from presto_tpu.storage.columnfile import FileConnector, write_table  # noqa: F401
