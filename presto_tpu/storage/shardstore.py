"""Shard store: the engine's native storage engine — PCF shards on
local disk behind a SQL (sqlite) shard-metadata database, with
compaction, rebalancing across storage nodes, and backup/restore.

Reference analog: ``presto-raptor`` (31k LoC) — ORC shards on worker
disks + a MySQL metadata store (``raptor/metadata/DatabaseShardManager``),
a shard compactor/organizer (``raptor/storage/organization/``), a
rebalancer (``raptor/storage/ShardRecoveryManager`` / bucket balancer)
and a pluggable backup store (``raptor/backup/BackupStore.java``).

TPU-first redesign rather than a port:

- Shard pruning happens **entirely in the metadata DB** (min/max
  per-column stats stored per shard row) before any file is opened, so
  a filtered scan launches one device program per *surviving* shard.
- Every varchar column has ONE table-level dictionary owned by the
  metadata DB; incoming writes are re-encoded to it (appending new
  values — codes are stable forever).  All shard files therefore share
  the same code space: cross-shard scans need no dictionary merging,
  min/max code stats are meaningful for pruning, and compaction can
  concatenate shard pages without re-encoding.
- Shards are single-stripe PCF files bounded by ``max_shard_rows``;
  an optional ``sorted_by`` table property keeps every shard sorted
  (raptor's "organized tables"), which the engine's streaming
  aggregation and merge paths exploit.
- ``temporal_column`` groups compaction by disjoint value ranges so
  time-correlated shards stay clustered (raptor's temporal
  organization).
"""

from __future__ import annotations

import json
import os
import shutil
import sqlite3
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Block, Dictionary, Page
from presto_tpu.storage.pcf import PcfFile, _col_stats, _type_str, write_pcf
from presto_tpu.types import Type, parse_type

_SCHEMA_SQL = """
CREATE TABLE IF NOT EXISTS tables (
    table_id   INTEGER PRIMARY KEY,
    name       TEXT UNIQUE NOT NULL,
    schema     TEXT NOT NULL,          -- [[col, type], ...]
    sorted_by  TEXT,                   -- json list or null
    temporal   TEXT                    -- temporal column name or null
);
CREATE TABLE IF NOT EXISTS shards (
    shard_uuid TEXT PRIMARY KEY,
    table_id   INTEGER NOT NULL REFERENCES tables(table_id),
    node       TEXT NOT NULL,
    row_count  INTEGER NOT NULL,
    data_bytes INTEGER NOT NULL,
    stats      TEXT NOT NULL           -- {col: [min, max]}
);
CREATE INDEX IF NOT EXISTS shards_by_table ON shards(table_id);
CREATE TABLE IF NOT EXISTS dictionaries (
    table_id   INTEGER NOT NULL REFERENCES tables(table_id),
    column     TEXT NOT NULL,
    idx        INTEGER NOT NULL,
    value      TEXT NOT NULL,
    PRIMARY KEY (table_id, column, idx)
);
"""


class ShardStoreConnector:
    """Native storage engine: sqlite shard metadata over PCF shards."""

    supports_table_properties = True

    def __init__(self, root: str, nodes: Sequence[str] = ("node0",),
                 max_shard_rows: int = 1 << 20,
                 backup_root: Optional[str] = None):
        self.root = root
        self.nodes = list(nodes)
        self.max_shard_rows = int(max_shard_rows)
        self.backup_root = backup_root
        os.makedirs(root, exist_ok=True)
        for n in self.nodes:
            os.makedirs(os.path.join(root, n), exist_ok=True)
        if backup_root:
            os.makedirs(backup_root, exist_ok=True)
        self._db = sqlite3.connect(os.path.join(root, "metadata.db"))
        self._db.executescript(_SCHEMA_SQL)
        self._db.commit()
        self._files: Dict[str, PcfFile] = {}
        self._next_node = 0

    # -- metadata helpers ---------------------------------------------------
    def _table_row(self, name: str):
        row = self._db.execute(
            "SELECT table_id, schema, sorted_by, temporal FROM tables "
            "WHERE name = ?", (name,)).fetchone()
        if row is None:
            raise KeyError(f"shardstore table {name!r} does not exist")
        return row

    def _shards(self, table: str) -> List[tuple]:
        tid = self._table_row(table)[0]
        return self._db.execute(
            "SELECT shard_uuid, node, row_count, data_bytes, stats "
            "FROM shards WHERE table_id = ? ORDER BY shard_uuid",
            (tid,)).fetchall()

    def _shard_path(self, node: str, shard_uuid: str) -> str:
        return os.path.join(self.root, node, shard_uuid + ".pcf")

    def _pcf(self, node: str, shard_uuid: str) -> PcfFile:
        key = f"{node}/{shard_uuid}"
        f = self._files.get(key)
        if f is None:
            f = self._files[key] = PcfFile(self._shard_path(node, shard_uuid))
        return f

    def _table_dict(self, tid: int, col: str) -> List[str]:
        return [v for (v,) in self._db.execute(
            "SELECT value FROM dictionaries WHERE table_id = ? AND "
            "column = ? ORDER BY idx", (tid, col))]

    # -- connector read SPI -------------------------------------------------
    def table_names(self) -> List[str]:
        return [n for (n,) in self._db.execute("SELECT name FROM tables")]

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return [(c, parse_type(t))
                for c, t in json.loads(self._table_row(table)[1])]

    def open_dictionary_columns(self, table: str) -> set:
        """Every dictionary varchar column accepts unseen values: writes
        re-encode onto the table dictionary, appending new entries."""
        return {c for c, t in self.schema(table)
                if t.is_string and not t.is_raw_string}

    def sort_order(self, table: str) -> Optional[List[str]]:
        s = self._table_row(table)[2]
        return json.loads(s) if s else None

    def num_splits(self, table: str) -> int:
        return max(1, len(self._shards(table)))

    def row_count(self, table: str) -> int:
        tid = self._table_row(table)[0]
        (n,) = self._db.execute(
            "SELECT COALESCE(SUM(row_count), 0) FROM shards "
            "WHERE table_id = ?", (tid,)).fetchone()
        return int(n)

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        tid = self._table_row(table)[0]
        vals = self._table_dict(tid, column)
        return Dictionary(vals) if vals else None

    def column_domain(self, table: str, column: str):
        t = dict(self.schema(table))[column]
        if t.is_string and not t.is_raw_string:
            d = self.dictionary_for(table, column)
            return (0, len(d) - 1) if d else None
        los, his = [], []
        for _, _, _, _, stats in self._shards(table):
            st = json.loads(stats).get(column)
            if st is None:
                return None
            los.append(st[0])
            his.append(st[1])
        return (min(los), max(his)) if los else None

    def split_stats(self, table: str, split: int):
        """Metadata-DB shard pruning: min/max per column straight from
        the shards table — no file is opened for a pruned shard."""
        shards = self._shards(table)
        if not shards:
            return {}
        stats = json.loads(shards[split][4])
        return {c: (v[0], v[1]) for c, v in stats.items()}

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        shards = self._shards(table)
        if not shards:
            return Page.empty([t for _, t in self.schema(table)], 1)
        shard_uuid, node = shards[split][0], shards[split][1]
        return self._pcf(node, shard_uuid).read_stripe(0, capacity=capacity)

    # -- write SPI ----------------------------------------------------------
    def create_table(self, name: str, schema, pages: Sequence[Page],
                     domains=None, primary_key=None, sort_order=None,
                     bucketing=None,
                     properties: Optional[dict] = None) -> None:
        props = properties or {}
        sorted_by = props.get("sorted_by") or sort_order
        if isinstance(sorted_by, str):
            sorted_by = [sorted_by]
        temporal = props.get("temporal_column")
        cols = [c for c, _ in schema]
        for c in (sorted_by or []) + ([temporal] if temporal else []):
            if c not in cols:
                raise ValueError(f"unknown column {c!r} in table property")
        exists = self._db.execute(
            "SELECT 1 FROM tables WHERE name = ?", (name,)).fetchone()
        if exists:
            # DELETE-by-rewrite recreates the table with survivor rows
            self.drop_table(name)
        cur = self._db.execute(
            "INSERT INTO tables (name, schema, sorted_by, temporal) "
            "VALUES (?, ?, ?, ?)",
            (name, json.dumps([[c, _type_str(t)] for c, t in schema]),
             json.dumps(sorted_by) if sorted_by else None, temporal))
        tid = cur.lastrowid
        self._write_shards(tid, name, list(schema), pages)
        self._db.commit()

    def append_pages(self, name: str, pages: Sequence[Page]) -> None:
        tid = self._table_row(name)[0]
        self._write_shards(tid, name, self.schema(name), pages)
        self._db.commit()

    def drop_table(self, name: str) -> None:
        tid = self._table_row(name)[0]
        for shard_uuid, node, *_ in self._shards(name):
            self._files.pop(f"{node}/{shard_uuid}", None)
            try:
                os.unlink(self._shard_path(node, shard_uuid))
            except FileNotFoundError:
                pass
        self._db.execute("DELETE FROM shards WHERE table_id = ?", (tid,))
        self._db.execute("DELETE FROM dictionaries WHERE table_id = ?", (tid,))
        self._db.execute("DELETE FROM tables WHERE table_id = ?", (tid,))
        self._db.commit()

    def rename_table(self, name: str, new_name: str) -> None:
        if self._db.execute("SELECT 1 FROM tables WHERE name = ?",
                            (new_name,)).fetchone():
            raise ValueError(f"shardstore table {new_name} already exists")
        self._table_row(name)  # existence check
        self._db.execute("UPDATE tables SET name = ? WHERE name = ?",
                         (new_name, name))
        self._db.commit()

    # -- transactions (staged writes) ---------------------------------------
    def begin_transaction(self):
        return _ShardTx()

    def stage(self, tx: "_ShardTx", op: str, *args, **kwargs) -> None:
        tx.ops.append((op, args, kwargs))

    def commit_transaction(self, tx: "_ShardTx") -> None:
        for op, args, kwargs in tx.ops:
            getattr(self, op)(*args, **kwargs)
        tx.ops.clear()

    def rollback_transaction(self, tx: "_ShardTx") -> None:
        tx.ops.clear()

    # -- shard writing ------------------------------------------------------
    def _encode_to_table_dict(self, tid: int, col: str, block_vals,
                              codes: np.ndarray) -> np.ndarray:
        """Remap one block's dictionary codes onto the table dictionary,
        appending unseen values (codes are stable: append-only)."""
        table_vals = self._table_dict(tid, col)
        index = {v: i for i, v in enumerate(table_vals)}
        remap = np.empty(len(block_vals), dtype=np.int32)
        for i, v in enumerate(block_vals):
            j = index.get(v)
            if j is None:
                j = len(index)
                index[v] = j
                self._db.execute(
                    "INSERT INTO dictionaries (table_id, column, idx, value) "
                    "VALUES (?, ?, ?, ?)", (tid, col, j, v))
            remap[i] = j
        return remap[np.asarray(codes, dtype=np.int64)]

    def _write_shards(self, tid: int, name: str, schema, pages) -> None:
        sorted_by = self.sort_order(name)
        # one batched host transfer per page, then numpy throughout
        pages = [p.compact_host() for p in pages]
        pages = [p for p in pages if int(np.asarray(p.row_mask).sum()) > 0]
        if not pages:
            return
        cols: List[np.ndarray] = []
        valids: List[np.ndarray] = []
        for i, (col, t) in enumerate(schema):
            parts, vparts = [], []
            for p in pages:
                n = int(np.asarray(p.row_mask).sum())
                b = p.blocks[i]
                data = np.asarray(b.data)[:n]
                if t.is_string and not t.is_raw_string and b.dictionary is not None:
                    data = self._encode_to_table_dict(
                        tid, col, list(b.dictionary.values), data)
                parts.append(data)
                vparts.append(np.asarray(b.valid)[:n])
            cols.append(np.concatenate(parts))
            valids.append(np.concatenate(vparts))
        total = len(cols[0])
        if sorted_by:
            by_name = {c: i for i, (c, _) in enumerate(schema)}
            keys = [cols[by_name[c]] for c in reversed(sorted_by)]
            order = np.lexsort(keys)
            cols = [c[order] for c in cols]
            valids = [v[order] for v in valids]
        dicts = {c: Dictionary(self._table_dict(tid, c))
                 for c, t in schema
                 if t.is_string and not t.is_raw_string and
                 self._table_dict(tid, c)}
        for lo in range(0, total, self.max_shard_rows):
            hi = min(lo + self.max_shard_rows, total)
            blocks, stats = [], {}
            for (col, t), data, valid in zip(schema, cols, valids):
                d, v = data[lo:hi], valid[lo:hi]
                blocks.append(Block(d, v, t, dicts.get(col)))
                st = _col_stats(d, v, t)
                if "min" in st:
                    stats[col] = [st["min"], st["max"]]
            page = Page(tuple(blocks), np.ones(hi - lo, dtype=np.bool_))
            shard_uuid = uuid.uuid4().hex
            node = self.nodes[self._next_node % len(self.nodes)]
            self._next_node += 1
            path = self._shard_path(node, shard_uuid)
            write_pcf(path, schema, [page])
            if self.backup_root:  # eager backup (raptor BackupManager)
                shutil.copyfile(
                    path, os.path.join(self.backup_root, shard_uuid + ".pcf"))
            self._db.execute(
                "INSERT INTO shards (shard_uuid, table_id, node, row_count, "
                "data_bytes, stats) VALUES (?, ?, ?, ?, ?, ?)",
                (shard_uuid, tid, node, hi - lo, os.path.getsize(path),
                 json.dumps(stats)))

    # -- maintenance: compaction / rebalance / recovery ---------------------
    def compact(self, table: str, target_rows: Optional[int] = None) -> int:
        """Merge small shards into full ones (raptor's ShardCompactor).
        Returns the number of shards eliminated.  With a temporal
        column, only shards from the same temporal bucket merge, so
        time-correlated data stays clustered."""
        target = int(target_rows or self.max_shard_rows)
        tid, schema_json, sorted_by, temporal = self._table_row(table)
        schema = self.schema(table)
        small = [s for s in self._shards(table) if s[2] < target]
        if len(small) < 2:
            return 0
        if temporal:
            # keep time-correlated shards together: order by temporal
            # min, then greedily batch consecutive runs up to target
            def tmin(shard):
                st = json.loads(shard[4]).get(temporal)
                return st[0] if st else float("inf")

            small.sort(key=tmin)
        groups: List[list] = [[]]
        acc = 0
        for s in small:
            if acc + s[2] > target and groups[-1]:
                groups.append([])
                acc = 0
            groups[-1].append(s)
            acc += s[2]
        eliminated = 0
        for group in groups:
            if len(group) < 2:
                continue
            pages = [self._pcf(node, su).read_stripe(0)
                     for su, node, *_ in group]
            # all shard files share the table dictionary: plain concat
            old = [(su, node) for su, node, *_ in group]
            with self._db:  # atomic metadata swap
                self._db.executemany(
                    "DELETE FROM shards WHERE shard_uuid = ?",
                    [(su,) for su, _ in old])
                self._write_shards(tid, table, schema, pages)
            for su, node in old:
                self._files.pop(f"{node}/{su}", None)
                try:
                    os.unlink(self._shard_path(node, su))
                except FileNotFoundError:
                    pass
            eliminated += len(group)
        return eliminated

    def rebalance(self) -> int:
        """Move shards so per-node byte totals even out (raptor's bucket
        balancer).  Returns the number of shards moved."""
        rows = self._db.execute(
            "SELECT shard_uuid, node, data_bytes FROM shards").fetchall()
        load = {n: 0 for n in self.nodes}
        for _, node, b in rows:
            load[node] = load.get(node, 0) + b
        moved = 0
        for shard_uuid, node, nbytes in sorted(rows, key=lambda r: -r[2]):
            donor = max(load, key=load.get)
            receiver = min(load, key=load.get)
            if node != donor or donor == receiver:
                continue
            if load[donor] - load[receiver] <= nbytes:
                continue
            src = self._shard_path(node, shard_uuid)
            dst = self._shard_path(receiver, shard_uuid)
            shutil.move(src, dst)
            with self._db:
                self._db.execute(
                    "UPDATE shards SET node = ? WHERE shard_uuid = ?",
                    (receiver, shard_uuid))
            self._files.pop(f"{node}/{shard_uuid}", None)
            load[donor] -= nbytes
            load[receiver] += nbytes
            moved += 1
        return moved

    def restore_missing(self) -> int:
        """Re-copy shard files lost from a node out of the backup store
        (raptor's ShardRecoveryManager).  Returns shards restored."""
        if not self.backup_root:
            raise ValueError("shardstore has no backup_root configured")
        restored = 0
        for shard_uuid, node in self._db.execute(
                "SELECT shard_uuid, node FROM shards"):
            path = self._shard_path(node, shard_uuid)
            if os.path.exists(path):
                continue
            bak = os.path.join(self.backup_root, shard_uuid + ".pcf")
            if not os.path.exists(bak):
                raise FileNotFoundError(
                    f"shard {shard_uuid} missing and not in backup")
            shutil.copyfile(bak, path)
            self._files.pop(f"{node}/{shard_uuid}", None)
            restored += 1
        return restored

    def shard_info(self, table: str) -> List[dict]:
        """system-table style shard listing (raptor system.shards)."""
        return [
            {"shard_uuid": su, "node": node, "row_count": rc,
             "data_bytes": b, "stats": json.loads(st)}
            for su, node, rc, b, st in self._shards(table)
        ]


class _ShardTx:
    """Staged write list (ConnectorTransactionHandle analog)."""

    def __init__(self):
        self.ops: list = []
