"""PCF — the engine's ORC-class single-file columnar format.

Reference analog: ``presto-orc`` (``orc/OrcReader.java``,
``OrcRecordReader.java``, ``writer/``) — a self-describing file of
row-group *stripes*, each holding per-column byte ranges with stats,
adaptive encodings and block compression, read lazily (only the
selected columns of the selected stripes ever leave disk).

Layout (little-endian)::

    [stripe 0 column chunks][stripe 1 column chunks]...
    [footer JSON][footer-length u32][b"PCF1"]

Each column chunk is the column's dtype bytes (+ packed validity
bitmap) under an optional codec.  The footer carries the schema,
table-level dictionaries (the engine's dictionary-coded VARCHAR), and
per-stripe, per-column: byte ranges, dtype/shape, codec, encoding,
min/max/null stats.

TPU-first choices vs ORC:
- chunks are raw numpy dtype bytes, not stream-encoded values — the
  device wants dense arrays; zero parse cost on the scan path;
- per-stripe ADAPTIVE DICTIONARY encoding applies to raw-varchar byte
  matrices (<=255 distinct values and a byte saving -> uint8 codes +
  a stripe-local dictionary), mirroring ORC's dictionary encoding
  decision per stripe;
- codecs are the stdlib's real compressors (zlib, lzma) chosen per
  column chunk (ORC offers zlib/LZ4/ZSTD/Snappy).
"""

from __future__ import annotations

import json
import lzma
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import Type, parse_type

MAGIC = b"PCF1"

_CODECS = {
    "raw": (lambda b: b, lambda b: b),
    "zlib": (lambda b: zlib.compress(b, 6), zlib.decompress),
    "lzma": (lambda b: lzma.compress(b, preset=1), lzma.decompress),
}


def _type_str(t: Type) -> str:
    if t.is_decimal:
        return f"decimal({t.precision},{t.scale})"
    if t.is_raw_string:
        return f"raw_varchar({t.precision})"
    if t.is_binary:
        return f"varbinary({t.precision})"
    return t.name


def _col_stats(data: np.ndarray, valid: np.ndarray, t: Type) -> dict:
    out = {"nulls": int((~valid).sum())}
    if data.ndim == 1 and not t.is_string and valid.any():
        live = data[valid]
        if np.issubdtype(data.dtype, np.integer):
            out["min"], out["max"] = int(live.min()), int(live.max())
        elif np.issubdtype(data.dtype, np.floating):
            out["min"], out["max"] = float(live.min()), float(live.max())
    return out


class PcfWriter:
    """Streaming stripe writer: feed pages, each page becomes one
    stripe (the caller controls stripe granularity the way the
    reference's writer flushes at stripe size)."""

    def __init__(self, path: str, schema: Sequence[Tuple[str, Type]],
                 compression: str = "zlib",
                 dictionaries: Optional[Dict[str, Sequence[str]]] = None):
        if compression not in _CODECS:
            raise ValueError(f"unknown codec {compression!r}")
        self.path = path
        self.schema = list(schema)
        self.compression = compression
        self.dictionaries: Dict[str, List[str]] = {
            k: list(v) for k, v in (dictionaries or {}).items()}
        self._f = open(path, "wb")
        self._stripes: List[dict] = []
        self._closed = False

    # -- encoding decisions -------------------------------------------------
    def _encode_column(self, col: str, t: Type, data: np.ndarray,
                       valid: np.ndarray) -> Tuple[bytes, dict]:
        meta: dict = {"dtype": str(data.dtype), "shape": list(data.shape[1:]),
                      "enc": "direct"}
        payload = np.ascontiguousarray(data).tobytes()
        if (t.is_raw_string or t.is_binary) and data.ndim == 2 and len(data):
            # adaptive dictionary encoding: unique byte rows -> uint8
            # codes + stripe-local dictionary (OrcWriter's per-stripe
            # DICTIONARY_V2 decision)
            uniq, codes = np.unique(data, axis=0, return_inverse=True)
            if len(uniq) <= 255:
                encoded = codes.astype(np.uint8).tobytes()
                dict_bytes = uniq.tobytes()
                if len(encoded) + len(dict_bytes) < len(payload):
                    meta["enc"] = "dict"
                    meta["dict_rows"] = int(len(uniq))
                    payload = encoded + dict_bytes
        return payload, meta

    def write_page(self, page: Page) -> None:
        assert not self._closed
        p = page.compact_host()
        n = int(np.asarray(p.num_rows()))
        cols: Dict[str, dict] = {}
        encode, _ = _CODECS[self.compression]
        for (col, t), b in zip(self.schema, p.blocks):
            data = np.asarray(b.data)[:n]
            valid = np.asarray(b.valid)[:n]
            if t.is_string and not t.is_raw_string and b.dictionary is not None:
                if col not in self.dictionaries:
                    self.dictionaries[col] = list(b.dictionary.values)
                elif self.dictionaries[col] != list(b.dictionary.values):
                    # codes are stored as-is and decoded against the
                    # FIRST page's dictionary; a different dictionary on
                    # a later page would silently decode to wrong values
                    raise ValueError(
                        f"column {col!r}: page dictionary differs from the "
                        "file's dictionary (PCF stores one table "
                        "dictionary per varchar column; re-encode the "
                        "page to the first page's dictionary)")
            payload, meta = self._encode_column(col, t, data, valid)
            body = encode(payload)
            codec = self.compression
            if len(body) >= len(payload):
                body, codec = payload, "raw"  # incompressible: store raw
            vbytes = np.packbits(valid).tobytes()
            off = self._f.tell()
            self._f.write(body)
            voff = self._f.tell()
            self._f.write(vbytes)
            meta.update({"off": off, "len": len(body), "voff": voff,
                         "vlen": len(vbytes), "codec": codec,
                         "raw_len": len(payload)})
            meta.update(_col_stats(data, valid, t))
            cols[col] = meta
        self._stripes.append({"rows": n, "columns": cols})

    def close(self) -> None:
        if self._closed:
            return
        footer = {
            "schema": [[c, _type_str(t)] for c, t in self.schema],
            "dictionaries": self.dictionaries,
            "stripes": self._stripes,
        }
        fj = json.dumps(footer).encode()
        self._f.write(fj)
        self._f.write(len(fj).to_bytes(4, "little"))
        self._f.write(MAGIC)
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_pcf(path: str, schema, pages, compression: str = "zlib",
              dictionaries=None) -> None:
    with PcfWriter(path, schema, compression, dictionaries) as w:
        for p in pages:
            w.write_page(p)


class PcfFile:
    """Lazy reader: the footer is parsed once; column chunks are read
    with per-chunk seeks only when asked for (OrcRecordReader's
    included-columns projection)."""

    def __init__(self, path: str):
        self.path = path
        self.bytes_read = 0  # observable laziness (tests + EXPLAIN)
        with open(path, "rb") as f:
            f.seek(-8, os.SEEK_END)
            tail = f.read(8)
            if tail[4:] != MAGIC:
                raise ValueError(f"{path}: not a PCF file")
            flen = int.from_bytes(tail[:4], "little")
            f.seek(-8 - flen, os.SEEK_END)
            footer = json.loads(f.read(flen))
        self.schema: List[Tuple[str, Type]] = [
            (c, parse_type(t)) for c, t in footer["schema"]]
        self._dict_values = footer["dictionaries"]
        self._dicts: Dict[str, Optional[Dictionary]] = {}
        self.stripes: List[dict] = footer["stripes"]

    @property
    def num_stripes(self) -> int:
        return len(self.stripes)

    def stripe_rows(self, i: int) -> int:
        return self.stripes[i]["rows"]

    def stripe_stats(self, i: int) -> Dict[str, Tuple[float, float]]:
        out = {}
        for col, m in self.stripes[i]["columns"].items():
            if "min" in m:
                out[col] = (m["min"], m["max"])
        return out

    def dictionary_for(self, column: str) -> Optional[Dictionary]:
        if column not in self._dicts:
            vals = self._dict_values.get(column)
            self._dicts[column] = Dictionary(vals) if vals is not None else None
        return self._dicts[column]

    def _read_range(self, f, off: int, ln: int) -> bytes:
        f.seek(off)
        self.bytes_read += ln
        return f.read(ln)

    def read_column(self, stripe: int, column: str):
        """(data, valid) numpy arrays for one column of one stripe."""
        s = self.stripes[stripe]
        m = s["columns"][column]
        n = s["rows"]
        with open(self.path, "rb") as f:
            body = self._read_range(f, m["off"], m["len"])
            vbytes = self._read_range(f, m["voff"], m["vlen"])
        _, decode = _CODECS[m["codec"]]
        payload = decode(body)
        dtype = np.dtype(m["dtype"])
        shape = tuple(m["shape"])
        if m.get("enc") == "dict":
            k = m["dict_rows"]
            codes = np.frombuffer(payload[:n], dtype=np.uint8)
            local = np.frombuffer(payload[n:], dtype=dtype).reshape((k,) + shape)
            data = local[codes]
        else:
            data = np.frombuffer(payload, dtype=dtype).reshape((n,) + shape)
        valid = np.unpackbits(
            np.frombuffer(vbytes, dtype=np.uint8))[:n].astype(bool)
        return data, valid

    def read_stripe(self, stripe: int, columns: Optional[Sequence[str]] = None,
                    capacity: Optional[int] = None) -> Page:
        names = [c for c, _ in self.schema]
        want = list(columns) if columns is not None else names
        types = dict(self.schema)
        cols, valids, dicts, ts = [], [], [], []
        n = self.stripes[stripe]["rows"]
        for c in want:
            data, valid = self.read_column(stripe, c)
            cols.append(data)
            valids.append(valid)
            ts.append(types[c])
            dicts.append(self.dictionary_for(c))
        return Page.from_arrays(cols, ts, valids=valids, dictionaries=dicts,
                                capacity=capacity or max(n, 1))


class PcfConnector:
    """Connector over a directory of ``<table>.pcf`` files: stripes are
    splits, stripe stats drive split pruning, and scans read only the
    projected columns (the presto-orc + raptor storage role behind the
    standard connector protocol)."""

    def __init__(self, root: str):
        self.root = root
        self._files: Dict[str, PcfFile] = {}

    def _file(self, table: str) -> PcfFile:
        if table not in self._files:
            self._files[table] = PcfFile(os.path.join(self.root, table + ".pcf"))
        return self._files[table]

    def table_names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(f[:-4] for f in os.listdir(self.root)
                      if f.endswith(".pcf"))

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return list(self._file(table).schema)

    def num_splits(self, table: str) -> int:
        return self._file(table).num_stripes

    def row_count(self, table: str) -> int:
        f = self._file(table)
        return sum(f.stripe_rows(i) for i in range(f.num_stripes))

    def split_stats(self, table: str, split: int):
        return self._file(table).stripe_stats(split)

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        f = self._file(table)
        t = dict(f.schema)[column]
        if t.is_string and not t.is_raw_string:
            d = f.dictionary_for(column)
            return (0, len(d) - 1) if d is not None else None
        los, his = [], []
        for i in range(f.num_stripes):
            st = f.stripe_stats(i).get(column)
            if st is None:
                return None
            los.append(st[0])
            his.append(st[1])
        if not los or not all(isinstance(v, int) for v in los + his):
            return None
        return (min(los), max(his))

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        return self._file(table).dictionary_for(column)

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        return self._file(table).read_stripe(split, columns=columns,
                                             capacity=capacity)
