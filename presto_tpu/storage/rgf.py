"""RGF (row-group file): the engine's second columnar file format.

Reference analog: ``presto-rcfile`` (9k LoC) — RCFile row groups with a
key section (lengths) + per-column value sections, **sync markers** so
a reader handed an arbitrary byte range of a huge file can resync to
the next row-group boundary (the property HDFS-style splittable scans
depend on; ``rcfile/RcFileReader.java`` sync logic), and two serdes
(binary / text).

Redesign, not a port:

- Each row group = [16-byte file sync marker][u32 header len][JSON
  header][per-column payload].  The header carries row count and
  per-column byte lengths, so columns project without reading their
  neighbours (RCFile's key-section role).
- ``binary`` serde stores validity bitmap + little-endian fixed-width
  values (dictionary varchar stores codes; the file-level footer keeps
  the dictionaries).  ``text`` serde stores newline-joined UTF-8 text
  fields — the LazyBinary vs ColumnarSerDe pair.
- Splits are BYTE RANGES, not stripe ids: ``RgfConnector`` carves a
  file into ``split_bytes`` ranges; a range reads exactly the groups
  whose sync marker begins inside it (resync semantics), so ranges
  compose to the whole file with no overlap — unlike PCF, whose reader
  walks a footer stripe index.  The two formats therefore exercise two
  genuinely different scan architectures.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Block, Dictionary, Page
from presto_tpu.storage.pcf import _type_str
from presto_tpu.types import Type, parse_type

_MAGIC = b"RGF1"


class RgfWriter:
    """Stream row groups; footer holds schema + dictionaries."""

    def __init__(self, path: str, schema: Sequence[Tuple[str, Type]],
                 serde: str = "binary", compress: bool = True):
        if serde not in ("binary", "text"):
            raise ValueError(f"unknown serde {serde!r}")
        self.path = path
        self.schema = list(schema)
        self.serde = serde
        self.compress = compress
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        # per-file random sync marker (RcFileWriter writes one per file)
        self.sync = os.urandom(16)
        self._f.write(self.sync)
        self._dicts: Dict[str, List[str]] = {}
        self._rows = 0

    def write_page(self, page: Page) -> None:
        p = page.compact_host()
        n = int(np.asarray(p.row_mask).sum())
        if n == 0:
            return
        self._rows += n
        payloads: List[bytes] = []
        for (col, t), b in zip(self.schema, p.blocks):
            data = np.asarray(b.data)[:n]
            valid = np.asarray(b.valid)[:n]
            if t.is_string and not t.is_raw_string and b.dictionary is not None:
                known = self._dicts.setdefault(col, list(b.dictionary.values))
                if known != list(b.dictionary.values):
                    # same contract as PcfWriter: one dictionary per file
                    if known != list(b.dictionary.values)[:len(known)]:
                        raise ValueError(
                            f"column {col!r}: page dictionary differs from "
                            "the file's dictionary")
                    self._dicts[col] = list(b.dictionary.values)
            if self.serde == "text":
                txt = "\n".join(
                    "" if not v else _to_text(d, t, self._dicts.get(col))
                    for d, v in zip(data.tolist(), valid.tolist()))
                payloads.append(txt.encode())
            else:
                payloads.append(np.packbits(valid).tobytes()
                                + np.ascontiguousarray(data).tobytes())
        raw = b"".join(payloads)
        codec = "raw"
        if self.compress:
            z = zlib.compress(raw, 1)
            if len(z) < len(raw):
                raw, codec = z, "zlib"
        header = json.dumps({
            "n": n, "codec": codec,
            "lens": [len(x) for x in payloads],
        }).encode()
        self._f.write(self.sync)
        self._f.write(struct.pack("<I", len(header)))
        self._f.write(header)
        self._f.write(struct.pack("<Q", len(raw)))
        self._f.write(raw)

    def close(self) -> None:
        footer = json.dumps({
            "schema": [[c, _type_str(t)] for c, t in self.schema],
            "serde": self.serde,
            "rows": self._rows,
            "dictionaries": self._dicts,
        }).encode()
        off = self._f.tell()
        self._f.write(footer)
        self._f.write(struct.pack("<Q", off))
        self._f.write(_MAGIC)
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_rgf(path: str, schema, pages, serde: str = "binary",
              compress: bool = True) -> None:
    with RgfWriter(path, schema, serde=serde, compress=compress) as w:
        for p in pages:
            w.write_page(p)


def _to_text(v, t: Type, dic: Optional[List[str]]) -> str:
    if t.is_string and dic is not None:
        return dic[int(v)]
    if t.name == "boolean":
        return "true" if v else "false"
    return str(v)


def _from_text(s: str, t: Type, index: Dict[str, int]):
    if t.is_string:
        return index[s]
    if t.name == "boolean":
        return s == "true"
    if np.issubdtype(t.np_dtype, np.integer):
        return int(s)
    return float(s)


class RgfFile:
    """Reader: footer-free byte-range scans via sync-marker resync."""

    def __init__(self, path: str):
        self.path = path
        self.size = os.path.getsize(path)
        with open(path, "rb") as f:
            assert f.read(4) == _MAGIC, f"not an RGF file: {path}"
            self.sync = f.read(16)
            f.seek(-12, io.SEEK_END)
            foot_off = struct.unpack("<Q", f.read(8))[0]
            assert f.read(4) == _MAGIC, f"truncated RGF file: {path}"
            f.seek(foot_off)
            footer = json.loads(
                f.read(self.size - 12 - foot_off).decode())
        self.schema = [(c, parse_type(t)) for c, t in footer["schema"]]
        self.serde = footer["serde"]
        self.rows = footer["rows"]
        self.dictionaries = {
            c: Dictionary(v) for c, v in footer["dictionaries"].items()}
        self.data_start = 4 + 16
        self.data_end = foot_off
        self.bytes_read = 0

    def _resync(self, f, lo: int) -> int:
        """First sync-marker position at or after ``lo`` (RCFile's
        readSync scan): scan forward for the 16-byte marker."""
        if lo <= self.data_start:
            return self.data_start
        base = lo  # file position of window[0]
        f.seek(base)
        window = b""
        while base + len(window) - 15 < self.data_end:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            tail = window[-15:]
            base += len(window) - len(tail)
            window = tail + chunk
            i = window.find(self.sync)
            if i >= 0:
                return base + i
        return self.data_end

    def read_range(self, lo: int, hi: int,
                   columns: Optional[Sequence[str]] = None) -> List[Page]:
        """All row groups whose sync marker starts in [lo, hi) — ranges
        tile a file exactly (each group belongs to ONE range)."""
        cols = [c for c, _ in self.schema]
        keep = ([cols.index(c) for c in columns] if columns is not None
                else list(range(len(cols))))
        pages: List[Page] = []
        with open(self.path, "rb") as f:
            pos = self._resync(f, lo)
            while pos < min(hi, self.data_end):
                f.seek(pos)
                marker = f.read(16)
                if marker != self.sync:
                    break  # corrupt / end
                (hlen,) = struct.unpack("<I", f.read(4))
                header = json.loads(f.read(hlen).decode())
                (plen,) = struct.unpack("<Q", f.read(8))
                raw = f.read(plen)
                self.bytes_read += 16 + 4 + hlen + 8 + plen
                if header["codec"] == "zlib":
                    raw = zlib.decompress(raw)
                pages.append(self._decode_group(header, raw, keep))
                pos = f.tell()
        return pages

    def _decode_group(self, header: dict, raw: bytes,
                      keep: Sequence[int]) -> Page:
        n = header["n"]
        lens = header["lens"]
        offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
        blocks = []
        for i in keep:
            col, t = self.schema[i]
            chunk = raw[offs[i]:offs[i + 1]]
            dic = self.dictionaries.get(col)
            if self.serde == "text":
                fields = chunk.decode().split("\n") if chunk else []
                index = ({v: j for j, v in enumerate(dic.values)}
                         if dic else {})
                valid = np.asarray([s != "" for s in fields], dtype=np.bool_)
                data = np.asarray(
                    [_from_text(s, t, index) if s != "" else 0
                     for s in fields], dtype=t.np_dtype)
            else:
                vbytes = (n + 7) // 8
                valid = np.unpackbits(
                    np.frombuffer(chunk[:vbytes], dtype=np.uint8)
                )[:n].astype(bool)
                data = np.frombuffer(chunk[vbytes:], dtype=t.np_dtype)
                data = data.reshape((n,) + t.value_shape)
            blocks.append(Block(data.copy(), valid, t, dic))
        return Page(tuple(blocks), np.ones(n, dtype=np.bool_))


class RgfConnector:
    """Directory of ``<table>.rgf`` files; splits are byte ranges."""

    def __init__(self, root: str, split_bytes: int = 1 << 22):
        self.root = root
        self.split_bytes = int(split_bytes)
        self._files: Dict[str, RgfFile] = {}

    def _file(self, table: str) -> RgfFile:
        f = self._files.get(table)
        if f is None:
            f = self._files[table] = RgfFile(
                os.path.join(self.root, table + ".rgf"))
        return f

    def table_names(self) -> List[str]:
        return sorted(f[:-4] for f in os.listdir(self.root)
                      if f.endswith(".rgf"))

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return list(self._file(table).schema)

    def row_count(self, table: str) -> int:
        return self._file(table).rows

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        return self._file(table).dictionaries.get(column)

    def column_domain(self, table: str, column: str):
        d = self.dictionary_for(table, column)
        return (0, len(d) - 1) if d is not None else None

    def _ranges(self, table: str) -> List[Tuple[int, int]]:
        f = self._file(table)
        out = []
        lo = f.data_start
        while lo < f.data_end:
            hi = min(lo + self.split_bytes, f.data_end)
            out.append((lo, hi))
            lo = hi
        return out or [(f.data_start, f.data_end)]

    def num_splits(self, table: str) -> int:
        return len(self._ranges(table))

    def page_for_split(self, table: str, split: int,
                       capacity: Optional[int] = None,
                       columns: Optional[Sequence[str]] = None) -> Page:
        from presto_tpu.page import concat_pages_host

        lo, hi = self._ranges(table)[split]
        pages = self._file(table).read_range(lo, hi)
        if not pages:
            return Page.empty([t for _, t in self.schema(table)], 1)
        if len(pages) == 1:
            return pages[0]
        return concat_pages_host(pages)
