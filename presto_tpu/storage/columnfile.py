"""Columnar on-disk storage: split-per-file column chunks.

Reference analog: the storage tier — ``presto-orc`` (columnar
reader/writer with per-column streams, stats-based predicate pushdown)
and ``presto-raptor`` (engine-native shards on local disk + metadata).
Redesigned for the TPU ingest path: each split is one .npz of raw
column arrays + validity bitmaps (zero parse cost, mmap-friendly,
dtype-preserving — the device wants dense arrays, not byte streams),
with table metadata (schema, dictionaries, per-split column min/max
stats) in a JSON sidecar.  Split-level min/max stats drive split
pruning, the role ORC stripe stats play in the reference's
predicate-pushdown scan.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import Type, parse_type

_META = "meta.json"


def _type_str(t: Type) -> str:
    if t.is_decimal:
        return f"decimal({t.precision},{t.scale})"
    if t.is_raw_string:
        return f"raw_varchar({t.precision})"
    return t.name


def write_table(
    root: str,
    name: str,
    schema: Sequence[Tuple[str, Type]],
    pages: Sequence[Page],
    dictionaries: Optional[Dict[str, Sequence[str]]] = None,
    compression: Optional[str] = None,
) -> None:
    """Write a table: one compacted .npz per input page (= one split).

    ``compression='zlib'`` deflate-compresses every column chunk (the
    reference's ORC writer offers LZ4/ZSTD/Snappy/zlib — zlib is the
    stdlib codec here); the default stays raw so the scan hot path
    keeps its zero-parse-cost reads."""
    tdir = os.path.join(root, name)
    os.makedirs(tdir, exist_ok=True)
    save = np.savez_compressed if compression == "zlib" else np.savez
    if compression not in (None, "zlib"):
        raise ValueError(f"unknown compression {compression!r}")
    split_stats: List[Dict] = []
    dicts: Dict[str, List[str]] = dict(dictionaries or {})
    for i, page in enumerate(pages):
        p = page.compact_host()
        n = int(np.asarray(p.num_rows()))
        arrays = {}
        stats: Dict[str, Tuple[float, float]] = {}
        for (col, t), b in zip(schema, p.blocks):
            data = np.asarray(b.data)[:n]
            valid = np.asarray(b.valid)[:n]
            arrays[f"{col}.data"] = data
            arrays[f"{col}.valid"] = np.packbits(valid)
            if t.is_string and col not in dicts and b.dictionary is not None:
                dicts[col] = list(b.dictionary.values)
            if n and not t.is_string and valid.any():
                live = data[valid]
                stats[col] = (int(live.min()), int(live.max())) if np.issubdtype(
                    data.dtype, np.integer
                ) else (float(live.min()), float(live.max()))
        save(os.path.join(tdir, f"split{i:06d}.npz"), rows=np.asarray(n), **arrays)
        split_stats.append({"rows": n, "stats": stats})
    meta = {
        "schema": [[c, _type_str(t)] for c, t in schema],
        "splits": len(pages),
        "split_stats": split_stats,
        "dictionaries": dicts,
        "compression": compression,
    }
    with open(os.path.join(tdir, _META), "w") as f:
        json.dump(meta, f)


class FileConnector:
    """Reads tables written by write_table; split pruning via the
    sidecar min/max stats (the scan-level TupleDomain pushdown role)."""

    def __init__(self, root: str):
        self.root = root
        self._meta: Dict[str, dict] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}

    def _m(self, table: str) -> dict:
        if table not in self._meta:
            with open(os.path.join(self.root, table, _META)) as f:
                self._meta[table] = json.load(f)
        return self._meta[table]

    # -- connector protocol -------------------------------------------------
    def table_names(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.exists(os.path.join(self.root, d, _META))
        )

    def schema(self, table: str) -> List[Tuple[str, Type]]:
        return [(c, parse_type(t)) for c, t in self._m(table)["schema"]]

    def num_splits(self, table: str) -> int:
        return self._m(table)["splits"]

    def row_count(self, table: str) -> int:
        return sum(s["rows"] for s in self._m(table)["split_stats"])

    def split_stats(self, table: str, split: int) -> Dict[str, Tuple[float, float]]:
        return self._m(table)["split_stats"][split]["stats"]

    def column_domain(self, table: str, column: str) -> Optional[Tuple[int, int]]:
        t = dict(self.schema(table))[column]
        if t.is_string:
            d = self.dictionary_for(table, column)
            return (0, len(d) - 1) if d is not None else None
        los, his = [], []
        for s in self._m(table)["split_stats"]:
            st = s["stats"].get(column)
            if st is None:
                return None
            los.append(st[0])
            his.append(st[1])
        if not los or not all(isinstance(v, int) for v in los + his):
            return None
        return (min(los), max(his))

    def dictionary_for(self, table: str, column: str) -> Optional[Dictionary]:
        t = dict(self.schema(table))[column]
        if not t.is_string:
            return None
        tcache = self._dicts.setdefault(table, {})
        if column not in tcache:
            vals = self._m(table)["dictionaries"].get(column)
            tcache[column] = Dictionary(vals) if vals is not None else None
        return tcache[column]

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page:
        path = os.path.join(self.root, table, f"split{split:06d}.npz")
        z = np.load(path)
        n = int(z["rows"])
        schema = self.schema(table)
        cols, valids, dicts = [], [], []
        for col, t in schema:
            data = z[f"{col}.data"]
            valid = np.unpackbits(z[f"{col}.valid"])[:n].astype(bool)
            cols.append(data)
            valids.append(valid)
            dicts.append(self.dictionary_for(table, col))
        return Page.from_arrays(cols, [t for _, t in schema], valids=valids,
                                dictionaries=dicts, capacity=capacity or max(n, 1))
