"""Catalog / metadata layer.

Reference analog: ``presto-main/.../metadata/MetadataManager.java`` (the
engine-facing facade over connectors) plus the connector metadata SPI
(``presto-spi/.../connector/ConnectorMetadata.java``).  Kept deliberately
small: a Connector exposes schemas, splits and Pages; the Catalog maps
``table`` names to connectors and serves column stats (min/max domains)
that the planner uses for exact key packing (see ops/aggregate.py
pack_or_hash_keys) — the analog of the reference's table statistics path
(``spi/statistics/TableStatistics.java`` via ``metadata/MetadataManager``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Protocol, Sequence, Tuple

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import Type


class Connector(Protocol):
    """Data-source contract (ConnectorMetadata + ConnectorSplitManager +
    ConnectorPageSourceProvider rolled together; reference:
    presto-spi/.../connector/)."""

    def table_names(self) -> List[str]: ...

    def schema(self, table: str) -> List[Tuple[str, Type]]: ...

    def num_splits(self, table: str) -> int: ...

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page: ...

    def row_count(self, table: str) -> int: ...


@dataclasses.dataclass(frozen=True)
class ColumnHandle:
    """Resolved column: position in the scan output + type + stats."""

    name: str
    type: Type
    index: int
    domain: Optional[Tuple[int, int]] = None  # known (lo, hi) in device repr
    dictionary: Optional[Dictionary] = None
    ndv: Optional[int] = None  # distinct values when domain width overstates


@dataclasses.dataclass(frozen=True)
class TableHandle:
    connector_name: str
    table: str
    columns: Tuple[ColumnHandle, ...]
    row_count: int
    num_splits: int
    primary_key: Optional[Tuple[str, ...]] = None

    def column(self, name: str) -> Optional[ColumnHandle]:
        for c in self.columns:
            if c.name == name:
                return c
        return None


class Catalog:
    """Connector registry + name resolution (MetadataManager analog)."""

    def __init__(self):
        self._connectors: Dict[str, object] = {}
        # target for CREATE TABLE AS (the reference routes writes to the
        # connector named in the qualified table name; flat namespace
        # here routes to a designated writable connector)
        self.write_connector: Optional[str] = None

    def register(self, name: str, connector, writable: bool = False) -> None:
        self._connectors[name] = connector
        if writable or (self.write_connector is None and hasattr(connector, "create_table")):
            self.write_connector = name

    def connector(self, name: str):
        return self._connectors[name]

    def resolve(self, table: str) -> TableHandle:
        """Find ``table`` in any registered connector, or resolve a
        ``catalog.table`` qualified name against the named connector
        (the reference's catalog.schema.table triple collapses to
        catalog[.table] — there is a single default schema)."""
        items = self._connectors.items()
        if "." in table:
            cname, bare = table.split(".", 1)
            if cname in self._connectors:
                items = [(cname, self._connectors[cname])]
                table = bare
        for cname, conn in items:
            if table in conn.table_names():
                schema = conn.schema(table)
                cols = []
                for i, (col, t) in enumerate(schema):
                    dom = None
                    dic = None
                    ndv = None
                    if hasattr(conn, "column_domain"):
                        dom = conn.column_domain(table, col)
                    if hasattr(conn, "dictionary_for"):
                        dic = conn.dictionary_for(table, col)
                    if hasattr(conn, "column_ndv"):
                        ndv = conn.column_ndv(table, col)
                    cols.append(ColumnHandle(col, t, i, dom, dic, ndv))
                pk = None
                if hasattr(conn, "primary_key"):
                    got = conn.primary_key(table)
                    pk = tuple(got) if got else None
                return TableHandle(
                    connector_name=cname,
                    table=table,
                    columns=tuple(cols),
                    row_count=conn.row_count(table),
                    num_splits=conn.num_splits(table),
                    primary_key=pk,
                )
        raise KeyError(f"table not found in any catalog: {table}")
