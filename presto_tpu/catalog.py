"""Catalog / metadata layer.

Reference analog: ``presto-main/.../metadata/MetadataManager.java`` (the
engine-facing facade over connectors) plus the connector metadata SPI
(``presto-spi/.../connector/ConnectorMetadata.java``).  Kept deliberately
small: a Connector exposes schemas, splits and Pages; the Catalog maps
``table`` names to connectors and serves column stats (min/max domains)
that the planner uses for exact key packing (see ops/aggregate.py
pack_or_hash_keys) — the analog of the reference's table statistics path
(``spi/statistics/TableStatistics.java`` via ``metadata/MetadataManager``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Protocol, Sequence, Tuple

from presto_tpu.page import Dictionary, Page
from presto_tpu.types import Type


class Connector(Protocol):
    """Data-source contract (ConnectorMetadata + ConnectorSplitManager +
    ConnectorPageSourceProvider rolled together; reference:
    presto-spi/.../connector/)."""

    def table_names(self) -> List[str]: ...

    def schema(self, table: str) -> List[Tuple[str, Type]]: ...

    def num_splits(self, table: str) -> int: ...

    def page_for_split(self, table: str, split: int, capacity: Optional[int] = None) -> Page: ...

    def row_count(self, table: str) -> int: ...


@dataclasses.dataclass(frozen=True)
class ViewDefinition:
    """Stored view: original SQL + the creation-time session namespace
    its unqualified table references re-bind against
    (metadata/ViewDefinition.java: originalSql, catalog, schema)."""

    sql: str
    catalog: Optional[str] = None
    schema: str = "default"


@dataclasses.dataclass(frozen=True)
class ColumnHandle:
    """Resolved column: position in the scan output + type + stats."""

    name: str
    type: Type
    index: int
    domain: Optional[Tuple[int, int]] = None  # known (lo, hi) in device repr
    dictionary: Optional[Dictionary] = None
    ndv: Optional[int] = None  # distinct values when domain width overstates


@dataclasses.dataclass(frozen=True)
class TableHandle:
    connector_name: str
    table: str
    columns: Tuple[ColumnHandle, ...]
    row_count: int
    num_splits: int
    primary_key: Optional[Tuple[str, ...]] = None

    def column(self, name: str) -> Optional[ColumnHandle]:
        for c in self.columns:
            if c.name == name:
                return c
        return None


class Catalog:
    """Connector registry + name resolution (MetadataManager analog)."""

    def __init__(self):
        self._connectors: Dict[str, object] = {}
        # target for CREATE TABLE AS (the reference routes writes to the
        # connector named in the qualified table name; flat namespace
        # here routes to a designated writable connector)
        self.write_connector: Optional[str] = None
        # schema registry: catalog -> schema names.  Connector table
        # namespaces stay flat; a table in schema s is physically named
        # "s.t" there, and "default" holds the bare names (the reference
        # keeps the triple in each connector's metastore —
        # metadata/MetadataManager.java listSchemaNames).
        self._schemas: Dict[str, set] = {}
        # view registry: (catalog, schema, name) -> ViewDefinition.
        # Views are engine-level metadata here (the reference persists
        # them through ConnectorMetadata.createView; a single in-memory
        # registry plays that role for every connector).
        self._views: Dict[Tuple[str, str, str], "ViewDefinition"] = {}

    def register(self, name: str, connector, writable: bool = False) -> None:
        self._connectors[name] = connector
        self._schemas.setdefault(name, {"default"})
        if writable or (self.write_connector is None and hasattr(connector, "create_table")):
            self.write_connector = name

    # -- schemas -----------------------------------------------------------
    def schemas(self, catalog: str) -> List[str]:
        if catalog not in self._connectors:
            raise KeyError(f"catalog not found: {catalog}")
        return sorted(self._schemas.setdefault(catalog, {"default"}))

    def has_schema(self, catalog: str, schema: str) -> bool:
        return (catalog in self._connectors
                and schema in self._schemas.setdefault(catalog, {"default"}))

    def create_schema(self, catalog: str, schema: str,
                      if_not_exists: bool = False) -> None:
        if catalog not in self._connectors:
            raise KeyError(f"catalog not found: {catalog}")
        ss = self._schemas.setdefault(catalog, {"default"})
        if schema in ss and not if_not_exists:
            raise ValueError(f"schema already exists: {catalog}.{schema}")
        ss.add(schema)

    def schema_tables(self, catalog: str, schema: str) -> List[str]:
        """Bare table names living in ``schema`` of ``catalog``."""
        conn = self._connectors[catalog]
        if schema == "default":
            return [t for t in conn.table_names() if "." not in t]
        pre = schema + "."
        return [t[len(pre):] for t in conn.table_names() if t.startswith(pre)]

    def drop_schema(self, catalog: str, schema: str, if_exists: bool = False,
                    cascade: bool = False) -> None:
        if schema == "default":
            raise ValueError("cannot drop the default schema")
        if not self.has_schema(catalog, schema):
            if if_exists:
                return
            raise KeyError(f"schema not found: {catalog}.{schema}")
        tables = self.schema_tables(catalog, schema)
        views = [k for k in self._views if k[0] == catalog and k[1] == schema]
        if (tables or views) and not cascade:
            raise ValueError(
                f"schema {catalog}.{schema} is not empty (use CASCADE)")
        conn = self._connectors[catalog]
        for t in tables:
            conn.drop_table(f"{schema}.{t}")
        for k in views:
            del self._views[k]
        self._schemas[catalog].discard(schema)

    def rename_schema(self, catalog: str, schema: str, new_name: str) -> None:
        if schema == "default" or new_name == "default":
            raise ValueError("cannot rename to/from the default schema")
        if not self.has_schema(catalog, schema):
            raise KeyError(f"schema not found: {catalog}.{schema}")
        if self.has_schema(catalog, new_name):
            raise ValueError(f"schema already exists: {catalog}.{new_name}")
        conn = self._connectors[catalog]
        for t in self.schema_tables(catalog, schema):
            conn.rename_table(f"{schema}.{t}", f"{new_name}.{t}")
        for k in list(self._views):
            if k[0] == catalog and k[1] == schema:
                self._views[(catalog, new_name, k[2])] = self._views.pop(k)
        ss = self._schemas[catalog]
        ss.discard(schema)
        ss.add(new_name)

    # -- views -------------------------------------------------------------
    def qualify(self, name: str, session=None) -> Tuple[str, str, str]:
        """(catalog, schema, bare) for a possibly-qualified object name,
        filling gaps from the session defaults (Session.getCatalog/
        getSchema in the reference's MetadataUtil.createQualifiedObjectName)."""
        parts = name.split(".")
        s_cat = getattr(session, "catalog", None)
        s_sch = getattr(session, "schema", None) or "default"
        if len(parts) == 3:
            return parts[0], parts[1], parts[2]
        if len(parts) == 2:
            if parts[0] in self._connectors:
                return parts[0], "default", parts[1]
            if s_cat is not None:  # schema-qualified under USE catalog
                return s_cat, parts[0], parts[1]
            return parts[0], "default", parts[1]
        return s_cat or "$any", s_sch, parts[0]

    def create_view(self, name: str, sql: str, session=None,
                    replace: bool = False) -> None:
        key = self.qualify(name, session)
        if not replace and key in self._views:
            raise ValueError(f"view already exists: {'.'.join(key)}")
        self._views[key] = ViewDefinition(
            sql=sql, catalog=getattr(session, "catalog", None),
            schema=getattr(session, "schema", None) or "default")

    def drop_view(self, name: str, session=None, if_exists: bool = False) -> None:
        found = self.lookup_view(name, session)  # same fallback as SELECT
        if found is None:
            if if_exists:
                return
            raise KeyError(
                f"view not found: {'.'.join(self.qualify(name, session))}")
        del self._views[found[0]]

    def lookup_view(self, name: str, session=None):
        """(key, ViewDefinition) or None.  Only when the session has no
        USE context does an unqualified name fall back to any-namespace
        matching (mirroring the flat table search); under USE the
        lookup is schema-scoped, so same-named views in other schemas
        stay invisible."""
        key = self.qualify(name, session)
        v = self._views.get(key)
        if v is None and "." not in name:
            # the sessionless '$any' namespace is global: views created
            # before any USE stay reachable (and droppable) afterwards
            g = ("$any", "default", name)
            if g in self._views:
                key, v = g, self._views[g]
            elif getattr(session, "catalog", None) is None:
                for k, cand in self._views.items():
                    if k[2] == name:
                        key, v = k, cand
                        break
        return (key, v) if v is not None else None

    def views_in(self, catalog: str, schema: str) -> List[str]:
        return sorted(k[2] for k in self._views
                      if k[0] == catalog and k[1] == schema)

    def connector(self, name: str):
        return self._connectors[name]

    def resolve(self, table: str, session=None) -> TableHandle:
        """Find ``table`` in any registered connector, or resolve a
        ``catalog[.schema].table`` qualified name against the named
        connector.  A session's USE defaults are consulted first for
        unqualified names: ``t`` under ``USE c.s`` means the physical
        table ``s.t`` in connector ``c`` (non-default schemas store
        tables schema-prefixed in the connector's flat namespace)."""
        s_cat = getattr(session, "catalog", None)
        s_sch = getattr(session, "schema", None)
        if ("." not in table and s_cat in self._connectors and s_sch
                and s_sch != "default"):
            # under USE catalog.schema an unqualified name means THAT
            # schema — a miss errors rather than silently reading a
            # same-named table elsewhere (MetadataUtil name resolution)
            phys = f"{s_sch}.{table}"
            if phys not in self._connectors[s_cat].table_names():
                raise KeyError(
                    f"table not found: {s_cat}.{s_sch}.{table}")
            table = f"{s_cat}.{phys}"
        items = self._connectors.items()
        if "." in table:
            cname, bare = table.split(".", 1)
            if cname in self._connectors:
                items = [(cname, self._connectors[cname])]
                table = bare
        for cname, conn in items:
            if table in conn.table_names():
                schema = conn.schema(table)
                cols = []
                for i, (col, t) in enumerate(schema):
                    dom = None
                    dic = None
                    ndv = None
                    if hasattr(conn, "column_domain"):
                        dom = conn.column_domain(table, col)
                    if hasattr(conn, "dictionary_for"):
                        dic = conn.dictionary_for(table, col)
                    if hasattr(conn, "column_ndv"):
                        ndv = conn.column_ndv(table, col)
                    cols.append(ColumnHandle(col, t, i, dom, dic, ndv))
                pk = None
                if hasattr(conn, "primary_key"):
                    got = conn.primary_key(table)
                    pk = tuple(got) if got else None
                return TableHandle(
                    connector_name=cname,
                    table=table,
                    columns=tuple(cols),
                    row_count=conn.row_count(table),
                    num_splits=conn.num_splits(table),
                    primary_key=pk,
                )
        raise KeyError(f"table not found in any catalog: {table}")
