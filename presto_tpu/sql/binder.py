"""Semantic analysis + logical planning: AST -> plan tree.

Reference analog: the analyzer/planner stack —
``sql/analyzer/StatementAnalyzer.java`` (name/type resolution, scopes),
``sql/planner/LogicalPlanner.java:137`` + ``QueryPlanner``/
``RelationPlanner`` (AST -> PlanNode DAG), and the key optimizer passes
folded in at build time the way AddExchanges folds distribution:

* predicate pushdown (optimizations/PredicatePushDown.java) — WHERE
  conjuncts routed to their source relations before joins;
* cross-join elimination via the equi-join graph
  (optimizations/EliminateCrossJoins.java) — comma-FROM + WHERE becomes
  a join tree greedily, probe side = largest estimated input
  (DetermineJoinDistributionType.java's build-small heuristic);
* partial-aggregation splitting happens in the executor
  (PushPartialAggregationThroughExchange.java analog);
* subquery decorrelation (TransformCorrelatedScalarAggregationToJoin,
  TransformExistsApplyToLateralNode rules): EXISTS -> semi/anti join,
  correlated scalar aggregates -> grouped-agg join, uncorrelated
  scalar subqueries -> single-row cross join.

Scopes are positional: binding produces ``expr.ir`` trees whose
ColumnRefs index the current plan node's output channels.
"""

from __future__ import annotations

import dataclasses
import datetime
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.catalog import Catalog
from presto_tpu.page import Dictionary
from presto_tpu.expr.ir import AggCall, Call, ColumnRef, Expr, Literal, call, infer_type
from presto_tpu.planner.plan import (
    AggregationNode,
    Channel,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    ValuesNode,
)
from presto_tpu.sql import ast
from presto_tpu.sql.parser import parse_query
from presto_tpu.types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, MICROS_PER_DAY, TIMESTAMP, VARCHAR,
    DecimalType, Type, common_super_type,
)

AGG_FUNCTIONS = {
    "sum", "avg", "count", "min", "max",
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "bool_and", "bool_or", "every",
    # approx_distinct: real HyperLogLog sketch (m=4096), lowered to a
    # two-level aggregation (see _rewrite_approx_distinct)
    "approx_distinct",
    "min_by", "max_by", "approx_percentile",
    "covar_pop", "covar_samp", "corr", "regr_slope", "regr_intercept",
    "checksum", "arbitrary", "count_if", "geometric_mean",
    "skewness", "kurtosis", "bitwise_and_agg", "bitwise_or_agg",
    "array_agg", "map_agg", "histogram", "map_union",
    # HLL sketches as first-class values (spi HyperLogLogType):
    # approx_set builds one, merge unions them, cardinality estimates
    "approx_set", "merge", "numeric_histogram", "multimap_agg",
    # presto-ml analogs: sufficient-statistic training aggregates
    "learn_regressor", "learn_classifier",
    "learn_libsvm_regressor", "learn_libsvm_classifier",
    # KMV set digests (type/setdigest/BuildSetDigestAggregation.java +
    # MergeSetDigestAggregation.java)
    "make_set_digest", "merge_set_digest",
    # presto-ml classifier evaluation (host-finalized string summary)
    "evaluate_classifier_predictions",
}

# Correlated bindings mark outer-scope columns with this offset so a
# conjunct's inner/outer sides are separable after binding.
_OUTER_BASE = 1 << 20

# Window results bind as sentinel channel refs during select binding and
# are patched to real appended-channel indexes once the aggregation's
# channel count is final.
_WIN_BASE = 1 << 24

WINDOW_FUNCTIONS = {
    "row_number", "rank", "dense_rank", "lead", "lag",
    "first_value", "last_value", "ntile", "percent_rank", "cume_dist",
    "nth_value",
} | AGG_FUNCTIONS

# scalar builtins (reference: operator/scalar/ ~130 files; the engine's
# set grows here + in expr/compile.py)
SCALAR_FUNCTIONS = {
    "abs", "sign", "sqrt", "cbrt", "exp", "ln", "log10", "log2", "power", "pow",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "degrees", "radians", "truncate",
    "width_bucket", "is_nan", "is_finite", "is_infinite", "pi", "e",
    "nan", "infinity",
    # bitwise scalars (operator/scalar/BitwiseFunctions.java)
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_shift_left", "bitwise_shift_right", "bit_count",
    # base conversion / binary hashes (VarbinaryFunctions.java)
    "from_base", "to_base", "crc32", "xxhash64", "to_utf8",
    # datetime breadth (DateTimeFunctions.java)
    "date_format", "date_parse", "from_iso8601_date",
    "last_day_of_month", "year_of_week",
    # string breadth (StringFunctions.java)
    "chr", "translate", "normalize", "soundex",
    "levenshtein_distance", "hamming_distance",
    # URL codecs, JSON normalization, binary hash hex forms
    "url_encode", "url_decode", "json_format", "json_parse", "json_size",
    "md5_hex", "sha1_hex", "sha256_hex", "split",
    "ceil", "ceiling", "floor", "round", "mod", "greatest", "least",
    "nullif", "coalesce", "if", "length", "strpos", "upper", "lower",
    "trim", "ltrim", "rtrim", "reverse", "substr",
    "year", "month", "day", "day_of_week", "day_of_year", "quarter", "week",
    "hour", "minute", "second", "millisecond",
    "date_trunc", "date_add", "date_diff", "from_unixtime", "to_unixtime",
    "regexp_like", "regexp_extract", "regexp_replace", "replace",
    "split_part", "lpad", "rpad", "concat", "starts_with", "ends_with",
    "codepoint",
    "json_extract", "json_extract_scalar", "json_array_length", "is_json_scalar",
    "url_extract_host", "url_extract_path", "url_extract_protocol",
    "url_extract_query", "url_extract_port",
    # geospatial (presto-geospatial GeoFunctions.java)
    "st_geometryfromtext", "st_point", "st_distance", "st_contains",
    "st_area", "st_x", "st_y",
    # ML inference (presto-ml regress/classify over array models)
    "regress", "classify", "features",
    # teradata compat (presto-teradata-functions)
    "index", "char2hexint", "nvl",
    # ARRAY / MAP (operator/scalar/ArrayFunctions, MapKeys, MapValues...)
    "cardinality", "contains", "element_at", "array_position",
    "jaccard_index", "intersection_cardinality", "hash_counts",
    "array_min", "array_max", "array_sum", "array_average",
    "array_sort", "array_distinct", "map_keys", "map_values", "map",
    "sequence", "slice", "repeat",
    # ARRAY set algebra + map concat (ArrayIntersectFunction,
    # ArrayUnionFunction, ArrayExceptFunction, ArraysOverlapFunction,
    # ArrayRemoveFunction, MapConcatFunction)
    "array_intersect", "array_union", "array_except", "arrays_overlap",
    "array_remove", "map_concat",
}


class BindError(Exception):
    """User-facing semantic error (SemanticException analog).  ``pos``
    is the character offset into the statement text when the failing
    AST node carried one (parser NodeLocation analog); the statement
    boundary (:meth:`Binder.plan`) renders it as ``line:col``."""

    def __init__(self, message, pos: Optional[int] = None):
        super().__init__(message)
        self.pos = pos


def annotate_position(e: BindError, sql: str) -> BindError:
    """Render a BindError's statement offset as ``line:col`` against
    the statement text (the reference's SemanticException carries a
    NodeLocation the same way).  No-op when no position is known or the
    error was already annotated (structured flag, not a message-text
    sniff — user identifiers may legitimately contain ' at line ')."""
    pos = getattr(e, "pos", None)
    if pos is None or getattr(e, "_annotated", False):
        return e
    line = sql.count("\n", 0, pos) + 1
    col = pos - (sql.rfind("\n", 0, pos) + 1) + 1
    out = BindError(f"{e} at line {line}:{col}", pos=pos)
    out._annotated = True
    return out


@dataclasses.dataclass
class ScopeCol:
    qualifier: Optional[str]
    name: str
    channel: Channel


class Scope:
    """Positional name resolution, optionally chained to an outer
    query's scope (StatementAnalyzer's Scope.java analog).  A parent
    hit resolves to ``len(self) + parent_index`` — the combined index
    space a correlated binding uses to separate inner from outer refs."""

    def __init__(self, cols: Sequence[ScopeCol], parent: Optional["Scope"] = None):
        self.cols = list(cols)
        self.parent = parent

    @classmethod
    def of(cls, node: PlanNode, qualifier: Optional[str] = None) -> "Scope":
        return cls([ScopeCol(qualifier, c.name, c) for c in node.channels])

    def concat(self, other: "Scope") -> "Scope":
        return Scope(self.cols + other.cols)

    def col(self, idx: int) -> ScopeCol:
        if idx < len(self.cols):
            return self.cols[idx]
        return self.parent.col(idx - len(self.cols))

    def resolve(self, qualifier: Optional[str], name: str) -> int:
        hits = [
            i
            for i, c in enumerate(self.cols)
            if c.name == name and (qualifier is None or c.qualifier == qualifier)
        ]
        if not hits:
            if self.parent is not None:
                return len(self.cols) + self.parent.resolve(qualifier, name)
            raise BindError(f"column not found: {qualifier + '.' if qualifier else ''}{name}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column: {name}")
        return hits[0]

    def __len__(self):
        return len(self.cols)


def desugar_quantified(e: ast.Node) -> ast.Node:
    """value op ANY|ALL (subquery) -> existing subquery forms
    (iterative/rule/TransformQuantifiedComparisonApplyToLateralJoin's
    role, done as an AST rewrite):
      = ANY  -> IN        <> ALL -> NOT IN        <> ANY -> NOT (= ALL)
      other op ANY/ALL -> CASE over min/max(S), count(*), count(S) with
      the ANSI edge semantics (the reference QuantifiedComparison
      rewriter's count-based expansion): ALL over empty is TRUE, ANY
      over empty is FALSE, and a non-definitive comparison against a
      set holding NULLs is UNKNOWN."""
    if isinstance(e, ast.Unary) and e.op == "not":
        # NOT (v op ALL/ANY ...) must still desugar underneath
        inner = desugar_quantified(e.operand)
        return e if inner is e.operand else ast.Unary("not", inner)
    if not isinstance(e, ast.QuantifiedComparison):
        return e
    if e.quantifier == "any" and e.op == "=":
        return ast.InSubquery(e.value, e.query, negated=False)
    if e.quantifier == "all" and e.op == "<>":
        return ast.InSubquery(e.value, e.query, negated=True)
    if e.quantifier == "any" and e.op == "<>":
        # v <> ANY S == NOT (v = ALL S) — exact under three-valued logic
        return ast.Unary("not", desugar_quantified(
            dataclasses.replace(e, op="=", quantifier="all")))

    if len(e.query.select) != 1 or isinstance(e.query.select[0].expr,
                                              ast.Star):
        raise BindError("quantified subquery must select one column")

    def scalar(fc: ast.FuncCall) -> ast.ScalarSubquery:
        q = e.query
        # the subquery stays INTACT as a derived table (its ORDER BY /
        # LIMIT apply before the aggregation); only the output column
        # gains a referenceable alias.  Every call builds a FRESH node
        # — subquery planning is keyed by object identity, so shared
        # nodes would double-plan.  KNOWN COST: the CASE forms below
        # re-plan the subquery once per aggregate reference (4-6x); a
        # single derived aggregation computing min/max/count(*)/count
        # together would be 1x (needs multi-column scalar subqueries —
        # future work, quantified comparisons are a rare operator).
        inner = dataclasses.replace(q.select[0], alias="__qc")
        wrapped = ast.Query(
            select=(ast.SelectItem(fc, None),),
            from_=(ast.SubqueryRel(
                dataclasses.replace(q, select=(inner,)), alias="__q"),),
        )
        return ast.ScalarSubquery(wrapped)

    def agg(fn: str) -> ast.FuncCall:
        return ast.FuncCall(fn, (ast.Identifier(("__qc",)),))

    def count_star() -> ast.ScalarSubquery:
        return scalar(ast.FuncCall("count", (), star=True))

    minmax = {("<", "any"): "max", ("<=", "any"): "max",
              (">", "any"): "min", (">=", "any"): "min",
              ("<", "all"): "min", ("<=", "all"): "min",
              (">", "all"): "max", (">=", "all"): "max"}
    key = (e.op, e.quantifier)
    if key in minmax:
        cmp = ast.Binary(e.op, e.value, scalar(agg(minmax[key])))
    elif e.op == "=" and e.quantifier == "all":
        cmp = ast.Binary("and",
                         ast.Binary("=", e.value, scalar(agg("min"))),
                         ast.Binary("=", e.value, scalar(agg("max"))))
    else:
        raise BindError(f"{e.op} {e.quantifier.upper()} (subquery) unsupported")

    true_, false_ = ast.NumberLit("1"), ast.NumberLit("0")
    no_nulls = ast.Binary("=", count_star(), scalar(agg("count")))
    empty = ast.Binary("=", count_star(), ast.NumberLit("0"))
    if e.quantifier == "all":
        whens = (
            (empty, true_),                              # vacuous truth
            (ast.Binary("and", cmp, no_nulls), true_),
            (cmp, ast.NullLit()),       # non-nulls passed, NULLs unknown
            (ast.Unary("not", cmp), false_),             # definite miss
        )
    else:  # any
        whens = (
            (empty, false_),
            (cmp, true_),               # some non-null element satisfies
            (ast.Binary("and", ast.Unary("not", cmp), no_nulls), false_),
        )
    # `CASE ... END = 1` keeps the three-valued result boolean-typed
    # (TRUE/FALSE literals parse as numbers in this grammar)
    return ast.Binary("=", ast.Case(whens=whens, else_=ast.NullLit()),
                      ast.NumberLit("1"))


_INTERVAL_MICROS = {"second": 1_000_000, "minute": 60_000_000,
                    "hour": 3_600_000_000, "day": 86_400_000_000}


def _interval_literal(iv: "ast.IntervalLit"):
    """(type, device value) of an interval literal — micros for the
    day-second family, months for year-month.  Accepts fractional
    seconds ('1.5' SECOND) and the 'Y-M' year-to-month form
    (sql/tree/IntervalLiteral.java + DateTimeUtils.parse*Interval)."""
    from presto_tpu.types import INTERVAL_DAY_SECOND, INTERVAL_YEAR_MONTH

    sign = -1 if iv.negative else 1
    text = iv.value.strip()
    if text.startswith(("-", "+")):  # sign inside the string
        if text[0] == "-":
            sign = -sign
        text = text[1:].strip()
    try:
        if iv.unit in _INTERVAL_MICROS:
            if "." in text:
                n = round(float(text) * _INTERVAL_MICROS[iv.unit])
            else:
                n = int(text) * _INTERVAL_MICROS[iv.unit]
            return INTERVAL_DAY_SECOND, sign * n
        if "-" in text and iv.unit == "year":  # 'Y-M' YEAR TO MONTH
            y, m = text.split("-", 1)
            return INTERVAL_YEAR_MONTH, sign * (int(y) * 12 + int(m))
        return (INTERVAL_YEAR_MONTH,
                sign * int(text) * (12 if iv.unit == "year" else 1))
    except ValueError:
        raise BindError(f"malformed interval literal {iv.value!r}")


def split_conjuncts(node: Optional[ast.Node]) -> List[ast.Node]:
    if node is None:
        return []
    if isinstance(node, ast.Binary) and node.op == "and":
        return split_conjuncts(node.left) + split_conjuncts(node.right)
    return [desugar_quantified(node)]


def expr_refs(e: Expr) -> List[int]:
    if isinstance(e, ColumnRef):
        return [e.index]
    if isinstance(e, Call):
        return [r for a in e.args for r in expr_refs(a)]
    from presto_tpu.expr.ir import LambdaExpr

    if isinstance(e, LambdaExpr):
        return expr_refs(e.body)  # captured outer-channel references
    return []


#: Joda-Time pattern letters -> the MySQL codes date_format speaks
#: (format_datetime's date-field subset; runs of the same letter pick
#: padded vs plain forms as Joda does)
_JODA_RUNS = {
    "yyyy": "%Y", "yy": "%y", "y": "%Y", "MMMM": "%M", "MMM": "%b",
    "MM": "%m", "M": "%c", "dd": "%d", "d": "%e", "EEEE": "%W",
    "EEE": "%a", "E": "%a", "DDD": "%j",
    # 'D' (unpadded day-of-year) has no MySQL code -> rejected
}


def _joda_to_mysql(fmt: str) -> str:
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "'":
            if i + 1 < len(fmt) and fmt[i + 1] == "'":
                out.append("'")  # Joda '' = one literal quote
                i += 2
                continue
            j = i + 1
            lit = []
            while j < len(fmt):
                if fmt[j] == "'":
                    if j + 1 < len(fmt) and fmt[j + 1] == "'":
                        lit.append("'")
                        j += 2
                        continue
                    break
                lit.append(fmt[j])
                j += 1
            else:
                raise BindError("unterminated quote in datetime pattern")
            out.append("".join(lit).replace("%", "%%"))
            i = j + 1
            continue
        if ch.isalpha():
            j = i
            while j < len(fmt) and fmt[j] == ch:
                j += 1
            run = fmt[i:j]
            got = _JODA_RUNS.get(run)
            if got is None:
                raise BindError(
                    f"unsupported datetime pattern letter run '{run}'")
            out.append(got)
            i = j
            continue
        out.append(ch.replace("%", "%%"))
        i += 1
    return "".join(out)


def remap_expr(e: Expr, mapping: Dict[int, int]) -> Expr:
    if isinstance(e, ColumnRef):
        return ColumnRef(type=e.type, index=mapping[e.index], name=e.name)
    if isinstance(e, Call):
        return Call(type=e.type, fn=e.fn, args=tuple(remap_expr(a, mapping) for a in e.args))
    from presto_tpu.expr.ir import LambdaExpr

    if isinstance(e, LambdaExpr):
        return LambdaExpr(type=e.type, params=e.params,
                          body=remap_expr(e.body, mapping))
    return e


def _parse_date(s: str) -> int:
    d = datetime.date.fromisoformat(s)
    return (d - datetime.date(1970, 1, 1)).days


def _parse_timestamp(s: str) -> int:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' -> epoch microseconds."""
    s = s.strip()
    if " " in s or "T" in s:
        dt = datetime.datetime.fromisoformat(s.replace("T", " "))
    else:
        d = datetime.date.fromisoformat(s)
        dt = datetime.datetime(d.year, d.month, d.day)
    delta = dt - datetime.datetime(1970, 1, 1)
    return (delta.days * 86_400 + delta.seconds) * 1_000_000 + delta.microseconds


def _parse_time_of_day(s: str) -> int:
    """'HH:MM:SS[.ffffff]' -> microseconds since midnight."""
    t = datetime.time.fromisoformat(s.strip())
    return ((t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000
            + t.microsecond)


def _shift_date(days: int, n: int, unit: str) -> int:
    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=days)
    if unit == "day":
        d = d + datetime.timedelta(days=n)
    else:
        months = n * (12 if unit == "year" else 1)
        m = d.month - 1 + months
        y = d.year + m // 12
        m = m % 12 + 1
        day = min(d.day, [31, 29 if y % 4 == 0 and (y % 100 != 0 or y % 400 == 0) else 28,
                          31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m - 1])
        d = datetime.date(y, m, day)
    return (d - datetime.date(1970, 1, 1)).days


def _flatten_bool(e: Expr, fn: str) -> List[Expr]:
    if isinstance(e, Call) and e.fn == fn:
        return _flatten_bool(e.args[0], fn) + _flatten_bool(e.args[1], fn)
    return [e]


def _extract_common_or(ir: Expr) -> List[Expr]:
    """Factor conjuncts common to every OR branch out of the OR
    (ExtractCommonPredicatesExpressionRewriter analog, on bound IR with
    structural equality). Returns the replacement conjunct list."""
    if not (isinstance(ir, Call) and ir.fn == "or"):
        return [ir]
    branches = [_flatten_bool(b, "and") for b in _flatten_bool(ir, "or")]
    common = [c for c in branches[0]
              if all(any(c == d for d in bc) for bc in branches[1:])]
    if not common:
        return [ir]
    reduced = []
    for bc in branches:
        rest = [c for c in bc if not any(c == d for d in common)]
        if not rest:
            return common  # one branch is fully covered: OR is implied
        out = rest[0]
        for c in rest[1:]:
            out = call("and", out, c)
        reduced.append(out)
    new_or = reduced[0]
    for b in reduced[1:]:
        new_or = call("or", new_or, b)
    return common + [new_or]


def _flatten_bool_ast(e: ast.Node, op: str) -> List[ast.Node]:
    if isinstance(e, ast.Binary) and e.op == op:
        return _flatten_bool_ast(e.left, op) + _flatten_bool_ast(e.right, op)
    return [e]


def _extract_common_or_ast(c: ast.Node) -> List[ast.Node]:
    """_extract_common_or at the AST level (frozen dataclasses compare
    structurally): (X and A) or (X and B) -> [X, (A or B)].  Lets a
    correlation conjunct shared by every OR branch factor out so
    _split_correlation can classify it (the TPC-DS q41/q85 shape)."""
    if not (isinstance(c, ast.Binary) and c.op == "or"):
        return [c]
    branches = [_flatten_bool_ast(b, "and") for b in _flatten_bool_ast(c, "or")]
    common = [x for x in branches[0] if all(x in bc for bc in branches[1:])]
    if not common:
        return [c]
    reduced = []
    for bc in branches:
        rest = [x for x in bc if x not in common]
        if not rest:
            return common  # one branch fully covered: OR is implied
        out = rest[0]
        for x in rest[1:]:
            out = ast.Binary("and", out, x)
        reduced.append(out)
    new_or = reduced[0]
    for b in reduced[1:]:
        new_or = ast.Binary("or", new_or, b)
    return common + [new_or]


def _iter_child_nodes(v):
    """Yield ast.Node values inside a field value, flattening nested
    tuples (Case.whens is a tuple of (cond, result) pairs)."""
    if isinstance(v, ast.Node):
        yield v
    elif isinstance(v, tuple):
        for x in v:
            yield from _iter_child_nodes(x)


def _find_mark_subqueries(e: ast.Node, out: List[ast.Node]) -> None:
    """Collect Exists/InSubquery nodes inside a general boolean
    expression (not descending into their query bodies) — the operands
    the mark-join lowering replaces with boolean columns."""
    if isinstance(e, (ast.Exists, ast.InSubquery)):
        out.append(e)
        return
    if isinstance(e, (ast.Query, ast.Union, ast.ScalarSubquery)):
        return
    if dataclasses.is_dataclass(e):
        for f in dataclasses.fields(e):
            for x in _iter_child_nodes(getattr(e, f.name)):
                _find_mark_subqueries(x, out)


def _find_scalar_subqueries(e: ast.Node, out: List[ast.Node]) -> None:
    """Collect ScalarSubquery nodes inside an expression (not descending
    into their query bodies)."""
    if isinstance(e, ast.ScalarSubquery):
        out.append(e)
        return
    if isinstance(e, (ast.Query, ast.Union, ast.InSubquery, ast.Exists)):
        return
    if dataclasses.is_dataclass(e):
        for f in dataclasses.fields(e):
            for x in _iter_child_nodes(getattr(e, f.name)):
                _find_scalar_subqueries(x, out)


def _is_subquery_conjunct(c: ast.Node) -> bool:
    if isinstance(c, (ast.InSubquery, ast.Exists)):
        return True
    if isinstance(c, ast.Unary) and c.op == "not":
        return _is_subquery_conjunct(c.operand)
    if isinstance(c, ast.Binary) and c.op in ("=", "<>", "<", "<=", ">", ">="):
        subs: List[ast.Node] = []
        _find_scalar_subqueries(c, subs)
        if subs:
            return True
    # EXISTS/IN-subquery anywhere inside (OR of EXISTS etc.): the
    # mark-join fallback owns these
    marks: List[ast.Node] = []
    _find_mark_subqueries(c, marks)
    return bool(marks)


@dataclasses.dataclass
class AggCtx:
    """Aggregation binding context: group expr matching + agg collection."""

    group_asts: List[ast.Node]
    group_irs: List[Expr]  # over the pre-agg scope
    aggs: List[AggCall] = dataclasses.field(default_factory=list)
    # grouping-set membership masks (GroupIdNode's set_masks) when the
    # aggregation came from GROUPING SETS/ROLLUP/CUBE — powers grouping()
    set_masks: Optional[List[List[bool]]] = None

    def key_ref(self, i: int) -> ColumnRef:
        return ColumnRef(type=self.group_irs[i].type, index=i)

    def agg_ref(self, agg: AggCall) -> ColumnRef:
        from presto_tpu.ops.aggregate import output_type

        for j, a in enumerate(self.aggs):
            if a == agg:
                return ColumnRef(type=output_type(a), index=len(self.group_irs) + j)
        self.aggs.append(agg)
        return ColumnRef(type=output_type(agg), index=len(self.group_irs) + len(self.aggs) - 1)


@dataclasses.dataclass
class Term:
    """One FROM relation: its plan + scope + global channel offset."""

    node: PlanNode
    scope: Scope
    offset: int = 0


class Binder:
    """Plans one SELECT query against a catalog."""

    def __init__(self, catalog: Catalog, session=None):
        self.catalog = catalog
        self.session = session
        # subquery conjuncts discovered while joining the current
        # query's FROM terms, applied after the join tree is built
        self._pending_subqueries: List[Tuple[ast.Node, Scope]] = []
        # window expressions registered while binding the current
        # query's select/order items: ast -> (slot, spec, WindowFunc)
        self._windows: List[Tuple[ast.WindowExpr, object, List[Expr], List[Expr], List[bool]]] = []
        self._win_slots: Dict[ast.WindowExpr, int] = {}
        # planned scalar-subquery marker refs keyed by id(ast node),
        # live only while binding the enclosing conjunct
        self._scalar_refs: Dict[int, ColumnRef] = {}
        # Exists/InSubquery -> mark-join boolean ref (EXISTS under OR)
        self._mark_refs: Dict[int, ColumnRef] = {}
        # UNNEST relations of the FROM clause currently being flattened
        self._from_unnests: List[ast.Unnest] = []
        # in-scope CTE definitions (WITH name AS (...)): name -> query ast
        self._ctes: Dict[str, ast.Node] = {}
        # views currently being expanded (cycle detection, the
        # reference's StatementAnalyzer.analyzeView recursion guard)
        self._view_stack: List[tuple] = []
        # the statement's single now() instant (reset per plan_ast)
        self._now: Optional[float] = None
        # lambda parameter scopes (innermost last): name -> LambdaVar
        self._lambda_params: List[Dict[str, object]] = []
        # statement-unique LambdaVar slots: shadowing-safe nesting
        self._lambda_slot_seq = iter(range(1 << 30))
        # CBO stats (cost/StatsCalculator.java analog); memo is safe to
        # share across plan() calls since plan nodes are identity-keyed
        from presto_tpu.planner.stats import StatsCalculator

        self._stats = StatsCalculator()

    def session_user(self) -> str:
        return self.session.user if self.session is not None else "presto"

    def _row_field(self, base, field: str):
        """expr.field over a ROW value -> the field's column slice
        (DereferenceExpression row access)."""
        t = base.type
        if t.name != "row":
            raise BindError(f"field access on non-row type {t}")
        if not t.field_names:
            raise BindError("row has no named fields (CAST to "
                            "ROW(name type, ...) to name them)")
        fl = field.lower()
        names = [n.lower() for n in t.field_names]
        if fl not in names:
            raise BindError(f"row has no field {field!r}")
        i = names.index(fl)
        # ops/container.row_field is 1-based (SQL subscript convention)
        return Call(type=t.fields[i], fn="row_field",
                    args=(base, Literal(type=BIGINT, value=i + 1)))

    # ==================================================================
    def _query_now(self) -> float:
        """One wall-clock instant per planned query: every
        current_date/current_timestamp/now() in a statement sees the
        same time (Session.getStartTime in the reference)."""
        if self._now is None:
            import time as _time

            self._now = _time.time()
        return self._now

    def plan(self, sql: str) -> OutputNode:
        self._stats.reset()  # don't pin prior queries' plan trees
        try:
            return self.plan_ast(parse_query(sql))
        except BindError as e:
            annotated = annotate_position(e, sql)
            if annotated is not e:
                # keep the internal traceback plan_ast's SPI wrap
                # promised (__cause__), don't suppress it
                raise annotated from e.__cause__
            raise

    def plan_ast(self, q: ast.Node,
                 validate_rewrites: Optional[bool] = None) -> OutputNode:
        self._now = None  # fresh instant for this statement
        # feedback loop: under the `feedback_stats` session property the
        # stats calculator consults the plan-history store (observed
        # actuals from prior executions override textbook selectivities
        # on structural-signature match).  Resolved per statement — the
        # session can toggle it between queries on this binder.
        self._stats.history = None
        if self.session is not None and bool(
                self.session.get("feedback_stats")):
            from presto_tpu.obs.history import (
                HistoricalStatsProvider, default_history,
            )

            store = default_history()
            if store is not None:
                self._stats.history = HistoricalStatsProvider(store)
        try:
            from presto_tpu import analysis

            if validate_rewrites is None:
                validate_rewrites = analysis.rewrite_validation_enabled() or (
                    self.session is not None
                    and bool(self.session.get("validate_rewrites")))
            node, names = self._plan_query_like(q)
            out = OutputNode(node, names)
            if analysis.validation_enabled() or (
                    self.session is not None
                    and bool(self.session.get("validate_plans"))):
                # pre-optimization half of the validate_plans contract:
                # a clean bound plan isolates any later violation to a
                # rewrite (the runner validates the optimized plan)
                analysis.assert_valid(out)
            if analysis.kernel_validation_enabled() or (
                    self.session is not None
                    and bool(self.session.get("validate_kernels"))):
                # same pre-optimization split for the kernel-soundness
                # tier: a clean bound plan pins any post-optimization
                # hazard on the rewrite that introduced it
                analysis.assert_kernel_sound(out)
            # iterative rule engine over the bound plan
            # (sql/planner/iterative/IterativeOptimizer.java)
            from presto_tpu.planner.iterative import IterativeOptimizer

            opt = IterativeOptimizer(validate=validate_rewrites)
            out = opt.optimize(out)
            out._optimizer_report = opt.stats
            self._enable_index_joins(out)
            # estimate capture: stamp the FINAL plan with its bind-time
            # row estimates under the structural stats keys, so EXPLAIN
            # ANALYZE can print est-vs-actual per operator and the
            # history store can attribute misestimates (planner/stats.
            # capture_estimates; feedback applied above via _stats.history)
            from presto_tpu.planner.stats import capture_estimates

            out._estimates = capture_estimates(out, self._stats)
            return out
        except (BindError, SyntaxError):
            raise
        except (KeyError, IndexError, AssertionError, TypeError) as e:
            # SPI boundary: internal exceptions must not leak raw to the
            # user (the r5 ``KeyError: frozenset()`` class).  The
            # message carries through verbatim; the original traceback
            # rides __cause__ for debugging.
            msg = (e.args[0] if e.args and isinstance(e.args[0], str)
                   else (str(e) or type(e).__name__))
            raise BindError(msg) from e

    def _enable_index_joins(self, root: PlanNode) -> None:
        """Flag (or side-swap) joins where one side is a bare scan of an
        index-capable connector and the other is much smaller: fetching
        build rows by probe keys beats the full scan
        (IndexJoinOptimizer.java).  The hash planner puts the largest
        term on the probe side, so the indexed scan usually arrives as
        ``left`` — inner joins swap sides behind a reordering
        projection."""
        from presto_tpu.planner.iterative import _replace_sources

        def indexable(scan: PlanNode, keys) -> bool:
            if not (isinstance(scan, TableScanNode) and not scan.constraints):
                return False
            if not all(isinstance(k, ColumnRef)
                       and k.type.name in ("bigint", "integer") for k in keys):
                return False
            conn = self.catalog.connector(scan.handle.connector_name)
            if not (hasattr(conn, "supports_index")
                    and hasattr(conn, "index_lookup")):
                return False
            key_cols = [scan.handle.columns[scan.columns[k.index]].name
                        for k in keys]
            return conn.supports_index(scan.handle.table, key_cols)

        def walk(n: PlanNode) -> PlanNode:
            srcs = n.sources
            if srcs:
                new = [walk(s) for s in srcs]
                if any(a is not b for a, b in zip(new, srcs)):
                    _replace_sources(n, new)
            if not isinstance(n, JoinNode):
                return n
            if (n.kind in ("inner", "semi", "anti")
                    and indexable(n.right, n.right_keys)
                    and self._estimate(n.left) * 10 < self._estimate(n.right)):
                n.use_index = True
                return n
            if (n.kind == "inner" and not n.use_index
                    and indexable(n.left, n.left_keys)
                    and self._estimate(n.right) * 10 < self._estimate(n.left)):
                nl, nr = len(n.left.channels), len(n.right.channels)
                swapped = JoinNode(
                    left=n.right, right=n.left,
                    left_keys=list(n.right_keys), right_keys=list(n.left_keys),
                    kind="inner", use_index=True,
                )
                chans = swapped.channels  # right-side first
                projections = (
                    [ColumnRef(type=chans[nr + i].type, index=nr + i)
                     for i in range(nl)]
                    + [ColumnRef(type=chans[i].type, index=i) for i in range(nr)]
                )
                names = ([c.name for c in chans[nr:]]
                         + [c.name for c in chans[:nr]])
                return ProjectNode(swapped, projections, names)
            return n

        walk(root)

    def _plan_query_like(self, q: ast.Node) -> Tuple[PlanNode, List[str]]:
        if isinstance(q, ast.With):
            # CTEs expand by name substitution: TableRef resolution
            # consults the scoped registry first (sql/tree/With.java)
            saved = dict(self._ctes)
            try:
                for name, sub in q.ctes:
                    self._ctes[name.lower()] = sub
                return self._plan_query_like(q.body)
            finally:
                self._ctes = saved
        if isinstance(q, ast.Union):
            return self._plan_union(q)
        if isinstance(q, ast.SetOp):
            return self._plan_setop(q)
        return self._plan_query(q)

    def _plan_setop(self, q: ast.SetOp) -> Tuple[PlanNode, List[str]]:
        """INTERSECT -> distinct(left) SEMI-joined to right on every
        column; EXCEPT -> ANTI join (the reference lowers through
        SetOperationNodeTranslator to the same semi/anti shapes).
        NULLs compare equal, per set-operation semantics — the join
        key packing already treats NULL keys as one class."""
        label = q.kind.upper()
        lnode, rnode, lnames = self._plan_set_arms(q, label)
        distinct_left = AggregationNode(
            lnode,
            [ColumnRef(type=c.type, index=i) for i, c in enumerate(lnode.channels)],
            lnames, [], [],
            max_groups=self._distinct_capacity(lnode),
        )
        join = JoinNode(
            left=distinct_left, right=rnode,
            left_keys=[ColumnRef(type=c.type, index=i)
                       for i, c in enumerate(distinct_left.channels)],
            right_keys=[ColumnRef(type=c.type, index=i)
                        for i, c in enumerate(rnode.channels)],
            kind="semi" if q.kind == "intersect" else "anti",
            null_safe_keys=True,  # set-op rows compare IS NOT DISTINCT FROM
        )
        names = lnames
        node = self._wrap_order_limit(join, names, q.order_by, q.limit, label)
        return node, names

    def _plan_set_arms(self, q, label: str):
        """Shared arm planning for UNION/INTERSECT/EXCEPT: plan both
        sides, check arity, align types via cast projections."""
        lnode, lnames = self._plan_query_like(q.left)
        rnode, rnames = self._plan_query_like(q.right)
        if len(lnode.channels) != len(rnode.channels):
            raise BindError(f"{label} arms have different column counts")
        targets = [
            common_super_type(a.type, b.type)
            for a, b in zip(lnode.channels, rnode.channels)
        ]
        lnode = self._coerce_columns(lnode, targets, lnames)
        rnode = self._coerce_columns(rnode, targets, lnames)
        return lnode, rnode, lnames

    def _wrap_order_limit(self, node: PlanNode, names: List[str], order_by,
                          limit, label: str) -> PlanNode:
        """Set-operation-level ORDER BY (names/ordinals) + LIMIT."""
        order_channels: List[ColumnRef] = []
        for o in order_by:
            e = o.expr
            if isinstance(e, ast.NumberLit):
                i = int(e.text) - 1
            elif isinstance(e, ast.Identifier) and e.name in names:
                i = names.index(e.name)
            else:
                raise BindError(
                    f"{label} ORDER BY must use output names or ordinals")
            order_channels.append(ColumnRef(type=node.channels[i].type, index=i))
        if order_by:
            asc = [o.ascending for o in order_by]
            nf = [o.nulls_first if o.nulls_first is not None else (not o.ascending)
                  for o in order_by]
            if limit is not None:
                return TopNNode(node, order_channels, asc, limit, nf)
            return SortNode(node, order_channels, asc, nf)
        if limit is not None:
            return LimitNode(node, limit)
        return node

    def _plan_union(self, u: ast.Union) -> Tuple[PlanNode, List[str]]:
        from presto_tpu.planner.plan import UnionNode

        lnode, rnode, names = self._plan_set_arms(u, "UNION")
        node: PlanNode = UnionNode([lnode, rnode])
        if u.distinct:
            node = AggregationNode(
                node,
                [ColumnRef(type=c.type, index=i) for i, c in enumerate(node.channels)],
                names, [], [],
                max_groups=self._distinct_capacity(node),
            )
        node = self._wrap_order_limit(node, names, u.order_by, u.limit, "UNION")
        return node, names

    def _coerce_columns(self, node: PlanNode, targets: List[Type], names: List[str]) -> PlanNode:
        if all(c.type == t for c, t in zip(node.channels, targets)):
            return node
        projections = []
        for i, (c, t) in enumerate(zip(node.channels, targets)):
            ref = ColumnRef(type=c.type, index=i)
            if c.type == t:
                projections.append(ref)
            elif t.name == "double":
                projections.append(call("cast_double", ref))
            elif t.is_decimal:
                # rescale through exact decimal addition of 0
                projections.append(call("add", ref, Literal(type=t, value=0)))
            elif t.name == "bigint":
                projections.append(call("cast_bigint", ref))
            else:
                raise BindError(f"cannot unify UNION column types {c.type} and {t}")
        return ProjectNode(node, projections, list(names))

    # ==================================================================
    # relation planning
    # ==================================================================
    def _plan_relation(self, rel: ast.Node) -> Tuple[PlanNode, Scope]:
        if isinstance(rel, ast.TableRef):
            cte = self._ctes.get(rel.name.lower())
            if cte is not None:
                node, names = self._plan_query_like(cte)
                qual = rel.alias or rel.name
                scope = Scope(
                    [ScopeCol(qual, n, c) for n, c in zip(names, node.channels)]
                )
                return node, scope
            view = self.catalog.lookup_view(rel.name, self.session) \
                if hasattr(self.catalog, "lookup_view") else None
            if view is not None:
                # view expansion: re-parse and re-bind the stored SQL
                # under the view's own creation-time namespace
                # (StatementAnalyzer.java:789 via metadata.getView)
                key, vdef = view
                if key in self._view_stack:
                    raise BindError(
                        "view is recursive: " + ".".join(key))
                if getattr(rel, "sample", None) is not None:
                    # the sample clause rides TableScanNode; silently
                    # scanning 100% of an expanded view would be a
                    # wrong result, so reject loudly
                    raise BindError("TABLESAMPLE over a view is not supported")
                from presto_tpu.sql.parser import parse_query

                saved = None
                if self.session is not None:
                    saved = (self.session.catalog, self.session.schema)
                    self.session.catalog = vdef.catalog
                    self.session.schema = vdef.schema
                self._view_stack.append(key)
                saved_ctes, self._ctes = self._ctes, {}
                try:
                    node, names = self._plan_query_like(parse_query(vdef.sql))
                finally:
                    self._view_stack.pop()
                    self._ctes = saved_ctes
                    if saved is not None:
                        self.session.catalog, self.session.schema = saved
                qual = rel.alias or rel.name.split(".")[-1]
                scope = Scope(
                    [ScopeCol(qual, n, c) for n, c in zip(names, node.channels)]
                )
                return node, scope
            handle = self.catalog.resolve(rel.name, session=self.session)
            scan = TableScanNode(handle, list(range(len(handle.columns))),
                                 sample=getattr(rel, "sample", None))
            # a catalog-qualified name aliases to its bare table name
            return scan, Scope.of(scan, rel.alias or rel.name.split(".")[-1])
        if isinstance(rel, ast.ValuesRel):
            return self._plan_values(rel)
        if isinstance(rel, ast.SubqueryRel):
            node, names = self._plan_query_like(rel.query)
            scope = Scope(
                [ScopeCol(rel.alias, n, c) for n, c in zip(names, node.channels)]
            )
            return node, scope
        if isinstance(rel, ast.JoinRel):
            return self._plan_join_rel(rel)
        raise BindError(f"unsupported relation {rel!r}")

    def _flatten_from(self, rels: Sequence[ast.Node]) -> Tuple[List[Term], List[ast.Node]]:
        """Flatten comma relations + inner join trees into terms and a
        conjunct pool (EliminateCrossJoins flattening)."""
        terms: List[Term] = []
        conjuncts: List[ast.Node] = []

        def walk(rel: ast.Node):
            if isinstance(rel, ast.JoinRel) and rel.kind == "inner":
                walk(rel.left)
                walk(rel.right)
                conjuncts.extend(split_conjuncts(rel.on))
            elif isinstance(rel, ast.JoinRel) and rel.kind == "cross":
                walk(rel.left)
                walk(rel.right)
            elif isinstance(rel, ast.Unnest):
                # lateral: binds against the joined FROM scope, applied
                # after the join graph (UNNEST is always a cross-join
                # expansion of the preceding terms)
                self._from_unnests.append(rel)
            else:
                node, scope = self._plan_relation(rel)
                terms.append(Term(node, scope))

        for r in rels:
            walk(r)
        off = 0
        for t in terms:
            t.offset = off
            off += len(t.scope)
        return terms, conjuncts

    def _input_presorted(self, node: PlanNode, group_irs) -> bool:
        """True when the aggregation input provably arrives with equal
        group keys contiguous: the input chain is scan(+filter/projection
        pass-through) of a table whose declared sort order's prefix is
        exactly the group-key set (connector ``sort_order`` metadata —
        the reference's ConnectorMetadata local properties feeding
        StreamingAggregationOperator selection)."""
        remap: Optional[Dict[int, int]] = None  # None = identity (no Project seen)
        cur = node
        while True:
            if isinstance(cur, FilterNode):
                cur = cur.source
            elif isinstance(cur, ProjectNode):
                proj_map = {}
                for i, p in enumerate(cur.projections):
                    if isinstance(p, ColumnRef):
                        proj_map[i] = p.index
                src_items = (remap.items() if remap is not None else
                             ((i, i) for i in range(len(cur.channels))))
                remap = {}
                for out_i, in_i in src_items:
                    if in_i in proj_map:
                        remap[out_i] = proj_map[in_i]
                cur = cur.source
            else:
                break
        if not isinstance(cur, TableScanNode):
            return False
        conn = self.catalog.connector(cur.handle.connector_name)
        so = conn.sort_order(cur.handle.table) if hasattr(conn, "sort_order") else None
        if not so:
            return False
        names = set()
        for e in group_irs:
            if not isinstance(e, ColumnRef):
                return False
            idx = e.index if remap is None else remap.get(e.index)
            if idx is None or idx >= len(cur.columns):
                return False
            names.add(cur.handle.columns[cur.columns[idx]].name)
        k = len(names)
        return 0 < k <= len(so) and set(so[:k]) == names

    def _plan_values(self, rel: ast.ValuesRel) -> Tuple[PlanNode, Scope]:
        """VALUES rows -> ValuesNode (sql/tree/Values.java): literal
        cells bind standalone; column types are the per-position common
        supertypes with NULL literals adopting them."""
        empty = Scope([])
        bound = [[self._bind(c, empty) for c in row] for row in rel.rows]
        if not bound:
            raise BindError("empty VALUES")
        arity = len(bound[0])
        for row in bound:
            if len(row) != arity:
                raise BindError("VALUES rows differ in arity")
            for j, cell in enumerate(row):
                if isinstance(cell, Call) and cell.fn == "array_construct" \
                        and all(isinstance(a, Literal) for a in cell.args):
                    # constant-fold ARRAY[...] literals to list values
                    vals = []
                    for a in cell.args:
                        if a.value is None:
                            vals.append(None)
                        elif a.type.is_decimal:
                            # plain python value; the page encoder
                            # re-scales to the element type
                            vals.append(a.value / 10 ** (a.type.scale or 0))
                        else:
                            vals.append(a.value)
                    row[j] = Literal(type=cell.type, value=vals)
                elif not isinstance(cell, Literal):
                    raise BindError("VALUES cells must be literals")
        types: List[Type] = []
        for j in range(arity):
            t = None
            for row in bound:
                cell = row[j]
                if cell.value is None:
                    continue
                if t is None:
                    t = cell.type
                elif t.is_array and cell.type.is_array:
                    from presto_tpu.types import ArrayType

                    t = ArrayType(
                        common_super_type(t.element, cell.type.element),
                        max(t.max_elems, cell.type.max_elems))
                else:
                    t = common_super_type(t, cell.type)
            types.append(t if t is not None else BIGINT)
        names = (list(rel.column_names) if rel.column_names
                 else [f"_col{j}" for j in range(arity)])
        if len(names) != arity:
            raise BindError("VALUES alias declares wrong column count")

        # string columns dictionary-encode over their distinct values
        dictionaries: List = []
        for j, t in enumerate(types):
            if t.is_string:
                values = sorted({row[j].value for row in bound
                                 if row[j].value is not None})
                dictionaries.append(Dictionary(values))
            else:
                dictionaries.append(None)

        def cell_value(cell: Literal, t: Type, d):
            if cell.value is None:
                return None
            v = cell.value
            if d is not None:
                return d.code_of(str(v))
            if t.is_decimal and cell.type.is_decimal:
                return v * 10 ** ((t.scale or 0) - (cell.type.scale or 0))
            if t.name == "double" and cell.type.is_decimal:
                return v / 10 ** (cell.type.scale or 0)
            return v

        rows = [
            tuple(cell_value(c, t, d)
                  for c, t, d in zip(row, types, dictionaries))
            for row in bound
        ]
        node = ValuesNode(names=names, types=types, rows=rows,
                          dictionaries=dictionaries)
        return node, Scope.of(node, rel.alias)

    def _names_resolvable(self, e: ast.Node, scope: Scope) -> bool:
        """True if every free Identifier in ``e`` resolves in ``scope``
        (subquery bodies are skipped — they bind their own scopes)."""
        ok = True

        def walk(n):
            nonlocal ok
            if not ok or not isinstance(n, ast.Node):
                return
            if isinstance(n, ast.InSubquery):
                walk(n.value)  # the probe value is free; the body is not
                return
            if isinstance(n, (ast.ScalarSubquery, ast.Exists)):
                return  # inner scopes resolve separately
            if isinstance(n, ast.Identifier):
                qualifier = n.parts[0] if len(n.parts) > 1 else None
                try:
                    scope.resolve(qualifier, n.parts[-1])
                except BindError:
                    ok = False
                return
            for f in dataclasses.fields(n):
                visit(getattr(n, f.name))

        def visit(v):
            # tuples nest (Case.whens is a tuple of (cond, result) pairs)
            if isinstance(v, tuple):
                for x in v:
                    visit(x)
            else:
                walk(v)

        walk(e)
        return ok

    def _apply_unnest(self, node: PlanNode, scope: Scope,
                      un: ast.Unnest) -> Tuple[PlanNode, Scope]:
        """UNNEST(args) lateral expansion (UnnestOperator.java:35)."""
        from presto_tpu.planner.plan import UnnestNode

        exprs = [self._bind(a, scope) for a in un.args]
        ncols = 0
        for e in exprs:
            if not (e.type.is_array or e.type.is_map):
                raise BindError(f"UNNEST argument must be ARRAY or MAP, got {e.type}")
            ncols += 2 if e.type.is_map else 1
        want = ncols + (1 if un.ordinality else 0)
        if un.column_names:
            if len(un.column_names) != want:
                raise BindError(
                    f"UNNEST alias declares {len(un.column_names)} columns, "
                    f"expansion produces {want}")
            names = list(un.column_names)
        else:
            names = [f"col{i+1}" for i in range(ncols)]
            if un.ordinality:
                names.append("ordinality")
        out = UnnestNode(node, exprs, names, un.ordinality)
        new_cols = [
            ScopeCol(un.alias, c.name, c) for c in out.channels[len(scope):]
        ]
        return out, Scope(scope.cols + new_cols)

    def _plan_join_rel(self, rel: ast.JoinRel) -> Tuple[PlanNode, Scope]:
        """Explicit JOIN trees. Inner joins route through the join-graph
        planner; LEFT/FULL joins are planned directly (null-extension
        pins probe/build sides). Reference: LookupJoinOperators.java:37
        (innerJoin/probeOuterJoin/lookupOuterJoin/fullOuterJoin)."""
        if rel.kind in ("inner", "cross"):
            terms, conjuncts = self._flatten_from([rel])
            node, scope, g2c = self._join_terms(terms, conjuncts)
            # join reordering permutes the tree's channel layout away
            # from the syntactic scope order; callers (e.g. the probe
            # side of an enclosing LEFT/FULL join) address channels BY
            # SCOPE POSITION, so restore the order with a pass-through
            # projection (fuses into the chain; ColumnRef projections
            # keep dictionary/domain metadata).  Dropping the mapping
            # here mis-bound every predicate above an outer join over a
            # reordered cluster (silent wrong results when types align).
            if any(g2c[i] != i for i in range(len(scope))):
                chans = node.channels
                node = ProjectNode(
                    node,
                    [ColumnRef(type=chans[g2c[i]].type, index=g2c[i])
                     for i in range(len(scope))],
                    [c.name for c in scope.cols],
                )
            return node, scope
        assert rel.kind in ("left", "full"), rel.kind
        lnode, lscope = self._plan_relation(rel.left)
        rnode, rscope = self._plan_relation(rel.right)
        glob = lscope.concat(rscope)
        lkeys: List[Expr] = []
        rkeys: List[Expr] = []
        post: List[Expr] = []
        for c in split_conjuncts(rel.on):
            ir = self._bind(c, glob)
            refs = expr_refs(ir)
            left_refs = [r for r in refs if r < len(lscope)]
            right_refs = [r for r in refs if r >= len(lscope)]
            if (
                isinstance(ir, Call) and ir.fn == "eq"
                and all(isinstance(a, ColumnRef) for a in ir.args)
                and len(left_refs) == 1 and len(right_refs) == 1
            ):
                a, b = ir.args
                if a.index >= len(lscope):
                    a, b = b, a
                lkeys.append(a)
                rkeys.append(ColumnRef(type=b.type, index=b.index - len(lscope)))
            elif not left_refs and rel.kind == "left":
                # right-side-only ON predicate: prefilter build (valid
                # for LEFT joins — unmatched probes still null-extend;
                # NOT valid for FULL, where filtered build rows must
                # still appear null-extended)
                rmap = {r: r - len(lscope) for r in right_refs}
                rnode = FilterNode(rnode, remap_expr(ir, rmap))
            else:
                raise BindError(f"unsupported {rel.kind.upper()} JOIN ON predicate: {c!r}")
        if not lkeys:
            raise BindError(f"{rel.kind.upper()} JOIN requires at least one equi-condition")
        join = JoinNode(
            left=lnode, right=rnode, left_keys=lkeys, right_keys=rkeys,
            kind=rel.kind, unique_build=self._build_is_unique(rnode, rkeys),
        )
        return join, glob

    # ==================================================================
    # join graph (comma FROM + WHERE equi conjuncts)
    # ==================================================================
    def _join_terms(
        self, terms: List[Term], conjunct_asts: List[ast.Node]
    ) -> Tuple[PlanNode, Scope, Dict[int, int]]:
        """Returns (tree, scope, glob->tree channel mapping)."""
        glob = Scope([])
        for t in terms:
            glob = glob.concat(t.scope)

        plain: List[Expr] = []
        for c in conjunct_asts:
            if _is_subquery_conjunct(c):
                self._pending_subqueries.append((c, glob))
                continue
            # (A and X) or (A and Y) -> A and (X or Y): frees common
            # equi-conjuncts (e.g. TPC-H Q19's join key) out of OR
            # blocks so they become join edges instead of a cross join
            # (optimizations/ExtractCommonPredicatesExpressionRewriter)
            plain.extend(_extract_common_or(self._bind(c, glob)))

        def term_of(ref: int) -> int:
            for i, t in enumerate(terms):
                if t.offset <= ref < t.offset + len(t.scope):
                    return i
            raise BindError(
                f"internal: channel reference ${ref} falls outside every "
                "join term's scope (binder channel-offset bug)")

        # route single-term conjuncts as pushed-down filters
        edges: List[Tuple[int, int, Expr]] = []  # (term_i, term_j, eq ir)
        post: List[Expr] = []
        for ir in plain:
            tset = sorted({term_of(r) for r in expr_refs(ir)})
            if len(tset) == 0:
                post.append(ir)  # constant predicate
            elif len(tset) == 1:
                i = tset[0]
                mapping = {r: r - terms[i].offset for r in expr_refs(ir)}
                local = remap_expr(ir, mapping)
                terms[i].node = FilterNode(terms[i].node, local)
                self._push_scan_constraints(terms[i].node, local)
            elif (
                len(tset) == 2
                and isinstance(ir, Call) and ir.fn == "eq"
                and all(isinstance(a, ColumnRef) for a in ir.args)
            ):
                edges.append((tset[0], tset[1], ir))
            else:
                post.append(ir)

        if len(terms) == 1:
            node = terms[0].node
            g2c = {terms[0].offset + i: i for i in range(len(terms[0].scope))}
        elif len(terms) <= 6:
            # cost-based enumeration (ReorderJoins + CostComparator +
            # DetermineJoinDistributionType analog): DP over subsets
            node, g2c = self._cost_based_join(terms, edges, post)
        else:
            node, g2c = self._greedy_join(terms, edges, post)

        for ir in post:
            node = FilterNode(node, remap_expr(ir, g2c))
        return node, glob, g2c

    # nominal worker count for the broadcast-vs-partitioned exchange
    # term of the join cost model (DetermineJoinDistributionType's
    # cost comparison folded into join-order enumeration)
    _COST_WORKERS = 8

    def _cost_based_join(self, terms, edges, post):
        """Selinger-style DP over connected subsets for <=6 relations
        (iterative/rule/ReorderJoins.java + cost/CostComparator.java
        analog).  Each join's cost = build materialization + probe pass
        + output + the cheaper of broadcast / repartitioned exchange —
        so the distribution choice is part of the same comparison.
        Cross joins (no connecting edge) are admitted with their
        Cartesian output as the penalty, keeping disconnected graphs
        and scalar-subquery single-row terms working."""
        from itertools import combinations

        n = len(terms)

        def base_map(i: int):
            return {terms[i].offset + k: k
                    for k in range(len(terms[i].scope))}

        # subset -> (cost, rows, node, g2c, used_edges frozenset)
        best = {}
        for i in range(n):
            rows = max(self._estimate(terms[i].node), 1.0)
            best[frozenset([i])] = (0.0, rows, terms[i].node, base_map(i),
                                    frozenset())

        def join_of(s1, s2):
            """Join best[s1] (probe) with best[s2] (build); returns a
            candidate entry or None."""
            c1, r1, n1, m1, u1 = best[s1]
            c2, r2, n2, m2, u2 = best[s2]
            cross = [k for k, (i, j, _) in enumerate(edges)
                     if k not in u1 and k not in u2
                     and ((i in s1 and j in s2) or (i in s2 and j in s1))]
            lkeys: List[Expr] = []
            rkeys: List[Expr] = []
            for k in cross:
                a, b = edges[k][2].args
                if a.index in m2:  # a on the build side: swap
                    a, b = b, a
                lkeys.append(ColumnRef(type=a.type, index=m1[a.index]))
                rkeys.append(ColumnRef(type=b.type, index=m2[b.index]))
            if not cross:
                zero = Literal(type=BIGINT, value=0)
                lkeys, rkeys = [zero], [zero]
                unique = self._provably_single_row(n2)
            else:
                key_refs = [ColumnRef(type=k.type, index=k.index)
                            for k in rkeys]
                unique = self._build_is_unique(n2, key_refs)
            join = JoinNode(left=n1, right=n2, left_keys=lkeys,
                            right_keys=rkeys, kind="inner",
                            unique_build=unique)
            if not cross:
                out = r1 * r2  # never trust the calculator on lit-keys
            else:
                out = max(self._estimate(join), 1.0)
            exchange = min(self._COST_WORKERS * r2, r1 + r2)
            cost = c1 + c2 + r2 + r1 + out + exchange
            if not cross:
                cost += 2 * out  # Cartesian penalty
            if not unique:
                # non-unique builds run the expanding (materializing)
                # kernel: extra output materialization + a host sync per
                # probe page — strongly prefer streaming orientations
                cost += 2 * (r1 + out)
            g2c = dict(m1)
            off = len(n1.channels)
            for r, idx in m2.items():
                g2c[r] = off + idx
            return (cost, out, join, g2c,
                    u1 | u2 | frozenset(cross))

        idx = list(range(n))
        for size in range(2, n + 1):
            for comb in combinations(idx, size):
                s = frozenset(comb)
                entry = None
                members = sorted(s)
                # enumerate splits; fix the smallest member to one side
                # to halve the symmetric space, but try BOTH probe/build
                # orientations of each split
                rest = [m for m in members if m != members[0]]
                for r_size in range(0, len(rest) + 1):
                    for picked in combinations(rest, r_size):
                        s2 = frozenset(picked) | {members[0]}
                        s1 = s - s2
                        if not s1:
                            continue
                        for probe, build in ((s1, s2), (s2, s1)):
                            cand = join_of(probe, build)
                            if cand is not None and (
                                entry is None or cand[0] < entry[0]
                            ):
                                entry = cand
                best[s] = entry
        cost, rows, node, g2c, used = best[frozenset(idx)]
        for k, (i, j, ir) in enumerate(edges):
            if k not in used:
                post.append(ir)  # cycle edge -> post filter
        return node, g2c

    def _greedy_join(self, terms, edges, post):
        """Probe = largest estimated term; repeatedly hash-join the
        smallest connected term as build side."""
        est = [self._estimate(t.node) for t in terms]
        start = max(range(len(terms)), key=lambda i: est[i])
        joined = {start}
        node = terms[start].node
        g2c = {terms[start].offset + i: i for i in range(len(terms[start].scope))}
        used = [False] * len(edges)
        remaining = set(range(len(terms))) - joined

        while remaining:
            candidates = set()
            for k, (i, j, _) in enumerate(edges):
                if used[k]:
                    continue
                if i in joined and j in remaining:
                    candidates.add(j)
                elif j in joined and i in remaining:
                    candidates.add(i)
            if not candidates:
                # disconnected: cross join smallest remaining term
                pick = min(remaining, key=lambda i: est[i])
                zero = Literal(type=BIGINT, value=0)
                t = terms[pick]
                node = JoinNode(
                    left=node, right=t.node, left_keys=[zero], right_keys=[zero],
                    kind="inner",
                    unique_build=self._provably_single_row(t.node),
                )
                base = len(g2c)
                for li in range(len(t.scope)):
                    g2c[t.offset + li] = base + li
                joined.add(pick)
                remaining.discard(pick)
                continue
            pick = min(candidates, key=lambda i: est[i])
            t = terms[pick]
            lkeys: List[Expr] = []
            rkeys: List[Expr] = []
            for k, (i, j, ir) in enumerate(edges):
                if used[k]:
                    continue
                if (i in joined and j == pick) or (j in joined and i == pick):
                    a, b = ir.args
                    if term_of_ref(terms, a.index) == pick:
                        a, b = b, a
                    lkeys.append(ColumnRef(type=a.type, index=g2c[a.index]))
                    rkeys.append(ColumnRef(type=b.type, index=b.index - t.offset))
                    used[k] = True
            build_unique = self._build_is_unique(t.node, rkeys)
            node = JoinNode(
                left=node, right=t.node, left_keys=lkeys, right_keys=rkeys,
                kind="inner", unique_build=build_unique,
            )
            base = len(g2c)
            for li in range(len(t.scope)):
                g2c[t.offset + li] = base + li
            joined.add(pick)
            remaining.discard(pick)
        # cycle edges (both ends already joined) become post filters
        for k, (i, j, ir) in enumerate(edges):
            if not used[k]:
                post.append(ir)
        return node, g2c

    def _push_scan_constraints(self, node: PlanNode, ir: Expr) -> None:
        """Record simple (col cmp literal) conjuncts on the underlying
        scan for stats-based split pruning (PickTableLayout /
        TupleDomain-pushdown analog)."""
        scan = node
        while isinstance(scan, FilterNode):
            scan = scan.source
        if not isinstance(scan, TableScanNode):
            return
        names = [scan.handle.columns[i].name for i in scan.columns]
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}

        def emit(op: str, col: ColumnRef, lit: Literal):
            if lit.value is None:
                return
            if col.type.is_string:
                # dictionary columns: EQUALITY pushes as a code point
                # constraint (split stats for varchar are code min/max;
                # code ORDER is arbitrary, so ranges stay un-pushed).
                # This is what prunes warehouse partitions on string
                # partition columns.
                if op != "eq" or col.type.is_raw_string:
                    return
                ch = scan.handle.columns[scan.columns[col.index]]
                if ch.dictionary is None:
                    return
                try:
                    code = list(ch.dictionary.values).index(lit.value)
                except ValueError:
                    code = -1  # absent value: every split prunes
                scan.constraints.append((names[col.index], "eq", code))
                return
            scan.constraints.append((names[col.index], op, lit.value))

        def walk(e: Expr):
            if not isinstance(e, Call):
                return
            if e.fn == "and":
                walk(e.args[0])
                walk(e.args[1])
                return
            if e.fn in ("eq", "lt", "le", "gt", "ge") and len(e.args) == 2:
                a, b = e.args
                if isinstance(a, ColumnRef) and isinstance(b, Literal):
                    emit(e.fn, a, b)
                elif isinstance(b, ColumnRef) and isinstance(a, Literal):
                    emit(flip[e.fn], b, a)
            elif e.fn == "between" and isinstance(e.args[0], ColumnRef):
                if isinstance(e.args[1], Literal):
                    emit("ge", e.args[0], e.args[1])
                if isinstance(e.args[2], Literal):
                    emit("le", e.args[0], e.args[2])

        walk(ir)

    # ------------------------------------------------------------------
    def _estimate(self, node: PlanNode) -> float:
        """Estimated output rows, via the stats calculator
        (cost/StatsCalculator.java analog, planner/stats.py)."""
        return self._stats.rows(node)

    def _provably_single_row(self, node: PlanNode) -> bool:
        """True only when the node is STRUCTURALLY guaranteed to emit
        at most one row — a global aggregation, a one-row VALUES, or
        LIMIT 1.  Never from cardinality estimates: unique_build is a
        correctness property (the streaming kernel keeps first matches
        only), and an estimate of 0-1 rows can be wrong."""
        n = node
        while isinstance(n, (ProjectNode, OutputNode)):
            n = n.source
        if isinstance(n, AggregationNode):
            return not n.group_exprs
        if isinstance(n, ValuesNode):
            return len(n.rows) <= 1
        if isinstance(n, LimitNode):
            return n.count <= 1
        return False

    def _build_is_unique(self, node: PlanNode, rkeys: Sequence[Expr]) -> bool:
        """True if the build side's join keys are unique: primary-key
        scans or group-by outputs (reference: the planner's knowledge in
        e.g. metadata uniqueness; used to pick the aligned probe kernel)."""
        key_idx = sorted(
            k.index for k in rkeys if isinstance(k, ColumnRef)
        )
        if len(key_idx) != len(rkeys):
            return False
        n = node
        while isinstance(n, (FilterNode, OutputNode)):
            n = n.source
        if isinstance(n, AggregationNode):
            return key_idx == list(range(len(n.group_exprs)))
        if isinstance(n, ProjectNode):
            # project of a PK scan: map refs through bare column projections
            inner_idx = []
            for i in key_idx:
                p = n.projections[i]
                if not isinstance(p, ColumnRef):
                    return False
                inner_idx.append(p.index)
            return self._build_is_unique(n.source, [
                ColumnRef(type=n.projections[i].type, index=j)
                for i, j in zip(key_idx, inner_idx)
            ])
        if isinstance(n, JoinNode):
            # a join that emits at most ONE row per probe row (inner or
            # left against a unique build, or a mark join) preserves
            # probe-side key uniqueness: rows may drop, never duplicate
            if (n.kind in ("mark", "semi", "anti")
                    or (n.kind in ("inner", "left") and n.unique_build)) \
                    and all(i < len(n.left.channels) for i in key_idx):
                return self._build_is_unique(n.left, [
                    ColumnRef(type=n.left.channels[i].type, index=i)
                    for i in key_idx
                ])
            return False
        if isinstance(n, TableScanNode):
            conn = self.catalog.connector(n.handle.connector_name)
            if not hasattr(conn, "primary_key"):
                return False
            pk = conn.primary_key(n.handle.table)
            if pk is None:
                return False
            names = [n.handle.columns[i].name for i in n.columns]
            try:
                pk_idx = sorted(names.index(c) for c in pk)
            except ValueError:
                return False
            return key_idx == pk_idx
        return False

    # ==================================================================
    # query planning
    # ==================================================================
    def _plan_query(self, q: ast.Query) -> Tuple[PlanNode, List[str]]:
        saved_pending = self._pending_subqueries
        saved_windows, saved_slots = self._windows, self._win_slots
        saved_unnests = self._from_unnests
        self._pending_subqueries = []
        self._windows, self._win_slots = [], {}
        self._from_unnests = []
        try:
            return self._plan_query_inner(q, saved_pending)
        finally:
            self._pending_subqueries = saved_pending
            self._windows, self._win_slots = saved_windows, saved_slots
            self._from_unnests = saved_unnests

    def _plan_query_inner(self, q: ast.Query, saved_pending) -> Tuple[PlanNode, List[str]]:
        if q.from_:
            terms, conjuncts = self._flatten_from(q.from_)
            where_cs = split_conjuncts(q.where)
            deferred_cs: List[ast.Node] = []
            if self._from_unnests:
                # WHERE conjuncts over unnest output columns apply after
                # the expansion; name-resolvability against the pre-unnest
                # scope decides placement (no side effects)
                preview = Scope([])
                for t in terms:
                    preview = preview.concat(t.scope)
                kept = []
                for c in where_cs:
                    if not self._names_resolvable(c, preview):
                        deferred_cs.append(c)
                    else:
                        kept.append(c)
                where_cs = kept
            conjuncts = conjuncts + where_cs
            drop_dummy = False
            if not terms and self._from_unnests:
                # FROM UNNEST(...) with no other relation: expand
                # against a synthetic one-row VALUES term (the
                # reference plans a lone Unnest over a single-row
                # source the same way); the hidden channel is
                # projected away after the expansion
                dummy = ValuesNode(names=["$dummy"], types=[BIGINT],
                                   rows=[(0,)])
                terms = [Term(dummy, Scope(
                    [ScopeCol(None, "$dummy", dummy.channels[0])]))]
                drop_dummy = True
            node, glob, g2c = self._join_terms(terms, conjuncts)
            scope = Scope(
                [glob.cols[g] for g, _ in sorted(g2c.items(), key=lambda kv: kv[1])]
            )
            unnests = self._from_unnests
            self._from_unnests = []
            for un in unnests:
                node, scope = self._apply_unnest(node, scope, un)
            if drop_dummy:
                chans = node.channels
                node = ProjectNode(
                    node,
                    [ColumnRef(type=c.type, index=i)
                     for i, c in enumerate(chans)][1:],
                    [c.name for c in chans[1:]],
                )
                scope = Scope(scope.cols[1:])
                g2c = {}
            for c in deferred_cs:
                if _is_subquery_conjunct(c):
                    ident = {i: i for i in range(len(scope))}
                    node, scope = self._apply_subquery_conjunct(
                        node, scope, ident, c, scope)
                else:
                    node = FilterNode(node, self._bind(c, scope))
        else:
            node = ValuesNode(names=["$dummy"], types=[BIGINT], rows=[(0,)])
            scope = Scope([])
            g2c = {}

        # subquery conjuncts (IN/EXISTS/scalar comparisons) -> joins
        pending = self._pending_subqueries
        self._pending_subqueries = []
        for c, cglob in pending:
            node, scope = self._apply_subquery_conjunct(node, scope, g2c, c, cglob)
        self._pending_subqueries = saved_pending

        # scalar subqueries in SELECT position (uncorrelated): each
        # plans standalone and cross-joins its single row onto the
        # relation; the expression binder resolves the original AST
        # node to the appended channel (TPC-DS q9's CASE-over-counts
        # shape; reference: SubqueryPlanner's apply of uncorrelated
        # scalars).  Aggregated outer queries keep the restriction.
        select_scalar_subs: List[ast.Node] = []
        for it in q.select:
            if not isinstance(it.expr, ast.Star):
                _find_scalar_subqueries(it.expr, select_scalar_subs)
        select_sub_ids: List[int] = []
        try:
            for sq in select_scalar_subs:
                sub_node, _ = self._plan_query_like(sq.query)
                ref = ColumnRef(type=sub_node.channels[0].type,
                                index=len(node.channels))
                node = CrossSingleNode(left=node, right=sub_node)
                self._scalar_refs[id(sq)] = ref
                select_sub_ids.append(id(sq))
        except BindError:
            for k in select_sub_ids:
                self._scalar_refs.pop(k, None)
            raise

        # select list expansion
        items: List[Tuple[ast.Node, str]] = []
        for it in q.select:
            if isinstance(it.expr, ast.Star):
                for sc in scope.cols:
                    if it.expr.qualifier is None or sc.qualifier == it.expr.qualifier:
                        items.append((ast.Identifier((sc.qualifier, sc.name) if sc.qualifier else (sc.name,)), sc.name))
            else:
                items.append((it.expr, it.alias or self._derive_name(it.expr)))

        group_asts = list(q.group_by)
        # ordinal group-by ("GROUP BY 1")
        group_asts = [
            items[int(g.text) - 1][0] if isinstance(g, ast.NumberLit) else g
            for g in group_asts
        ]
        grouping_sets = None
        expanded = self._expand_grouping(group_asts)
        if expanded is not None:
            group_asts, grouping_sets = expanded
        has_aggs = bool(group_asts) or grouping_sets is not None or any(
            self._contains_agg(e) for e, _ in items
        ) or (q.having is not None and self._contains_agg(q.having))

        order_items = list(q.order_by)
        if order_items:
            # ORDER BY may reference select aliases INSIDE expressions
            # (e.g. CASE WHEN lochierarchy = 0 THEN ... — TPC-DS
            # q36/q70/q86); substitute the aliased expression wherever
            # the name does not resolve as a real column
            alias_map = {n: se for se, n in items
                         if not isinstance(se, ast.Star)}
            order_items = [
                dataclasses.replace(
                    o, expr=self._substitute_aliases(o.expr, alias_map, scope))
                for o in order_items
            ]

        if has_aggs:
            if select_sub_ids:
                for k in select_sub_ids:
                    self._scalar_refs.pop(k, None)
                raise BindError(
                    "scalar subquery in the SELECT of an aggregating "
                    "query unsupported")
            node, out_irs, names, order_irs = self._plan_aggregation(
                node, scope, items, group_asts, q.having, order_items,
                grouping_sets=grouping_sets,
            )
        else:
            if q.having is not None:
                raise BindError("HAVING without aggregation")
            try:
                out_irs = [self._bind(e, scope) for e, _ in items]
            finally:
                for k in select_sub_ids:
                    self._scalar_refs.pop(k, None)
            names = [n for _, n in items]
            order_irs = self._bind_order(order_items, items, out_irs, scope)

        # windows sit above aggregation/having; patch sentinel refs to
        # real appended channels
        if self._windows:
            node, win_map = self._attach_windows(node)
            out_irs = [self._patch_windows(ir, win_map) for ir in out_irs]
            order_irs = [self._patch_windows(ir, win_map) for ir in order_irs]

        node = ProjectNode(node, out_irs + [ir for ir in order_irs if ir not in out_irs],
                           names + [f"$order{i}" for i, ir in enumerate(order_irs) if ir not in out_irs])
        # order exprs as channel refs over the project output
        order_channels: List[ColumnRef] = []
        for ir in order_irs:
            idx = node.projections.index(ir)
            order_channels.append(ColumnRef(type=ir.type, index=idx))

        if q.distinct:
            node = AggregationNode(
                node,
                [ColumnRef(type=c.type, index=i) for i, c in enumerate(node.channels)],
                node.output_names,
                [], [],
                max_groups=self._distinct_capacity(node),
            )

        if order_items:
            asc = [o.ascending for o in order_items]
            nf = [o.nulls_first if o.nulls_first is not None else (not o.ascending) for o in order_items]
            if q.limit is not None:
                node = TopNNode(node, order_channels, asc, q.limit, nf)
            else:
                node = SortNode(node, order_channels, asc, nf)
        elif q.limit is not None:
            node = LimitNode(node, q.limit)

        if len(node.channels) > len(names):  # drop hidden order-by channels
            node = ProjectNode(
                node,
                [ColumnRef(type=c.type, index=i) for i, c in enumerate(node.channels[: len(names)])],
                names,
            )
        return node, names

    def _expand_grouping(self, group_by) -> Optional[Tuple[List[ast.Node], List[List[int]]]]:
        """Expand ROLLUP/CUBE/GROUPING SETS group-by items into
        (full key list, grouping sets as key-index lists); None for plain
        GROUP BY. Mixed items combine by cartesian concatenation, the
        reference's semantics (sql/analyzer/StatementAnalyzer.java
        analyzeGroupBy: cross product of grouping-element sets)."""
        comps: List[List[Tuple[ast.Node, ...]]] = []
        plain = True
        for g in group_by:
            if isinstance(g, ast.Rollup):
                comps.append([tuple(g.items[:i]) for i in range(len(g.items), -1, -1)])
                plain = False
            elif isinstance(g, ast.Cube):
                sets = []
                for bits in range(1 << len(g.items)):
                    sets.append(tuple(e for i, e in enumerate(g.items) if bits & (1 << i)))
                comps.append(sets)
                plain = False
            elif isinstance(g, ast.GroupingSets):
                comps.append([tuple(s) for s in g.sets])
                plain = False
            else:
                comps.append([(g,)])
        if plain:
            return None
        combined: List[Tuple[ast.Node, ...]] = [()]
        for sets in comps:
            combined = [c + s for c in combined for s in sets]
        full: List[ast.Node] = []
        for s in combined:
            for e in s:
                if e not in full:
                    full.append(e)
        sets_idx = [sorted({full.index(e) for e in s}) for s in combined]
        return full, sets_idx

    def _distinct_capacity(self, node: PlanNode) -> int:
        est = int(self._estimate(node))
        return max(1 << 10, min(1 << (max(est - 1, 1)).bit_length(), 1 << 24))

    def _derive_name(self, e: ast.Node) -> str:
        if isinstance(e, ast.Identifier):
            return e.name
        if isinstance(e, ast.FuncCall):
            return e.name
        return "_col"

    def _contains_agg(self, e: ast.Node) -> bool:
        if isinstance(e, ast.WindowExpr):
            # a window function is NOT an aggregate query trigger —
            # only aggregates nested inside its arguments are
            return (
                any(self._contains_agg(a) for a in e.func.args)
                or any(self._contains_agg(p) for p in e.partition_by)
                or any(self._contains_agg(o.expr) for o in e.order_by)
            )
        if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCTIONS:
            return True
        for f in dataclasses.fields(e) if dataclasses.is_dataclass(e) else []:
            v = getattr(e, f.name)
            for x in v if isinstance(v, tuple) else [v]:
                if isinstance(x, ast.Node) and not isinstance(x, ast.Query) and self._contains_agg(x):
                    return True
        return False

    # ------------------------------------------------------------------
    def _plan_aggregation(self, node, scope, items, group_asts, having, order_items,
                          grouping_sets=None):
        group_irs = [self._bind(g, scope) for g in group_asts]
        if grouping_sets is not None:
            # GROUPING SETS: replicate rows per set via GroupIdNode and
            # aggregate once grouped by (keys..., $group_id); inactive
            # keys are NULL-masked so each set groups independently.
            nsrc = len(scope)
            key_names = [self._derive_name(g) for g in group_asts]
            masks = [[i in s for i in range(len(group_asts))] for s in grouping_sets]
            node = GroupIdNode(node, group_irs, key_names, masks)
            group_irs = [
                ColumnRef(type=g.type, index=nsrc + i, name=key_names[i])
                for i, g in enumerate(group_irs)
            ] + [ColumnRef(type=BIGINT, index=nsrc + len(group_asts), name="$group_id")]
        agg_ctx = AggCtx(group_asts=group_asts, group_irs=group_irs,
                         set_masks=masks if grouping_sets is not None else None)

        out_irs = [self._bind_agg(e, scope, agg_ctx) for e, _ in items]
        names = [n for _, n in items]

        # HAVING: plain conjuncts filter the agg output; conjuncts with
        # scalar subqueries ANYWHERE in the expression — bare (Q11) or
        # nested in arithmetic (TPC-DS q44's avg(x) > 0.9 * (select …))
        # — plan each subquery as a single-row cross join and bind the
        # subquery positions to negative sentinel refs, remapped to the
        # spliced cross-join channels after the aggregation is built.
        having_plain: List[Expr] = []
        having_sub: List[Tuple[Expr, List[PlanNode], bool]] = []
        for c in split_conjuncts(having):
            negated = False
            while isinstance(c, ast.Unary) and c.op == "not":
                negated = not negated
                c = c.operand
            subs: List[ast.Node] = []
            _find_scalar_subqueries(c, subs)
            if subs:
                planned: List[PlanNode] = []
                for k, sq in enumerate(subs):
                    sub_node, _ = self._plan_query_like(sq.query)
                    self._scalar_refs[id(sq)] = ColumnRef(
                        type=sub_node.channels[0].type, index=-(k + 1))
                    planned.append(sub_node)
                try:
                    ir = self._bind_agg(c, scope, agg_ctx)
                finally:
                    for sq in subs:
                        self._scalar_refs.pop(id(sq), None)
                having_sub.append((ir, planned, negated))
            elif _is_subquery_conjunct(c):
                raise BindError(
                    "only scalar subqueries are supported in HAVING")
            else:
                ir = self._bind_agg(c, scope, agg_ctx)
                having_plain.append(call("not", ir) if negated else ir)
        order_irs = []
        for o in order_items:
            e = o.expr
            if isinstance(e, ast.NumberLit):  # ordinal
                order_irs.append(out_irs[int(e.text) - 1])
                continue
            # select alias?
            alias_hit = next(
                (out_irs[i] for i, (se, n) in enumerate(items) if isinstance(e, ast.Identifier) and e.name == n),
                None,
            )
            if alias_hit is not None:
                order_irs.append(alias_hit)
            else:
                order_irs.append(self._bind_agg(e, scope, agg_ctx))

        group_names = [self._derive_name(g) for g in group_asts]
        if grouping_sets is not None:
            group_names = group_names + ["$group_id"]
        agg_names = [f"$agg{j}" for j in range(len(agg_ctx.aggs))]

        # approx_percentile: exact-rank rewrite through a window pre-pass
        if any(a.fn == "approx_percentile" for a in agg_ctx.aggs):
            node = self._rewrite_approx_percentile(node, group_irs, agg_ctx)

        # histogram: two-level rewrite (inner per-value counts, outer
        # map_agg) — HistogramAggregation analog
        if any(a.fn == "histogram" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_histogram(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # approx_distinct: HyperLogLog two-level aggregation rewrite
        if any(a.fn == "approx_distinct" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_approx_distinct(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # multimap_agg: inner per-(keys, k) array_agg(v), outer scatter
        if any(a.fn == "multimap_agg" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_multimap(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # numeric_histogram: window min/max span -> fixed-width bins
        if any(a.fn == "numeric_histogram" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_numeric_histogram(
                node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # approx_set: two-level HLL rewrite materializing the sketch
        if any(a.fn == "approx_set" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_approx_set(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # merge(hll): unnest sketch registers, per-bucket max, re-sketch
        if any(a.fn == "merge" for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_hll_union(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        # distinct aggregates: rewrite through a distinct pre-aggregation
        if any(a.distinct for a in agg_ctx.aggs):
            node, agg_ctx = self._rewrite_distinct_aggs(node, scope, group_irs, agg_ctx)
            group_irs = agg_ctx.group_irs

        est = self._estimate(node)
        agg = AggregationNode(
            node, group_irs, group_names, agg_ctx.aggs, agg_names,
            max_groups=self._group_capacity(group_irs, scope, est, node=node),
            presorted=self._input_presorted(node, group_irs),
        )
        out: PlanNode = agg
        for ir in having_plain:
            out = FilterNode(out, ir)
        for ir, planned, negated in having_sub:
            mapping = {r: r for r in expr_refs(ir) if r >= 0}
            for k, sub_node in enumerate(planned):
                mapping[-(k + 1)] = len(out.channels)
                out = CrossSingleNode(left=out, right=sub_node)
            pred = remap_expr(ir, mapping)
            if negated:
                pred = call("not", pred)
            out = FilterNode(out, pred)
        return out, out_irs, names, order_irs

    def _group_capacity(self, group_irs: List[Expr], scope: Scope, est_rows: float,
                        node: Optional[PlanNode] = None) -> int:
        """Initial group capacity from domains / NDV stats; the executor
        doubles (or spills) on overflow, so this is a starting size, not
        a correctness bound."""
        if not group_irs:
            return 1
        prod = 1.0
        for g in group_irs:
            ndv = None
            if isinstance(g, ColumnRef):
                if node is not None:
                    ndv = self._stats.estimate(node).col(g.index).ndv
                if ndv is None and g.index < len(scope.cols) \
                        and scope.cols[g.index].channel.domain is not None:
                    lo, hi = scope.cols[g.index].channel.domain
                    ndv = float(hi - lo + 2)
            if ndv is None:
                prod = float(1 << 60)
                break
            prod *= max(ndv, 1.0)
        cap = int(min(prod, est_rows + 1))
        cap = 1 << (max(cap - 1, 1)).bit_length()
        return max(1 << 4, min(cap, 1 << 24))

    def _rewrite_approx_percentile(self, node, group_irs, agg_ctx: AggCtx):
        """approx_percentile(x, p) -> max(if(rn = floor(p*(cnt-1))+1, x))
        over a window pre-pass computing rn = row_number() and cnt =
        count(x) per group partition ordered by x. Exact rank selection
        (better than the reference's qdigest approximation,
        operator/aggregation/ApproximateLongPercentileAggregations.java)
        expressed with existing segmented-scan machinery — no sketch
        state to merge. Entries are replaced in place so already-bound
        output references stay valid."""
        from presto_tpu.ops.window import WindowFunc
        from presto_tpu.planner.plan import WindowNode

        win_cache: Dict[tuple, tuple] = {}  # (x, w) -> channel refs
        for j, a in enumerate(list(agg_ctx.aggs)):
            if a.fn != "approx_percentile":
                continue
            if a.distinct:
                raise BindError("approx_percentile DISTINCT unsupported")
            x, p = a.arg, a.arg2
            cache_key = (x, a.arg3, a.filter)
            base = len(node.channels)
            if a.arg3 is not None:
                # weighted: smallest x whose running weight (ordered by
                # x) reaches p * total weight — exact weighted rank
                # selection via a running-sum window (one window pass
                # per distinct (x, w) spec, shared by ARRAY fractions)
                from presto_tpu.ops.window import WindowFunc
                from presto_tpu.planner.plan import WindowNode

                if cache_key in win_cache:
                    cw, tw = win_cache[cache_key]
                    hit = call("ge", cw, call("mul", p, tw))
                    newarg = call("if", hit, x,
                                  Literal(type=x.type, value=None))
                    agg_ctx.aggs[j] = AggCall(fn="min", arg=newarg,
                                              type=a.type, filter=a.filter)
                    continue

                w = call("cast_double", a.arg3) \
                    if a.arg3.type.name != "double" else a.arg3
                # rows the aggregate ignores (NULL x, FILTER-excluded)
                # must not contribute weight to the running/total sums
                counted = call("not_null", x)
                if a.filter is not None:
                    counted = call("and", counted, a.filter)
                w = call("if", counted, w, Literal(type=DOUBLE, value=0.0))
                node = WindowNode(
                    source=node, partition_exprs=list(group_irs),
                    order_exprs=[x], ascending=[True],
                    funcs=[WindowFunc(kind="sum", arg=w),
                           WindowFunc(kind="sum", arg=w, frame=("whole",))],
                    func_names=[f"$pctl_cw{j}", f"$pctl_tw{j}"],
                )
                cw = ColumnRef(type=DOUBLE, index=base)
                tw = ColumnRef(type=DOUBLE, index=base + 1)
                win_cache[cache_key] = (cw, tw)
                hit = call("ge", cw, call("mul", p, tw))
                newarg = call("if", hit, x, Literal(type=x.type, value=None))
                agg_ctx.aggs[j] = AggCall(fn="min", arg=newarg, type=a.type,
                                          filter=a.filter)
                continue
            if cache_key in win_cache:
                rn_ref, cnt_ref = win_cache[cache_key]
            else:
                node = WindowNode(
                    source=node,
                    partition_exprs=list(group_irs),
                    order_exprs=[x],
                    ascending=[True],
                    funcs=[WindowFunc(kind="row_number"),
                           WindowFunc(kind="count", arg=x, frame=("whole",))],
                    func_names=[f"$pctl_rn{j}", f"$pctl_cnt{j}"],
                )
                rn_ref = ColumnRef(type=BIGINT, index=base)
                cnt_ref = ColumnRef(type=BIGINT, index=base + 1)
                win_cache[cache_key] = (rn_ref, cnt_ref)
            target = call(
                "add",
                call("cast_bigint",
                     call("floor",
                          call("mul", p,
                               call("cast_double",
                                    call("sub", cnt_ref, Literal(type=BIGINT, value=1)))))),
                Literal(type=BIGINT, value=1),
            )
            newarg = call("if", call("eq", rn_ref, target), x,
                          Literal(type=x.type, value=None))
            agg_ctx.aggs[j] = AggCall(fn="max", arg=newarg, type=a.type,
                                      filter=a.filter)
        return node

    def _rewrite_histogram(self, node, scope, group_irs, agg_ctx: AggCtx):
        """histogram(x) -> inner aggregation grouped by (keys..., x)
        computing count(*), outer map_agg(x, count)
        (operator/aggregation/histogram/Histogram.java realized through
        the engine's own container machinery)."""
        if not all(a.fn == "histogram" for a in agg_ctx.aggs):
            raise BindError("histogram cannot mix with other aggregates")
        args = {a.arg for a in agg_ctx.aggs}
        if len(args) != 1:
            raise BindError("multiple histogram arguments unsupported")
        (arg,) = args
        inner_keys = group_irs + [arg]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [AggCall(fn="count_star", arg=None, type=BIGINT)], ["$cnt"],
            max_groups=self._group_capacity(
                inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i) for i, g in enumerate(group_irs)]
        x_ref = ColumnRef(type=arg.type, index=len(group_irs))
        cnt_ref = ColumnRef(type=BIGINT, index=len(inner_keys))
        from presto_tpu.ops.aggregate import output_type as _agg_out

        proto = AggCall(fn="map_agg", arg=x_ref, type=arg.type, arg2=cnt_ref)
        new_aggs = [dataclasses.replace(proto, type=_agg_out(proto))
                    for _ in agg_ctx.aggs]
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group,
                     aggs=new_aggs)
        return inner, ctx

    def _rewrite_approx_distinct(self, node, scope, group_irs, agg_ctx: AggCtx):
        """approx_distinct(x) -> inner aggregation grouped by
        (keys..., hll_bucket(x)) computing max(hll_rho(x)), outer
        hll_merge folding the per-bucket registers into the HLL
        estimate. Reference: ApproximateCountDistinctAggregations.java
        (airlift HyperLogLog); here the register file IS the inner
        aggregation's output — no per-group register arrays."""
        if not all(a.fn == "approx_distinct" for a in agg_ctx.aggs):
            raise BindError("approx_distinct cannot mix with other aggregates")
        args = {a.arg for a in agg_ctx.aggs}
        if len(args) != 1:
            raise BindError("multiple approx_distinct arguments unsupported")
        (arg,) = args
        inner_keys = group_irs + [call("hll_bucket", arg)]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [AggCall(fn="max", arg=call("hll_rho", arg), type=BIGINT)], ["$rho"],
            max_groups=self._group_capacity(inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i) for i, g in enumerate(group_irs)]
        rho_ref = ColumnRef(type=BIGINT, index=len(inner_keys))
        new_aggs = [AggCall(fn="hll_merge", arg=rho_ref, type=BIGINT)
                    for _ in agg_ctx.aggs]
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group, aggs=new_aggs)
        return inner, ctx

    def _rewrite_multimap(self, node, scope, group_irs, agg_ctx: AggCtx):
        """multimap_agg(k, v) -> inner aggregation grouped by
        (keys..., k) computing array_agg(v), outer scatter of
        (k, array) pairs into a MAP(K, ARRAY(V)) value (reference:
        MultimapAggregationFunction; the nested value lanes stay fixed
        matrices so the scatter is one 2-D gather)."""
        if not all(a.fn == "multimap_agg" for a in agg_ctx.aggs):
            raise BindError("multimap_agg cannot mix with other aggregates")
        pairs = {(a.arg, a.arg2) for a in agg_ctx.aggs}
        if len(pairs) != 1:
            raise BindError("multiple multimap_agg argument pairs unsupported")
        ((karg, varg),) = pairs
        inner_keys = group_irs + [karg]
        from presto_tpu.ops.aggregate import output_type as _agg_out

        arr_proto = AggCall(fn="array_agg", arg=varg, type=varg.type)
        arr_proto = dataclasses.replace(arr_proto, type=_agg_out(arr_proto))
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [arr_proto], ["$vals"],
            max_groups=self._group_capacity(
                inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i)
                     for i, g in enumerate(group_irs)]
        k_ref = ColumnRef(type=karg.type, index=len(group_irs))
        arr_ref = ColumnRef(type=arr_proto.type, index=len(inner_keys))
        proto = AggCall(fn="multimap_agg", arg=k_ref, type=karg.type,
                        arg2=arr_ref)
        proto = dataclasses.replace(proto, type=_agg_out(proto))
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group,
                     aggs=[proto for _ in agg_ctx.aggs])
        return inner, ctx

    def _rewrite_numeric_histogram(self, node, scope, group_irs,
                                   agg_ctx: AggCtx):
        """numeric_histogram(b, x) -> window (min/max of x per group)
        -> bin index -> inner per-(keys, bin) avg(x) + count ->
        outer map_agg(mean, count) as MAP(DOUBLE, DOUBLE)
        (NumericHistogramAggregation's role: per-bin centroids and
        weights; fixed-width bins over the group's span instead of the
        reference's streaming Ben-Haim/Tom-Tov merges)."""
        from presto_tpu.ops.window import WindowFunc
        from presto_tpu.planner.plan import WindowNode
        from presto_tpu.ops.aggregate import output_type as _agg_out

        if not all(a.fn == "numeric_histogram" for a in agg_ctx.aggs):
            raise BindError(
                "numeric_histogram cannot mix with other aggregates")
        pairs = {(a.arg, a.arg2.value) for a in agg_ctx.aggs}
        if len(pairs) != 1:
            raise BindError("multiple numeric_histogram arguments unsupported")
        ((arg, nb),) = pairs
        nb = int(nb)
        base = len(node.channels)
        x = call("cast_double", arg) if arg.type.name != "double" else arg
        node = WindowNode(
            source=node, partition_exprs=list(group_irs), order_exprs=[],
            ascending=[],
            funcs=[WindowFunc(kind="min", arg=x, frame=("whole",)),
                   WindowFunc(kind="max", arg=x, frame=("whole",))],
            func_names=["$nh_min", "$nh_max"],
        )
        mn = ColumnRef(type=DOUBLE, index=base)
        mx = ColumnRef(type=DOUBLE, index=base + 1)
        width = call("div", call("sub", mx, mn),
                     Literal(type=DOUBLE, value=float(nb)))
        safe_w = call("if", call("gt", width, Literal(type=DOUBLE, value=0.0)),
                      width, Literal(type=DOUBLE, value=1.0))
        bidx = call("least",
                    call("cast_bigint",
                         call("floor", call("div", call("sub", x, mn), safe_w))),
                    Literal(type=BIGINT, value=nb - 1))
        inner_keys = group_irs + [bidx]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [AggCall(fn="avg", arg=x, type=DOUBLE),
             AggCall(fn="count", arg=x, type=BIGINT)],
            ["$mean", "$cnt"],
            max_groups=self._group_capacity(
                inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i)
                     for i, g in enumerate(group_irs)]
        mean_ref = ColumnRef(type=DOUBLE, index=len(inner_keys))
        cnt_ref = call("cast_double",
                       ColumnRef(type=BIGINT, index=len(inner_keys) + 1))
        proto = AggCall(fn="map_agg", arg=mean_ref, type=DOUBLE, arg2=cnt_ref)
        proto = dataclasses.replace(proto, type=_agg_out(proto))
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group,
                     aggs=[proto for _ in agg_ctx.aggs])
        return inner, ctx

    def _rewrite_approx_set(self, node, scope, group_irs, agg_ctx: AggCtx):
        """approx_set(x) -> inner aggregation grouped by
        (keys..., hll_bucket(x, P)) computing max(hll_rho(x, P)), outer
        hll_sketch scattering (bucket, rho) into the HYPERLOGLOG map
        value (reference: ApproximateSetAggregation.java producing a
        P4HyperLogLog; here the sketch is the map_agg scatter over
        m = HLL_SET_BUCKETS registers)."""
        from presto_tpu.types import HLL_SET_BUCKETS, HllType

        if not all(a.fn == "approx_set" for a in agg_ctx.aggs):
            raise BindError("approx_set cannot mix with other aggregates")
        args = {a.arg for a in agg_ctx.aggs}
        if len(args) != 1:
            raise BindError("multiple approx_set arguments unsupported")
        (arg,) = args
        p_lit = Literal(type=BIGINT, value=HLL_SET_BUCKETS.bit_length() - 1)
        inner_keys = group_irs + [call("hll_bucket", arg, p_lit)]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [AggCall(fn="max", arg=call("hll_rho", arg, p_lit), type=BIGINT)],
            ["$rho"],
            max_groups=self._group_capacity(
                inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i)
                     for i, g in enumerate(group_irs)]
        bucket_ref = ColumnRef(type=BIGINT, index=len(group_irs))
        rho_ref = ColumnRef(type=BIGINT, index=len(inner_keys))
        new_aggs = [AggCall(fn="hll_sketch", arg=bucket_ref, type=HllType(),
                            arg2=rho_ref)
                    for _ in agg_ctx.aggs]
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group,
                     aggs=new_aggs)
        return inner, ctx

    def _rewrite_hll_union(self, node, scope, group_irs, agg_ctx: AggCtx):
        """merge(sketch) -> unnest each sketch's (bucket, rho) entries,
        per-(keys, bucket) max(rho), re-sketch — HLL union as plain
        relational algebra (reference: MergeHyperLogLogAggregation)."""
        from presto_tpu.planner.plan import UnnestNode
        from presto_tpu.types import HllType

        if not all(a.fn == "merge" for a in agg_ctx.aggs):
            raise BindError("merge cannot mix with other aggregates")
        args = {a.arg for a in agg_ctx.aggs}
        if len(args) != 1:
            raise BindError("multiple merge arguments unsupported")
        (arg,) = args
        if not arg.type.is_hll:
            raise BindError("merge() expects a HYPERLOGLOG argument "
                            "(approx_set output)")
        base = len(node.channels)
        node = UnnestNode(node, [arg], ["$hbucket", "$hrho"])
        bucket_col = ColumnRef(type=BIGINT, index=base)
        rho_col = ColumnRef(type=BIGINT, index=base + 1)
        inner_keys = group_irs + [bucket_col]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            [AggCall(fn="max", arg=rho_col, type=BIGINT)], ["$rho"],
            max_groups=self._group_capacity(
                inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i)
                     for i, g in enumerate(group_irs)]
        bucket_ref = ColumnRef(type=BIGINT, index=len(group_irs))
        rho_ref = ColumnRef(type=BIGINT, index=len(inner_keys))
        new_aggs = [AggCall(fn="hll_sketch", arg=bucket_ref, type=HllType(),
                            arg2=rho_ref)
                    for _ in agg_ctx.aggs]
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group,
                     aggs=new_aggs)
        return inner, ctx

    # non-distinct aggregates that survive the two-level distinct
    # rewrite: inner per-(g, x) value re-aggregated by the outer fn
    # count re-aggregates through sum0 (sum with 0-on-empty): a plain
    # count must stay 0, never NULL, over empty input
    _DECOMPOSABLE_OUTER = {"sum": "sum", "count": "sum0",
                           "count_star": "sum0", "min": "min", "max": "max"}

    def _rewrite_distinct_aggs(self, node, scope, group_irs, agg_ctx: AggCtx):
        """agg(DISTINCT x) GROUP BY g  ->  inner group on (g, x), outer
        re-aggregation (MarkDistinct /
        MultipleDistinctAggregationToMarkDistinct analog).  All DISTINCT
        aggregates must share one argument; non-distinct aggregates mix
        in when they are decomposable (sum/count/min/max): the inner
        level computes them per (g, x) and the outer level re-combines
        (count(distinct o) + sum(cost) — the TPC-DS q16/q95 shape)."""
        distinct_args = {a.arg for a in agg_ctx.aggs if a.distinct}
        if len(distinct_args) != 1:
            raise BindError("mixed/multi-arg DISTINCT aggregates unsupported")
        plain = [a for a in agg_ctx.aggs if not a.distinct]
        if not all(a.fn in self._DECOMPOSABLE_OUTER for a in plain):
            raise BindError(
                "DISTINCT aggregates mix only with sum/count/min/max")
        (arg,) = distinct_args
        inner_keys = group_irs + [arg]
        inner = AggregationNode(
            node, inner_keys, [f"$k{i}" for i in range(len(inner_keys))],
            list(plain), [f"$p{i}" for i in range(len(plain))],
            max_groups=self._group_capacity(inner_keys, scope, self._estimate(node), node=node),
        )
        new_group = [ColumnRef(type=g.type, index=i) for i, g in enumerate(group_irs)]
        arg_ref = ColumnRef(type=arg.type, index=len(group_irs))
        inner_out = inner.channels
        new_aggs = []
        plain_pos = 0
        for a in agg_ctx.aggs:
            if a.distinct:
                new_aggs.append(
                    AggCall(fn=a.fn, arg=arg_ref, type=a.type, distinct=False))
            else:
                ref = ColumnRef(
                    type=inner_out[len(inner_keys) + plain_pos].type,
                    index=len(inner_keys) + plain_pos,
                )
                new_aggs.append(AggCall(fn=self._DECOMPOSABLE_OUTER[a.fn],
                                        arg=ref, type=a.type))
                plain_pos += 1
        ctx = AggCtx(group_asts=agg_ctx.group_asts, group_irs=new_group, aggs=new_aggs)
        return inner, ctx

    # ==================================================================
    # subquery conjuncts
    # ==================================================================
    def _apply_subquery_conjunct(self, node, scope, g2c, c: ast.Node, glob: Scope):
        negated = False
        while isinstance(c, ast.Unary) and c.op == "not":
            negated = not negated
            c = c.operand

        remap = dict(g2c)

        if isinstance(c, ast.InSubquery):
            if self._is_correlated(c.query, glob):
                # correlated IN: x IN (select y from t where corr) ==
                # EXISTS (select 1 from t where corr and y = x) — the
                # membership equality becomes one more correlation
                # equi-conjunct (TransformCorrelatedInPredicateToJoin)
                q = c.query
                if len(q.select) != 1 or isinstance(q.select[0].expr, ast.Star):
                    raise BindError("IN subquery must select one column")
                if q.group_by or q.having or q.limit is not None \
                        or self._contains_agg(q.select[0].expr):
                    raise BindError(
                        "correlated IN over an aggregated/limited subquery "
                        "is unsupported")
                eq = ast.Binary("=", q.select[0].expr, c.value)
                new_where = eq if q.where is None else \
                    ast.Binary("and", q.where, eq)
                q2 = dataclasses.replace(
                    q, select=(ast.SelectItem(ast.NumberLit("1"), None),),
                    where=new_where)
                kind = "anti" if (negated ^ c.negated) else "semi"
                return self._plan_exists(node, scope, remap, glob, q2, kind)
            sub, sub_names = self._plan_query_like(c.query)
            value_ir = remap_expr(self._bind(c.value, glob), remap)
            kind = "anti" if (negated ^ c.negated) else "semi"
            join = JoinNode(
                left=node, right=sub,
                left_keys=[value_ir],
                right_keys=[ColumnRef(type=sub.channels[0].type, index=0)],
                kind=kind,
                null_aware=True,  # ANSI three-valued IN/NOT IN
            )
            return join, scope

        if isinstance(c, ast.Exists):
            kind = "anti" if (negated ^ c.negated) else "semi"
            return self._plan_exists(node, scope, remap, glob, c.query, kind)

        contained_marks: List[ast.Node] = []
        _find_mark_subqueries(c, contained_marks)
        if isinstance(c, ast.Binary) and not contained_marks:
            # the scalar subquery may sit anywhere inside the comparison
            # (e.g. price > 1.2 * (select avg(...))): plan it, bind the
            # conjunct with the subquery replaced by a marker ref, then
            # remap the marker to the planned output channel
            subs: List[ast.Node] = []
            _find_scalar_subqueries(c, subs)
            # one plan per DISTINCT node: the quantified-comparison
            # desugar shares one comparison subtree across CASE whens,
            # so the same ScalarSubquery object can occur repeatedly
            seen_ids = set()
            subs = [sq for sq in subs
                    if not (id(sq) in seen_ids or seen_ids.add(id(sq)))]
            if not subs:
                raise BindError("no scalar subquery found in conjunct")
            # any number of scalar subqueries per conjunct (quantified
            # comparisons desugar to CASEs over min/max + two counts):
            # each plans as a single-row cross join, bound through a
            # distinct marker ref remapped to its spliced channel
            markers: Dict[int, int] = {}
            for j, sq in enumerate(subs):
                node, scope, value_ref = self._plan_scalar_subquery(
                    node, scope, remap, glob, sq.query)
                marker = (1 << 28) + j
                self._scalar_refs[id(sq)] = ColumnRef(
                    type=value_ref.type, index=marker)
                markers[marker] = value_ref.index
            try:
                ir = self._bind(c, glob)
            finally:
                for sq in subs:
                    self._scalar_refs.pop(id(sq), None)
            full_map = dict(remap)
            full_map.update(markers)
            pred = remap_expr(ir, full_map)
            if negated:
                pred = call("not", pred)
            return FilterNode(node, pred), scope

        # General fallback: a boolean expression with EXISTS/IN-subquery
        # operands in arbitrary positions (e.g. OR of two EXISTS — the
        # TPC-DS q10/q35 shape).  Each subquery lowers to a MARK join
        # appending a boolean presence column; the expression then binds
        # with the subquery operands replaced by those columns
        # (the reference's mark semijoin: SemiJoinNode + the rewrite in
        # TransformExistsApplyToLateralNode/MarkDistinct machinery).
        marks: List[ast.Node] = []
        _find_mark_subqueries(c, marks)
        if marks:
            full_map = dict(remap)
            planned: List[int] = []
            try:
                for j, m in enumerate(marks):
                    if isinstance(m, ast.Exists):
                        node, mark_idx = self._plan_exists_mark(
                            node, remap, glob, m.query)
                    else:
                        node, mark_idx = self._plan_in_mark(node, remap, glob, m)
                    marker = (1 << 28) + j
                    from presto_tpu.types import BOOLEAN as _BOOLEAN

                    self._mark_refs[id(m)] = ColumnRef(type=_BOOLEAN, index=marker)
                    planned.append(id(m))
                    full_map[marker] = mark_idx
                ir = self._bind(c, glob)
            finally:
                for key in planned:
                    self._mark_refs.pop(key, None)
            pred = remap_expr(ir, full_map)
            if negated:
                pred = call("not", pred)
            return FilterNode(node, pred), scope

        raise BindError(f"unsupported subquery conjunct {c!r}")

    def _plan_exists_mark(self, node, remap, glob, q):
        """EXISTS as a mark join: returns (new node, channel index of
        the boolean presence column)."""
        if isinstance(q, ast.Union):
            raise BindError("EXISTS over UNION unsupported")
        terms, inner_conjuncts, corr, corr_extra, nested, inner_glob = \
            self._split_correlation(q, glob)
        if not corr:
            raise BindError("uncorrelated EXISTS unsupported")
        if nested or corr_extra:
            raise BindError("complex correlation under OR'd EXISTS unsupported")
        saved = self._pending_subqueries
        self._pending_subqueries = []
        inner_node, _, inner_map = self._join_terms(terms, inner_conjuncts)
        self._pending_subqueries = saved
        left_keys = [
            remap_expr(ColumnRef(type=glob.cols[g].channel.type, index=g), remap)
            for _, g in corr
        ]
        right_keys = [remap_expr(ir, inner_map) for ir, _ in corr]
        mark_idx = len(node.channels)
        join = JoinNode(left=node, right=inner_node,
                        left_keys=left_keys, right_keys=right_keys, kind="mark")
        return join, mark_idx

    def _plan_in_mark(self, node, remap, glob, m):
        """value IN (subquery) as a mark join (uncorrelated only).
        The mark is three-valued (HashSemiJoinOperator.java:32): NULL
        when unmatched with a NULL probe value or a NULL on the
        subquery side, so negated uses under OR agree with ANSI IN."""
        sub, _ = self._plan_query_like(m.query)
        value_ir = remap_expr(self._bind(m.value, glob), remap)
        mark_idx = len(node.channels)
        join = JoinNode(
            left=node, right=sub, left_keys=[value_ir],
            right_keys=[ColumnRef(type=sub.channels[0].type, index=0)],
            kind="mark",
            null_aware=True,
        )
        return join, mark_idx

    def _is_correlated(self, q: ast.Query, outer_glob: Scope) -> bool:
        """A subquery is correlated iff it does not bind standalone."""
        try:
            self._plan_query_like(q)
            return False
        except BindError:
            return True

    def _split_correlation(self, q: ast.Query, outer_glob: Scope):
        """Plan a subquery's FROM; bind its WHERE in (inner + outer)
        scope; separate correlation equi-conjuncts from inner filters."""
        terms, conjuncts = self._flatten_from(q.from_)
        conjuncts = conjuncts + split_conjuncts(q.where)
        # correlation may hide inside an OR whose branches all repeat it
        conjuncts = [x for c in conjuncts for x in _extract_common_or_ast(c)]
        inner_glob = Scope([])
        for t in terms:
            inner_glob = inner_glob.concat(t.scope)

        combined = Scope(inner_glob.cols, parent=outer_glob)

        inner_conjuncts: List[ast.Node] = []
        corr: List[Tuple[Expr, int]] = []  # (inner ir, outer glob ref)
        # non-equi correlation: (cmp fn, inner ir, outer glob ref) —
        # decorrelated via per-group min/max aggregates (Q21 shape)
        corr_extra: List[Tuple[str, Expr, int]] = []
        nested: List[ast.Node] = []
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "ne": "ne"}
        for c in conjuncts:
            if _is_subquery_conjunct(c):
                nested.append(c)
                continue
            ir = self._bind(c, combined)
            refs = expr_refs(ir)
            outer_refs = [r for r in refs if r >= len(inner_glob)]
            if not outer_refs:
                inner_conjuncts.append(c)
            elif (
                isinstance(ir, Call) and ir.fn == "eq"
                and all(isinstance(a, ColumnRef) for a in ir.args)
                and len(outer_refs) == 1
            ):
                a, b = ir.args
                if a.index >= len(inner_glob):
                    a, b = b, a
                corr.append((a, b.index - len(inner_glob)))
            elif (
                isinstance(ir, Call) and ir.fn in flip
                and len(ir.args) == 2 and len(outer_refs) == 1
            ):
                a, b = ir.args
                fn = ir.fn
                if isinstance(a, ColumnRef) and a.index >= len(inner_glob):
                    a, b, fn = b, a, flip[fn]
                if not (
                    isinstance(b, ColumnRef) and b.index >= len(inner_glob)
                    and all(r < len(inner_glob) for r in expr_refs(a))
                ):
                    raise BindError(f"unsupported correlated predicate {c!r}")
                corr_extra.append((fn, a, b.index - len(inner_glob)))
            else:
                raise BindError(f"unsupported correlated predicate {c!r}")
        return terms, inner_conjuncts, corr, corr_extra, nested, inner_glob

    def _plan_exists(self, node, scope, remap, glob, q, kind: str):
        if isinstance(q, ast.Union):
            raise BindError("EXISTS over UNION unsupported")
        terms, inner_conjuncts, corr, corr_extra, nested, inner_glob = \
            self._split_correlation(q, glob)
        if not corr:
            raise BindError("uncorrelated EXISTS unsupported")
        if nested:
            raise BindError("nested subquery in EXISTS unsupported")
        saved = self._pending_subqueries
        self._pending_subqueries = []
        inner_node, _, inner_map = self._join_terms(terms, inner_conjuncts)
        self._pending_subqueries = saved

        left_keys = [
            remap_expr(ColumnRef(type=glob.cols[g].channel.type, index=g), remap)
            for _, g in corr
        ]
        right_keys = [remap_expr(ir, inner_map) for ir, _ in corr]

        if not corr_extra:
            join = JoinNode(
                left=node, right=inner_node, left_keys=left_keys, right_keys=right_keys,
                kind=kind,
            )
            return join, scope

        # Non-equi correlation (e.g. Q21's  l2.x <> l1.x):
        # EXISTS(k = outer.k AND x <> outer.x)  <=>
        #   group inner by k with min(x), max(x); left-join on k;
        #   matched AND (min <> outer.x OR max <> outer.x).
        # (for <,<=: test min; for >,>=: test max)
        if len(corr_extra) != 1:
            raise BindError("multiple non-equi correlated predicates unsupported")
        fn, inner_x, outer_g = corr_extra[0]
        x = remap_expr(inner_x, inner_map)
        group_irs = right_keys
        aggs = [AggCall("min", x, x.type), AggCall("max", x, x.type)]
        inner_scope_cols = [
            inner_glob.cols[g] for g, _ in sorted(inner_map.items(), key=lambda kv: kv[1])
        ]
        agg = AggregationNode(
            inner_node, group_irs, [f"$k{i}" for i in range(len(group_irs))],
            aggs, ["$min", "$max"],
            max_groups=self._group_capacity(
                group_irs, Scope(inner_scope_cols), self._estimate(inner_node)
            ),
        )
        key_refs = [ColumnRef(type=g.type, index=i) for i, g in enumerate(group_irs)]
        join = JoinNode(
            left=node, right=agg, left_keys=left_keys, right_keys=key_refs,
            kind="left", unique_build=True,
        )
        base = len(node.channels) + len(group_irs)
        min_ref = ColumnRef(type=x.type, index=base)
        max_ref = ColumnRef(type=x.type, index=base + 1)
        outer_val = remap_expr(
            ColumnRef(type=glob.cols[outer_g].channel.type, index=outer_g), remap
        )
        matched = call("not_null", min_ref)
        if fn == "ne":
            cond = call("and", matched,
                        call("or", call("ne", min_ref, outer_val), call("ne", max_ref, outer_val)))
        elif fn in ("lt", "le"):
            cond = call("and", matched, call(fn, min_ref, outer_val))
        elif fn in ("gt", "ge"):
            cond = call("and", matched, call(fn, max_ref, outer_val))
        else:
            raise BindError(f"unsupported correlated comparison {fn}")
        pred = cond if kind == "semi" else call("not", cond)
        return FilterNode(join, pred), scope

    def _plan_scalar_subquery(self, node, scope, remap, glob, q):
        """Returns (new node, scope, ColumnRef to the scalar value)."""
        if isinstance(q, ast.Union):
            sub_node, _ = self._plan_union(q)
            out = CrossSingleNode(left=node, right=sub_node)
            ref = ColumnRef(type=sub_node.channels[0].type, index=len(node.channels))
            return out, scope, ref
        if len(q.select) != 1:
            raise BindError("scalar subquery must select one column")
        sel = q.select[0].expr

        if not self._is_correlated(q, glob):
            # uncorrelated: plan the full query, single-row cross join
            sub_node, _ = self._plan_query_like(q)
            out = CrossSingleNode(left=node, right=sub_node)
            ref = ColumnRef(type=sub_node.channels[0].type, index=len(node.channels))
            return out, scope, ref

        terms, inner_conjuncts, corr, corr_extra, nested, inner_glob = \
            self._split_correlation(q, glob)
        if corr_extra:
            raise BindError("non-equi correlation in scalar subquery unsupported")
        if not corr:
            raise BindError(f"cannot bind scalar subquery {q!r}")
        saved = self._pending_subqueries
        self._pending_subqueries = []
        inner_node, _, inner_map = self._join_terms(terms, inner_conjuncts)
        pend = self._pending_subqueries
        self._pending_subqueries = saved
        inner_scope = Scope(
            [inner_glob.cols[g] for g, _ in sorted(inner_map.items(), key=lambda kv: kv[1])]
        )
        for c, cglob in pend:
            inner_node, inner_scope = self._apply_subquery_conjunct(
                inner_node, inner_scope, inner_map, c, cglob
            )

        # correlated scalar aggregate -> grouped agg joined on correlation
        if not self._contains_agg(sel):
            raise BindError("correlated scalar subquery must aggregate")
        group_irs = [remap_expr(ir, inner_map) for ir, _ in corr]
        agg_ctx = AggCtx(group_asts=[], group_irs=group_irs)
        sel_ir = self._bind_agg_scope(sel, inner_scope, inner_map, agg_ctx)
        agg = AggregationNode(
            inner_node, group_irs, [f"$k{i}" for i in range(len(group_irs))],
            agg_ctx.aggs, [f"$agg{j}" for j in range(len(agg_ctx.aggs))],
            max_groups=self._group_capacity(group_irs, inner_scope, self._estimate(inner_node)),
        )
        key_refs = [ColumnRef(type=g.type, index=i) for i, g in enumerate(group_irs)]
        proj = ProjectNode(agg, key_refs + [sel_ir],
                           [f"$k{i}" for i in range(len(key_refs))] + ["$scalar"])
        left_keys = [
            remap_expr(ColumnRef(type=glob.cols[g].channel.type, index=g), remap)
            for _, g in corr
        ]
        join = JoinNode(
            left=node, right=proj, left_keys=left_keys, right_keys=key_refs,
            kind="inner", unique_build=True,
        )
        ref = ColumnRef(type=sel_ir.type, index=len(node.channels) + len(key_refs))
        return join, scope, ref

    def _bind_agg_scope(self, e: ast.Node, inner_scope: Scope, inner_map, agg_ctx: AggCtx):
        """Bind a subquery select expr with aggregates over the joined
        inner tree (inner_scope indexes = tree channels)."""
        return self._bind_agg(e, inner_scope, agg_ctx)

    # ==================================================================
    # expression binding
    # ==================================================================
    def _bind(self, e: ast.Node, scope: Scope) -> Expr:
        return self._bind_impl(e, scope, None)

    def _bind_agg(self, e: ast.Node, scope: Scope, agg_ctx: AggCtx) -> Expr:
        return self._bind_impl(e, scope, agg_ctx)

    def _bind_impl(self, e: ast.Node, scope: Scope, agg: Optional[AggCtx]) -> Expr:
        try:
            return self._bind_node(e, scope, agg)
        except BindError as err:
            # attach the nearest enclosing node's statement offset; the
            # innermost failing node wins (recursion attaches first)
            if getattr(err, "pos", None) is None \
                    and getattr(e, "pos", None) is not None:
                err.pos = e.pos
            raise

    def _bind_node(self, e: ast.Node, scope: Scope, agg: Optional[AggCtx]) -> Expr:
        if agg is not None:
            # group-expr match (AST or bound-IR equality)
            for i, g in enumerate(agg.group_asts):
                if e == g:
                    return agg.key_ref(i)
            if not isinstance(e, (ast.NumberLit, ast.StringLit, ast.DateLit, ast.NullLit, ast.IntervalLit, ast.WindowExpr)):
                try:
                    ir = self._bind_impl(e, scope, None)
                    for i, g in enumerate(agg.group_irs):
                        if ir == g:
                            return agg.key_ref(i)
                except BindError:
                    pass
            if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCTIONS:
                return self._bind_agg_call(e, scope, agg)
            if isinstance(e, ast.FuncCall) and e.name == "grouping":
                return self._bind_grouping(e, scope, agg)

        if isinstance(e, ast.Identifier) and e.qualifier is None:
            for frame in reversed(self._lambda_params):
                if e.name in frame:
                    return frame[e.name]

        if isinstance(e, ast.Identifier) and e.qualifier is None \
                and e.name.lower() in ("current_date", "current_timestamp",
                                       "localtimestamp"):
            # parenless niladic datetime functions (SqlBase.g4 specialForm);
            # bind-time constants so a query sees one consistent instant
            now = self._query_now()
            if e.name.lower() == "current_date":
                return Literal(type=DATE, value=int(now // 86400))
            return Literal(type=TIMESTAMP, value=int(now * 1_000_000))

        if isinstance(e, ast.Identifier) and e.qualifier is None \
                and e.name.lower() == "current_user":
            # SqlBase.g4 specialForm CURRENT_USER -> the session user
            return Literal(type=VARCHAR, value=self.session_user())

        if isinstance(e, ast.Identifier):
            try:
                idx = scope.resolve(e.qualifier, e.name)
            except BindError:
                # r.x / t.r.x where a prefix is a ROW-typed column:
                # progressively re-resolve the prefix as a column (bare
                # or table-qualified) and walk the rest as row fields
                # (DereferenceExpression's row branch)
                if e.qualifier is None:
                    raise
                parts = e.parts
                prefixes = [(parts[:1], parts[1:])]
                if len(parts) >= 3:
                    prefixes.append((parts[:2], parts[2:]))
                for head, fields in prefixes:
                    try:
                        base = self._bind_impl(
                            ast.Identifier(tuple(head)), scope, agg)
                    except BindError:
                        continue
                    for f in fields:
                        base = self._row_field(base, f)
                    return base
                raise
            ch = scope.col(idx).channel
            if agg is not None:
                raise BindError(f"column {e.name} not in GROUP BY")
            return ColumnRef(type=ch.type, index=idx, name=e.name)

        if isinstance(e, ast.QuantifiedComparison):
            return self._bind_impl(desugar_quantified(e), scope, agg)

        if isinstance(e, ast.FieldAccess):
            return self._row_field(self._bind_impl(e.base, scope, agg),
                                   e.field)

        if isinstance(e, ast.ScalarSubquery):
            ref = self._scalar_refs.get(id(e))
            if ref is not None:
                return ref

        if isinstance(e, (ast.Exists, ast.InSubquery)):
            # lowered to a mark-join boolean column by
            # _apply_subquery_conjunct's general fallback
            ref = self._mark_refs.get(id(e))
            if ref is not None:
                return call("not", ref) if e.negated else ref

        if isinstance(e, ast.NumberLit):
            return self._bind_number(e.text)
        if isinstance(e, ast.StringLit):
            return Literal(type=VARCHAR, value=e.value)
        if isinstance(e, ast.DateLit):
            return Literal(type=DATE, value=_parse_date(e.value))
        if isinstance(e, ast.TimestampLit):
            return Literal(type=TIMESTAMP, value=_parse_timestamp(e.value))
        if isinstance(e, ast.TimeLit):
            from presto_tpu.types import TIME as _TIME

            return Literal(type=_TIME, value=_parse_time_of_day(e.value))
        if isinstance(e, ast.NullLit):
            return Literal(type=BIGINT, value=None)

        if isinstance(e, ast.IntervalLit):
            # standalone interval VALUE (spi IntervalDayTimeType /
            # IntervalYearMonthType): micros / months on device
            t, v = _interval_literal(e)
            return Literal(type=t, value=v)

        if isinstance(e, ast.Parameter):
            raise BindError(
                f"unbound parameter ?{e.index + 1} — run via EXECUTE ... USING")

        if isinstance(e, ast.Binary):
            if e.op in ("and", "or"):
                return call(e.op, self._bind_impl(e.left, scope, agg), self._bind_impl(e.right, scope, agg))
            if e.op in ("=", "<>") and (
                _is_row_ast(e.left) or _is_row_ast(e.right)
            ):
                return self._bind_impl(
                    _row_comparison(e.left, e.right, e.op), scope, agg)
            if e.op in ("=", "<>", "<", "<=", ">", ">="):
                opmap = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
                l_ir = self._bind_impl(e.left, scope, agg)
                r_ir = self._bind_impl(e.right, scope, agg)
                if l_ir.type.name == "row" or r_ir.type.name == "row":
                    raise BindError(
                        "ROW comparisons desugar pairwise — compare "
                        "row constructors directly, not row-typed "
                        "values")
                return call(opmap[e.op], l_ir, r_ir)
            if e.op in ("+", "-") and (
                isinstance(e.right, ast.IntervalLit)
                or isinstance(e.left, ast.IntervalLit)
            ) and not (isinstance(e.right, ast.IntervalLit)
                       and isinstance(e.left, ast.IntervalLit)):
                # literal-interval date arithmetic keeps the civil
                # month/year shift semantics for DATE bases — but only
                # when the OTHER side is not itself an interval
                probe = self._bind_impl(
                    e.left if isinstance(e.right, ast.IntervalLit)
                    else e.right, scope, agg)
                if not probe.type.name.startswith("interval"):
                    return self._bind_date_arith(e, scope, agg)
            opmap = {"+": "add", "-": "sub", "*": "mul", "/": "div", "%": "mod"}
            l_ir = self._bind_impl(e.left, scope, agg)
            r_ir = self._bind_impl(e.right, scope, agg)
            iv_arith = self._bind_interval_arith(e.op, l_ir, r_ir)
            if iv_arith is not None:
                return iv_arith
            if e.op == "-" and l_ir.type.name == r_ir.type.name \
                    and l_ir.type.name in ("timestamp", "date"):
                # datetime difference -> INTERVAL DAY TO SECOND
                # (IntervalDayTimeType; micros on device)
                from presto_tpu.types import INTERVAL_DAY_SECOND

                if l_ir.type.name == "date":
                    l_ir = call("cast_bigint", l_ir)
                    r_ir = call("cast_bigint", r_ir)
                    days = Call(type=BIGINT, fn="sub", args=(l_ir, r_ir))
                    return Call(
                        type=INTERVAL_DAY_SECOND, fn="mul",
                        args=(days,
                              Literal(type=BIGINT, value=MICROS_PER_DAY)))
                return Call(type=INTERVAL_DAY_SECOND, fn="sub",
                            args=(l_ir, r_ir))
            return call(opmap[e.op], l_ir, r_ir)

        if isinstance(e, ast.Unary):
            if e.op == "not":
                return call("not", self._bind_impl(e.operand, scope, agg))
            operand = self._bind_impl(e.operand, scope, agg)
            if isinstance(operand, Literal) and operand.value is not None:
                return Literal(type=operand.type, value=-operand.value)
            return call("neg", operand)

        if isinstance(e, ast.Between):
            v = self._bind_impl(e.value, scope, agg)
            lo = self._bind_impl(e.low, scope, agg)
            hi = self._bind_impl(e.high, scope, agg)
            out = call("between", v, lo, hi)
            return call("not", out) if e.negated else out

        if isinstance(e, ast.InList):
            if _is_row_ast(e.value):
                # (a, b) IN ((1, 2), (3, 4)) -> OR of pairwise ANDs
                # (sql/tree/Row.java comparisons; row(a, b) form too)
                out_ast = None
                for item in e.items:
                    conj = _row_comparison(e.value, item, "=")
                    out_ast = conj if out_ast is None else ast.Binary("or", out_ast, conj)
                if out_ast is None:
                    raise BindError("empty IN list")
                if e.negated:
                    out_ast = ast.Unary("not", out_ast)
                return self._bind_impl(out_ast, scope, agg)
            v = self._bind_impl(e.value, scope, agg)
            items = [self._bind_impl(x, scope, agg) for x in e.items]
            out = call("in", v, *items)
            return call("not", out) if e.negated else out

        if isinstance(e, ast.Like):
            v = self._bind_impl(e.value, scope, agg)
            p = self._bind_impl(e.pattern, scope, agg)
            out = call("like", v, p)
            return call("not", out) if e.negated else out

        if isinstance(e, ast.IsNull):
            v = self._bind_impl(e.value, scope, agg)
            return call("is_null" if not e.negated else "not_null", v)

        if isinstance(e, ast.WindowExpr):
            return self._register_window(e, scope, agg)

        if isinstance(e, ast.Case):
            return self._bind_case(e, scope, agg)

        if isinstance(e, ast.Cast):
            v = self._bind_impl(e.value, scope, agg)
            tn = e.type_name.lower()
            if isinstance(v, Literal) and v.type == VARCHAR \
                    and tn in ("double", "double precision", "bigint",
                               "integer", "int"):
                # unparseable / out-of-int64-range -> NULL (deviation:
                # the reference raises; the column form's dictionary
                # LUT uses the same strict parser)
                from presto_tpu.expr.compile import parse_number_strict

                return Literal(
                    type=DOUBLE if tn.startswith("double") else BIGINT,
                    value=parse_number_strict(
                        v.value, tn.startswith("double")))
            if tn in ("double", "double precision"):
                return call("cast_double", v)
            if tn in ("bigint", "integer", "int"):
                return call("cast_bigint", v)
            if tn == "date":
                if isinstance(v, Literal) and v.type == VARCHAR:
                    return Literal(type=DATE, value=_parse_date(v.value))
                return call("cast_date", v)
            if tn == "timestamp":
                if isinstance(v, Literal) and v.type == VARCHAR:
                    return Literal(type=TIMESTAMP, value=_parse_timestamp(v.value))
                return call("cast_timestamp", v)
            if tn.startswith("decimal"):
                from presto_tpu.types import parse_type

                t = parse_type(tn)
                if v.type.is_decimal and v.type.scale == t.scale \
                        and v.type.is_long_decimal == t.is_long_decimal \
                        and v.type.value_shape == t.value_shape:
                    return v
                return call("cast_decimal", v,
                            Literal(type=BIGINT, value=t.precision or 18),
                            Literal(type=BIGINT, value=t.scale or 0))
            if tn == "real":
                return call("cast_real", v)
            if tn == "smallint":
                return call("cast_smallint", v)
            if tn == "tinyint":
                return call("cast_tinyint", v)
            if tn == "time":
                from presto_tpu.types import TIME as _TIME

                if isinstance(v, Literal) and v.type == VARCHAR:
                    return Literal(type=_TIME,
                                   value=_parse_time_of_day(v.value))
                return call("cast_time", v)
            if tn.startswith("char"):
                if v.type.is_string and not v.type.is_raw_string:
                    from presto_tpu.types import parse_type

                    return call("cast_char", v,
                                Literal(type=BIGINT,
                                        value=parse_type(tn).precision or 32))
            if tn.startswith("varbinary"):
                if v.type.is_raw_string:
                    from presto_tpu.types import parse_type

                    # a raw varchar IS a byte matrix; re-type in place
                    return call("cast_varbinary", v,
                                Literal(type=BIGINT,
                                        value=parse_type(tn).precision
                                        or (v.type.precision or 32)))
            if tn.startswith("varchar"):
                # identity for string-typed values (the engine's strings
                # are dictionary codes; re-typing is metadata-only)
                if v.type.is_string:
                    return v
            if tn.startswith("row"):
                from presto_tpu.types import parse_type

                target = parse_type(tn)
                if v.type.name != "row":
                    raise BindError("CAST to ROW requires a row value")
                if len(v.type.fields) != len(target.fields):
                    raise BindError("ROW cast arity mismatch")
                if tuple(v.type.fields) == tuple(target.fields):
                    # naming-only cast: the storage matrix is unchanged
                    return Call(type=target, fn="retype_row", args=(v,))
                # field types differ: rebuild the row from converted
                # fields (value conversion, e.g. decimal -> double)
                conv = {"double": "cast_double", "bigint": "cast_bigint",
                        "integer": "cast_bigint", "real": "cast_real"}
                new_fields = []
                for i, (st, dt) in enumerate(zip(v.type.fields,
                                                 target.fields)):
                    f = Call(type=st, fn="row_field",
                             args=(v, Literal(type=BIGINT, value=i + 1)))
                    if st != dt:
                        if dt.name not in conv:
                            raise BindError(
                                f"ROW cast cannot convert {st} to {dt}")
                        f = call(conv[dt.name], f)
                    new_fields.append(f)
                return Call(type=target, fn="row_construct",
                            args=tuple(new_fields))
            raise BindError(f"unsupported CAST to {e.type_name}")

        if isinstance(e, ast.Extract):
            field = {"dow": "day_of_week", "doy": "day_of_year"}.get(e.field, e.field)
            return call(field, self._bind_impl(e.value, scope, agg))

        if isinstance(e, ast.Lambda):
            raise BindError("lambda only valid as an argument of "
                            "transform/filter/any_match/all_match/none_match")

        if isinstance(e, ast.FuncCall):
            if e.ignore_nulls:
                # only the window value functions under OVER consume it
                raise BindError(
                    "IGNORE NULLS applies to window value functions "
                    "(lead/lag/first_value/last_value/nth_value OVER)")
            if e.name in ("transform", "filter", "any_match", "all_match",
                          "none_match") and len(e.args) == 2 \
                    and isinstance(e.args[1], ast.Lambda):
                return self._bind_array_lambda(e, scope, agg)
            if (e.name in ("map_filter", "transform_keys",
                           "transform_values") and len(e.args) == 2) \
                    or (e.name == "zip_with" and len(e.args) == 3) \
                    or (e.name == "reduce" and len(e.args) == 4):
                return self._bind_container_lambda(e, scope, agg)
            if e.name == "row" and e.args:
                # first-class anonymous ROW value (spi/type/RowType.java
                # subset: fixed-width scalar fields, 1-based subscript)
                from presto_tpu.types import RowType

                items = [self._bind_impl(a, scope, agg) for a in e.args]
                try:
                    rt = RowType(*[a.type for a in items])
                except ValueError as ex:
                    raise BindError(str(ex))
                return Call(type=rt, fn="row_construct", args=tuple(items))
            if e.name == "split":
                if len(e.args) not in (2, 3):
                    raise BindError("split takes (string, delimiter"
                                    "[, limit])")
                dl = self._bind_impl(e.args[1], scope, agg)
                if not isinstance(dl, Literal) or not dl.value:
                    raise BindError(
                        "split delimiter must be a non-empty literal")
                if len(e.args) == 3:
                    lim = self._bind_impl(e.args[2], scope, agg)
                    if not isinstance(lim, Literal) or lim.value is None \
                            or not lim.type.is_integerlike \
                            or not 1 <= int(lim.value) <= 64:
                        raise BindError(
                            "split limit must be a literal in [1, 64]")
            if e.name == "map_concat" and len(e.args) > 2:
                # variadic: left-fold into binary concats
                folded = ast.FuncCall("map_concat", e.args[:2])
                for extra in e.args[2:]:
                    folded = ast.FuncCall("map_concat", (folded, extra))
                return self._bind_impl(folded, scope, agg)
            if e.name == "typeof":
                if len(e.args) != 1:
                    raise BindError("typeof takes one argument")
                arg = self._bind_impl(e.args[0], scope, agg)
                return Literal(type=VARCHAR, value=repr(arg.type))
            if e.name == "now":
                if e.args:
                    raise BindError("now() takes no arguments")
                return Literal(type=TIMESTAMP,
                               value=int(self._query_now() * 1_000_000))
            if e.name in ("pi", "e", "nan", "infinity") and not e.args:
                import math as _math

                return Literal(type=DOUBLE, value={
                    "pi": _math.pi, "e": _math.e, "nan": _math.nan,
                    "infinity": _math.inf}[e.name])
            if e.name == "to_iso8601" and len(e.args) == 1:
                # date -> ISO 'yyyy-mm-dd' via the date_format domain
                # dictionary (DateTimeFunctions.java#toISO8601);
                # timestamps would silently lose time-of-day, so reject
                arg0 = self._bind_impl(e.args[0], scope, agg)
                if arg0.type.name != "date":
                    raise BindError(
                        "to_iso8601 supports DATE arguments (a "
                        "timestamp's time-of-day has no domain "
                        "dictionary)")
                return self._bind_impl(
                    ast.FuncCall("date_format",
                                 (e.args[0], ast.StringLit("%Y-%m-%d"))),
                    scope, agg)
            if e.name in ("day_name", "month_name") and len(e.args) == 1:
                fmt = "%W" if e.name == "day_name" else "%M"
                return self._bind_impl(
                    ast.FuncCall("date_format",
                                 (e.args[0], ast.StringLit(fmt))),
                    scope, agg)
            if e.name == "format_datetime" and len(e.args) == 2:
                # Joda pattern subset -> the MySQL codes date_format
                # speaks (DateTimeFunctions.java#formatDatetime)
                p = self._bind_impl(e.args[1], scope, agg)
                if not isinstance(p, Literal) or p.value is None:
                    raise BindError(
                        "format_datetime pattern must be a literal")
                return self._bind_impl(
                    ast.FuncCall(
                        "date_format",
                        (e.args[0], ast.StringLit(_joda_to_mysql(p.value)))),
                    scope, agg)
            if e.name == "concat_ws" and len(e.args) >= 2:
                # separator-joined concat (deviation: a NULL argument
                # nulls the result; the reference skips NULLs)
                sep = e.args[0]
                parts: list = []
                for i, a in enumerate(e.args[1:]):
                    if i:
                        parts.append(sep)
                    parts.append(a)
                return self._bind_impl(
                    ast.FuncCall("concat", tuple(parts)), scope, agg)
            if e.name == "to_hex" and len(e.args) == 1 \
                    and isinstance(e.args[0], ast.FuncCall) \
                    and e.args[0].name in ("md5", "sha1", "sha256") \
                    and len(e.args[0].args) == 1 \
                    and isinstance(e.args[0].args[0], ast.FuncCall) \
                    and e.args[0].args[0].name == "to_utf8":
                # to_hex(md5(to_utf8(x))) collapses into one dictionary
                # transform (VarbinaryFunctions md5/sha*/toHexString)
                inner = e.args[0].args[0].args[0]
                return self._bind_impl(
                    ast.FuncCall(f"{e.args[0].name}_hex", (inner,)),
                    scope, agg)
            if e.name in ("week_of_year", "yow", "doy", "dow",
                          "day_of_month"):
                # DateTimeFunctions.java aliases
                canon = {"week_of_year": "week", "yow": "year_of_week",
                         "doy": "day_of_year", "dow": "day_of_week",
                         "day_of_month": "day"}[e.name]
                return self._bind_impl(
                    ast.FuncCall(canon, e.args), scope, agg)
            if e.name == "chr":
                # code point -> single-char string; literal-foldable
                # only (a column form would need a dynamic dictionary)
                arg = self._bind_impl(e.args[0], scope, agg) if e.args else None
                if not isinstance(arg, Literal):
                    raise BindError("chr requires an integer literal")
                if arg.value is None:
                    return Literal(type=VARCHAR, value=None)
                cp = int(arg.value)
                if not 0 <= cp < 0x110000:
                    raise BindError(f"chr code point out of range: {cp}")
                return Literal(type=VARCHAR, value=chr(cp))
            if e.name == "to_base":
                if len(e.args) != 2:
                    raise BindError("to_base takes (value, radix)")
                v = self._bind_impl(e.args[0], scope, agg)
                rx = self._bind_impl(e.args[1], scope, agg)
                if not isinstance(v, Literal) or not isinstance(rx, Literal):
                    raise BindError(
                        "to_base supports literal arguments only (a "
                        "column form would need a dynamic dictionary)")
                if v.value is None or rx.value is None:
                    return Literal(type=VARCHAR, value=None)
                n, radix = int(v.value), int(rx.value)
                if not 2 <= radix <= 36:
                    raise BindError("to_base radix must be in [2, 36]")
                digits = "0123456789abcdefghijklmnopqrstuvwxyz"
                m, out = abs(n), ""
                while True:
                    m, r = divmod(m, radix)
                    out = digits[r] + out
                    if m == 0:
                        break
                return Literal(type=VARCHAR,
                               value=("-" if n < 0 else "") + out)
            if e.name == "index":
                # teradata index(s, sub) = strpos (DateTimeFunctions.java
                # analog in presto-teradata-functions)
                return self._bind_impl(
                    ast.FuncCall("strpos", e.args), scope, agg)
            if e.name == "nvl":
                return self._bind_impl(
                    ast.FuncCall("coalesce", e.args), scope, agg)
            if e.name == "try":
                # TRY(e): the trappable errors the reference's
                # TryExpression catches (division by zero, unparseable
                # casts, out-of-range subscripts) already evaluate to
                # NULL engine-wide (XLA kernels cannot trap), so TRY
                # compiles to the identity (sql/tree/TryExpression.java
                # + DesugarTryExpression.java) — but the marker stays
                # in the IR so the kernel-soundness tier knows hazards
                # beneath it are sanctioned: inside TRY the reference
                # ALSO returns NULL, so NULLed lanes are not deviations
                if len(e.args) != 1:
                    raise BindError("try takes one argument")
                inner = self._bind_impl(e.args[0], scope, agg)
                return Call(type=inner.type, fn="try", args=(inner,))
            if e.name == "features":
                # presto-ml feature vector -> ARRAY(double)
                args = [call("cast_double", self._bind_impl(a, scope, agg))
                        for a in e.args]
                return call("array_construct", *args)
            if e.name in AGG_FUNCTIONS:
                if agg is None:
                    raise BindError(f"aggregate {e.name} in scalar context")
                return self._bind_agg_call(e, scope, agg)
            if e.name in SCALAR_FUNCTIONS:
                _arity = {"array_intersect": 2, "array_union": 2,
                          "array_except": 2, "arrays_overlap": 2,
                          "array_remove": 2}.get(e.name)
                if _arity is not None and len(e.args) != _arity:
                    raise BindError(
                        f"{e.name} takes {_arity} arguments")
                if e.name == "map_concat" and len(e.args) < 2:
                    raise BindError("map_concat takes at least two maps")
                args = [self._bind_impl(a, scope, agg) for a in e.args]
                folded = self._fold_literal_call(e.name, args)
                if folded is not None:
                    return folded
                if e.name == "concat" and len(args) == 2 \
                        and any(a.type.is_array for a in args):
                    # ARRAY || scalar appends the element (and the
                    # symmetric prepend) — wrap the scalar side
                    a0, a1 = args
                    if any((a.type.is_array and a.type.element is not None
                            and a.type.element.is_string)
                           or (not a.type.is_array and a.type.is_string)
                           for a in args):
                        # literal string arrays each carry their OWN
                        # derived dictionary; concatenation would mix
                        # incompatible code spaces (silent NULLs)
                        raise BindError(
                            "string-array concatenation unsupported")
                    if not a0.type.is_array:
                        a0 = call("array_construct", a0)
                    if not a1.type.is_array:
                        a1 = call("array_construct", a1)
                    return call("array_concat", a0, a1)
                if e.name == "concat":
                    if any(isinstance(a, Literal) and a.value is None for a in args):
                        return Literal(type=VARCHAR, value=None)  # NULL-propagating
                    non_lit = [a for a in args if not isinstance(a, Literal)]
                    if not non_lit:
                        return Literal(type=VARCHAR,
                                       value="".join(str(a.value) for a in args))
                    if (len(non_lit) != 1
                            and not all(a.type.is_raw_string for a in non_lit)):
                        raise BindError(
                            "multi-column concat needs raw varchar operands"
                            " (dictionary columns support one column + literals)")
                return call(e.name, *args)
            raise BindError(f"unknown function {e.name}",
                            pos=getattr(e, "pos", None))

        if isinstance(e, ast.ArrayCtor):
            items = [self._bind_impl(x, scope, agg) for x in e.items]
            if not items:
                raise BindError("empty ARRAY[] needs a typed context")
            # NULL literals adopt the elements' common type
            typed = [a for a in items if not (isinstance(a, Literal) and a.value is None)]
            if typed:
                elem_t = typed[0].type
                for a in typed[1:]:
                    elem_t = common_super_type(elem_t, a.type)
                items = [
                    Literal(type=elem_t, value=None)
                    if isinstance(a, Literal) and a.value is None else a
                    for a in items
                ]
            if any(a.type.is_string for a in items):
                # all-literal string arrays ride a derived dictionary
                # (codes constructed at compile time; VERDICT r5's
                # UNNEST(MAP(..., ARRAY['a','b'])) probe needs them);
                # anything computed stays unsupported
                if not all(isinstance(a, Literal) for a in items):
                    raise BindError(
                        "ARRAY of strings unsupported in expressions (array "
                        "columns with dictionary-coded string elements work)")
            if any(a.type.is_array or a.type.is_map for a in items):
                # element types now UNIFY (identical widths no longer
                # error, VERDICT r5), but the flat container storage
                # has no nested-array value layout — report the real
                # limitation instead of leaking a storage ValueError
                raise BindError(
                    "nested ARRAY construction unsupported: array "
                    "elements must be fixed-width scalars")
            return call("array_construct", *items)

        if isinstance(e, ast.Subscript):
            base = self._bind_impl(e.base, scope, agg)
            idx = self._bind_impl(e.index, scope, agg)
            if base.type.name == "row":
                if not isinstance(idx, Literal) or idx.value is None:
                    raise BindError("ROW field index must be a literal")
                i = int(idx.value)
                if not 1 <= i <= len(base.type.fields):
                    raise BindError(
                        f"ROW field index {i} out of range "
                        f"[1, {len(base.type.fields)}]")
                return Call(type=base.type.fields[i - 1], fn="row_field",
                            args=(base, Literal(type=BIGINT, value=i)))
            return call("subscript", base, idx)

        if isinstance(e, ast.Substring):
            v = self._bind_impl(e.value, scope, agg)
            start = self._bind_impl(e.start, scope, agg)
            if not isinstance(start, Literal):
                raise BindError("substring start must be a literal")
            args = [v, start]
            if e.length is not None:
                ln = self._bind_impl(e.length, scope, agg)
                if not isinstance(ln, Literal):
                    raise BindError("substring length must be a literal")
                args.append(ln)
            folded = self._fold_literal_call("substr", args)
            if folded is not None:
                return folded
            return call("substr", *args)

        raise BindError(f"cannot bind {e!r}")

    def _bind_array_lambda(self, e: ast.FuncCall, scope: Scope, agg) -> Expr:
        """transform/filter/..._match(arr, x -> body): the lambda body
        binds in a scope where the parameter resolves to a LambdaVar of
        the array's element type (LambdaBytecodeGenerator's captured
        scope, realized as an extra virtual channel)."""
        from presto_tpu.expr.ir import LambdaVar

        arr = self._bind_impl(e.args[0], scope, agg)
        if not arr.type.is_array:
            raise BindError(f"{e.name} expects an ARRAY first argument")
        lam: ast.Lambda = e.args[1]
        var = LambdaVar(type=arr.type.element,
                        slot=next(self._lambda_slot_seq))
        body = self._bind_lambda_body(lam.body, {lam.param: var}, scope, agg)
        fn = {"transform": "array_transform", "filter": "array_filter"}.get(
            e.name, e.name)
        if fn == "array_filter" or fn.endswith("_match"):
            if body.type.name != "boolean":
                raise BindError(f"{e.name} lambda must return boolean")
        from presto_tpu.expr.ir import LambdaExpr

        return call(fn, arr, LambdaExpr(type=body.type, params=(var,),
                                        body=body))

    def _bind_lambda_body(self, body: ast.Node, params: dict,
                          scope: Scope, agg) -> Expr:
        """Bind with the lambda parameters shadowing outer columns (and
        exempt from group-key checks inside aggregate contexts): a
        scoped parameter frame is consulted before identifier
        resolution.  ``params`` maps name -> LambdaVar."""
        self._lambda_params.append(dict(params))
        try:
            return self._bind_impl(body, scope, agg)
        finally:
            self._lambda_params.pop()

    def _bind_container_lambda(self, e: ast.FuncCall, scope: Scope,
                               agg) -> Expr:
        """map_filter / transform_keys / transform_values / zip_with /
        reduce — the multi-parameter lambda surface
        (MapFilterFunction.java, MapTransformKeyFunction.java,
        MapTransformValueFunction.java, ZipWithFunction.java,
        ReduceFunction.java).  Lambda parameters become slot-numbered
        LambdaVars bound to flattened entry lanes by the compiler."""
        from presto_tpu.expr.ir import LambdaExpr, LambdaVar
        from presto_tpu.types import ArrayType, MapType

        name = e.name

        def lam_of(a, n_params):
            if not isinstance(a, ast.Lambda) or len(a.all_params) != n_params:
                raise BindError(
                    f"{name} expects a {n_params}-parameter lambda")
            return a

        def new_var(t):
            return LambdaVar(type=t, slot=next(self._lambda_slot_seq))

        if name in ("map_filter", "transform_keys", "transform_values"):
            m = self._bind_impl(e.args[0], scope, agg)
            if not m.type.is_map or m.type.name != "map" or (
                    m.type.element is not None and m.type.element.is_array):
                raise BindError(f"{name} expects a scalar-valued map")
            lam = lam_of(e.args[1], 2)
            kv, vv = new_var(m.type.key_element), new_var(m.type.element)
            body = self._bind_lambda_body(
                lam.body, {lam.all_params[0]: kv, lam.all_params[1]: vv},
                scope, agg)
            if name == "map_filter":
                if body.type.name != "boolean":
                    raise BindError("map_filter lambda must return boolean")
                out_t = m.type
            elif name == "transform_keys":
                out_t = MapType(body.type, m.type.element, m.type.max_elems)
            else:
                out_t = MapType(m.type.key_element, body.type,
                                m.type.max_elems)
            le = LambdaExpr(type=body.type, params=(kv, vv), body=body)
            return Call(type=out_t, fn=name, args=(m, le))
        if name == "zip_with":
            a1 = self._bind_impl(e.args[0], scope, agg)
            a2 = self._bind_impl(e.args[1], scope, agg)
            if not (a1.type.is_array and a2.type.is_array):
                raise BindError("zip_with expects two arrays")
            lam = lam_of(e.args[2], 2)
            xv, yv = new_var(a1.type.element), new_var(a2.type.element)
            body = self._bind_lambda_body(
                lam.body, {lam.all_params[0]: xv, lam.all_params[1]: yv},
                scope, agg)
            out_t = ArrayType(body.type,
                              max(a1.type.max_elems, a2.type.max_elems))
            le = LambdaExpr(type=body.type, params=(xv, yv), body=body)
            return Call(type=out_t, fn=name, args=(a1, a2, le))
        # reduce(arr, init, (s, x) -> comb, s -> out)
        arr = self._bind_impl(e.args[0], scope, agg)
        if not arr.type.is_array:
            raise BindError("reduce expects an array first argument")
        init = self._bind_impl(e.args[1], scope, agg)
        comb_l = lam_of(e.args[2], 2)
        sv = new_var(init.type)
        xv = new_var(arr.type.element)
        comb = self._bind_lambda_body(
            comb_l.body, {comb_l.all_params[0]: sv, comb_l.all_params[1]: xv},
            scope, agg)
        if comb.type != init.type:
            raise BindError(
                f"reduce combiner returns {comb.type}, state is {init.type}")
        out_l = lam_of(e.args[3], 1)
        sv2 = new_var(init.type)
        out_body = self._bind_lambda_body(
            out_l.body, {out_l.all_params[0]: sv2}, scope, agg)
        return Call(
            type=out_body.type, fn="reduce",
            args=(arr, init,
                  LambdaExpr(type=comb.type, params=(sv, xv), body=comb),
                  LambdaExpr(type=out_body.type, params=(sv2,),
                             body=out_body)))

    def _bind_grouping(self, e: ast.FuncCall, scope: Scope, agg: AggCtx) -> Expr:
        """grouping(a, b, ...) -> bitmask int: bit j (MSB-first) is 1
        when argument j is NOT aggregated in the current grouping set
        (sql/tree/GroupingOperation.java + the reference's rewrite to a
        $group_id lookup in QueryPlanner.planGroupingOperations)."""
        from presto_tpu.expr.ir import lit

        if agg.set_masks is None:
            raise BindError(
                "grouping() requires GROUPING SETS / ROLLUP / CUBE")
        idxs = []
        for a in e.args:
            hit = next((i for i, g in enumerate(agg.group_asts) if g == a), None)
            if hit is None:
                raise BindError(
                    f"grouping() argument {a!r} is not a grouping column")
            idxs.append(hit)
        k = len(idxs)
        vals = []
        for mask in agg.set_masks:
            v = 0
            for j, i in enumerate(idxs):
                if not mask[i]:
                    v |= 1 << (k - 1 - j)
            vals.append(v)
        gid_ref = agg.key_ref(len(agg.group_irs) - 1)  # $group_id key
        expr: Expr = lit(vals[-1], BIGINT)
        for g in range(len(vals) - 2, -1, -1):
            expr = call("if", call("eq", gid_ref, lit(g, BIGINT)),
                        lit(vals[g], BIGINT), expr)
        return expr

    def _bind_number(self, text: str) -> Literal:
        if "e" in text.lower():
            return Literal(type=DOUBLE, value=float(text))
        if "." in text:
            # exact digit parse (float round-trips lose precision past
            # 15-16 digits); > 18 digits becomes a long decimal
            whole, frac = text.split(".", 1)
            scale = len(frac)
            scaled = int((whole + frac) or "0")
            digits = len((whole + frac).lstrip("+-").lstrip("0")) or 1
            precision = max(digits, scale)
            if precision > 38:
                raise BindError(f"decimal literal exceeds 38 digits: {text}")
            if precision > 36:
                return Literal(type=DecimalType(38, scale), value=scaled)
            return Literal(type=DecimalType(36 if precision > 18 else 18, scale),
                           value=scaled)
        v = int(text)
        if not (-(1 << 63) <= v < (1 << 63)):
            # integer literal beyond int64: a decimal(<=38, 0) literal
            # (the reference types wide literals as decimals too)
            digits = len(text.lstrip("+-").lstrip("0")) or 1
            if digits > 38:
                raise BindError(f"decimal literal exceeds 38 digits: {text}")
            return Literal(type=DecimalType(38 if digits > 36 else 36, 0),
                           value=v)
        return Literal(type=BIGINT, value=v)

    def _bind_interval_arith(self, op: str, l_ir: Expr,
                             r_ir: Expr) -> Optional[Expr]:
        """Typed interval arithmetic (dispatch on BOUND types, so
        interval-valued sub-expressions work like literals):
        interval +- interval (same family), datetime +- day-second
        interval, datetime +- year-month interval.  Returns None when
        neither operand is interval-typed."""
        from presto_tpu.types import INTERVAL_DAY_SECOND

        IV = ("interval day to second", "interval year to month")
        lt, rt = l_ir.type.name, r_ir.type.name
        if lt not in IV and rt not in IV:
            return None
        if op in ("*", "/"):
            # interval scaled by a number (IntervalDayTimeOperators
            # multiplyBy*/dividedBy*: the product truncates to the unit
            # count like the reference's (long) cast)
            if op == "*" and lt not in IV and l_ir.type.is_numeric:
                iv, k = r_ir, l_ir
            elif rt not in IV and r_ir.type.is_numeric:
                iv, k = l_ir, r_ir
            else:
                raise BindError(
                    f"operator {op} undefined for these interval operands")
            from presto_tpu.types import DOUBLE as _DOUBLE

            exact = op == "*" and k.type.name in (
                "bigint", "integer", "smallint", "tinyint")
            if exact:
                return Call(type=iv.type, fn="mul", args=(iv, k))
            # fractional scale: compute in double, truncate like the
            # reference's (long) cast
            prod = Call(type=_DOUBLE, fn="mul" if op == "*" else "div",
                        args=(iv, k))
            return Call(type=iv.type, fn="cast_bigint", args=(prod,))
        if op not in ("+", "-"):
            raise BindError(f"operator {op} undefined for intervals")
        if lt in IV and rt in IV:
            if lt != rt:
                raise BindError(
                    "cannot mix day-second and year-month intervals")
            return Call(type=l_ir.type, fn="add" if op == "+" else "sub",
                        args=(l_ir, r_ir))
        iv, base = (l_ir, r_ir) if lt in IV else (r_ir, l_ir)
        if base.type.name not in ("timestamp", "date"):
            raise BindError(
                f"cannot apply interval to {base.type}")
        if op == "-" and lt in IV:
            raise BindError("interval - datetime unsupported")
        if op == "-":
            iv = Call(type=iv.type, fn="mul",
                      args=(iv, Literal(type=BIGINT, value=-1)))
        if iv.type == INTERVAL_DAY_SECOND:
            if base.type == DATE:
                base = call("cast_timestamp", base)
            return call("ts_add_micros", base, iv)
        if base.type == DATE:
            return call("date_add_months", base, iv)
        return call("ts_add_months", base, iv)

    def _bind_date_arith(self, e: ast.Binary, scope: Scope, agg) -> Expr:
        if isinstance(e.right, ast.IntervalLit):
            base_ast, iv = e.left, e.right
        else:
            if e.op == "-":
                raise BindError("interval - date unsupported")
            base_ast, iv = e.right, e.left
        # ONE literal parser serves the standalone-value and date-arith
        # paths (fractional seconds, 'Y-M', signed strings included)
        t_iv, v_iv = _interval_literal(iv)
        if e.op == "-":
            v_iv = -v_iv
        base = self._bind_impl(base_ast, scope, agg)
        from presto_tpu.types import INTERVAL_DAY_SECOND

        if t_iv == INTERVAL_DAY_SECOND:
            whole_days = v_iv % MICROS_PER_DAY == 0
            if isinstance(base, Literal) and base.type == DATE \
                    and base.value is not None:
                if whole_days:  # civil DATE shift stays a DATE
                    return Literal(type=DATE,
                                   value=base.value + v_iv // MICROS_PER_DAY)
                return Literal(type=TIMESTAMP,
                               value=base.value * MICROS_PER_DAY + v_iv)
            if isinstance(base, Literal) and base.type == TIMESTAMP \
                    and base.value is not None:
                return Literal(type=TIMESTAMP, value=base.value + v_iv)
            if base.type == TIMESTAMP:
                return call("ts_add_micros", base,
                            Literal(type=BIGINT, value=v_iv))
            if whole_days:
                return call("date_add_days", base,
                            Literal(type=BIGINT,
                                    value=v_iv // MICROS_PER_DAY))
            # sub-day interval promotes the date to a timestamp
            return call("ts_add_micros", call("cast_timestamp", base),
                        Literal(type=BIGINT, value=v_iv))
        months = v_iv
        if isinstance(base, Literal) and base.type == DATE \
                and base.value is not None:
            return Literal(type=DATE,
                           value=_shift_date(base.value, months, "month"))
        if isinstance(base, Literal) and base.type == TIMESTAMP \
                and base.value is not None:
            days = base.value // MICROS_PER_DAY
            tod = base.value - days * MICROS_PER_DAY
            return Literal(
                type=TIMESTAMP,
                value=_shift_date(days, months, "month") * MICROS_PER_DAY
                + tod)
        if base.type == TIMESTAMP:
            return call("ts_add_months", base,
                        Literal(type=BIGINT, value=months))
        return call("date_add_months", base,
                    Literal(type=BIGINT, value=months))

    def _bind_case(self, e: ast.Case, scope: Scope, agg) -> Expr:
        whens = []
        for cond, res in e.whens:
            if e.operand is not None:
                cond = ast.Binary("=", e.operand, cond)
            whens.append((self._bind_impl(cond, scope, agg), self._bind_impl(res, scope, agg)))
        args: List[Expr] = []
        for c, r in whens:
            args.extend([c, r])
        if e.else_ is not None:
            else_ir = self._bind_impl(e.else_, scope, agg)
        else:
            else_ir = Literal(type=whens[0][1].type, value=None)
        args.append(else_ir)
        return call("case", *args)

    def _register_window(self, e: ast.WindowExpr, scope: Scope, agg) -> ColumnRef:
        from presto_tpu.ops.window import WindowFunc

        if e in self._win_slots:
            slot = self._win_slots[e]
            return ColumnRef(type=self._windows[slot][1].type, index=_WIN_BASE + slot)

        fc = e.func
        name = fc.name
        if name not in WINDOW_FUNCTIONS:
            raise BindError(f"unknown window function {name}")
        kind = name
        arg = None
        offset = 1
        if name in ("row_number", "rank", "dense_rank", "percent_rank", "cume_dist"):
            if fc.args:
                raise BindError(f"{name} takes no arguments")
        elif name == "ntile":
            if len(fc.args) != 1:
                raise BindError("ntile takes one argument")
            n_ir = self._bind_impl(fc.args[0], scope, agg)
            if (not isinstance(n_ir, Literal) or n_ir.value is None
                    or int(n_ir.value) < 1):
                raise BindError("ntile bucket count must be a positive literal")
            offset = int(n_ir.value)
        elif name == "count" and (fc.star or not fc.args):
            kind = "count_star"
        else:
            if not fc.args:
                raise BindError(f"{name} requires an argument")
            arg = self._bind_impl(fc.args[0], scope, agg)
            if name in ("lead", "lag", "nth_value") and len(fc.args) > 1:
                off_ir = self._bind_impl(fc.args[1], scope, agg)
                if not isinstance(off_ir, Literal) or off_ir.value is None:
                    raise BindError(f"{name} offset must be a literal")
                offset = int(off_ir.value)
                if name == "nth_value" and offset < 1:
                    raise BindError("nth_value position must be >= 1")
                if offset < 0:
                    raise BindError(f"{name} offset must be non-negative")
        frame = self._bind_frame(e.frame, kind)
        if fc.ignore_nulls and kind not in (
                "lead", "lag", "first_value", "last_value", "nth_value"):
            raise BindError(
                "IGNORE NULLS applies to lead/lag/first_value/"
                "last_value/nth_value only")
        wf = WindowFunc(kind=kind, arg=arg, offset=offset, frame=frame,
                        ignore_nulls=fc.ignore_nulls)
        partition_irs = [self._bind_impl(p, scope, agg) for p in e.partition_by]
        order_irs = [self._bind_impl(o.expr, scope, agg) for o in e.order_by]
        ascending = [o.ascending for o in e.order_by]
        slot = len(self._windows)
        self._windows.append((e, wf, partition_irs, order_irs, ascending))
        self._win_slots[e] = slot
        return ColumnRef(type=wf.type, index=_WIN_BASE + slot)

    def _bind_frame(self, frame, kind: str):
        """AST frame -> WindowFunc.frame. RANGE frames support only the
        unbounded/current bounds (reference parity: 0.208 rejects RANGE
        with value offsets); ROWS frames become signed row offsets."""
        if frame is None:
            return None
        ft, (sk, sn), (ek, en) = frame
        if ft == "range":
            if sk != "unbounded_preceding":
                raise BindError("RANGE frame start must be UNBOUNDED PRECEDING")
            if ek == "current":
                return None  # the default frame
            if ek == "unbounded_following":
                return ("whole",)
            raise BindError("RANGE frame end must be CURRENT ROW or UNBOUNDED FOLLOWING")
        s_off = {"unbounded_preceding": None, "preceding": -sn, "current": 0,
                 "following": sn}.get(sk)
        e_off = {"unbounded_following": None, "preceding": -en, "current": 0,
                 "following": en}.get(ek)
        if sk == "unbounded_following" or ek == "unbounded_preceding":
            raise BindError("invalid ROWS frame bounds")
        if kind in ("min", "max") and s_off is not None:
            raise BindError(f"{kind} supports only UNBOUNDED PRECEDING frame starts")
        if (s_off, e_off) == (None, None):
            return ("whole",)
        return ("rows", s_off, e_off)

    def _attach_windows(self, node: PlanNode) -> Tuple[PlanNode, Dict[int, int]]:
        """Build WindowNode(s) above ``node``, grouping registered
        windows by identical (partition, order) spec; returns the node
        and the sentinel-slot -> real-channel mapping."""
        from presto_tpu.planner.plan import WindowNode

        specs: List[Tuple[tuple, List[int]]] = []  # (spec key, slots)
        for slot, (e, wf, p_irs, o_irs, asc) in enumerate(self._windows):
            key = (tuple(p_irs), tuple(o_irs), tuple(asc))
            for k, slots in specs:
                if k == key:
                    slots.append(slot)
                    break
            else:
                specs.append((key, [slot]))
        base = len(node.channels)
        mapping: Dict[int, int] = {}
        for key, slots in specs:
            p_irs, o_irs, asc = key
            funcs = [self._windows[s][1] for s in slots]
            names = [f"$win{s}" for s in slots]
            for j, s in enumerate(slots):
                mapping[s] = base + j
            node = WindowNode(
                source=node,
                partition_exprs=list(p_irs),
                order_exprs=list(o_irs),
                ascending=list(asc),
                funcs=funcs,
                func_names=names,
            )
            base += len(slots)
        return node, mapping

    def _patch_windows(self, e: Expr, mapping: Dict[int, int]) -> Expr:
        if isinstance(e, ColumnRef):
            if e.index >= _WIN_BASE:
                return ColumnRef(type=e.type, index=mapping[e.index - _WIN_BASE], name=e.name)
            return e
        if isinstance(e, Call):
            return Call(
                type=e.type, fn=e.fn,
                args=tuple(self._patch_windows(a, mapping) for a in e.args),
            )
        from presto_tpu.expr.ir import LambdaExpr

        if isinstance(e, LambdaExpr):
            return LambdaExpr(type=e.type, params=e.params,
                              body=self._patch_windows(e.body, mapping))
        return e

    def _bind_agg_call(self, e: ast.FuncCall, scope: Scope, agg: AggCtx) -> ColumnRef:
        if e.ignore_nulls:
            raise BindError(
                "IGNORE NULLS applies to window value functions "
                "(lead/lag/first_value/last_value/nth_value OVER)")
        from presto_tpu.ops.aggregate import output_type

        if e.star or (e.name == "count" and not e.args):
            a = AggCall(fn="count_star", arg=None, type=BIGINT)
            return agg.agg_ref(a)
        if e.name == "arbitrary":
            # any value per group: the max of the group qualifies
            # (ArbitraryAggregation semantics are "some input value")
            return self._bind_agg_call(
                ast.FuncCall("max", e.args, distinct=e.distinct), scope, agg)
        if e.name == "count_if":
            if len(e.args) != 1:
                raise BindError("count_if takes one argument")
            pred = self._bind(e.args[0], scope)
            a = AggCall(fn="count_star", arg=None, type=BIGINT, filter=pred)
            return agg.agg_ref(a)
        if e.name == "geometric_mean":
            inner = self._bind_agg_call(
                ast.FuncCall("avg", (ast.FuncCall("ln", e.args),)), scope, agg)
            return call("exp", inner)
        fn, distinct = e.name, e.distinct
        if fn == "approx_percentile" and len(e.args) == 2 \
                and isinstance(e.args[1], ast.ArrayCtor):
            # array-of-fractions form: one rank-select per fraction,
            # recomposed as ARRAY[..] (ApproximateLongPercentileArrayAggregations)
            refs = [self._bind_agg_call(
                        ast.FuncCall(fn, (e.args[0], p)), scope, agg)
                    for p in e.args[1].items]
            return call("array_construct", *refs)
        if fn == "approx_percentile" and len(e.args) == 3:
            # weighted form: approx_percentile(x, w, p)
            if distinct:
                raise BindError("approx_percentile DISTINCT unsupported")
            arg = self._bind(e.args[0], scope)
            w = self._bind(e.args[1], scope)
            p_ast = e.args[2]
            arg2 = self._bind(p_ast, scope)
            if not isinstance(arg2, Literal) or arg2.value is None:
                raise BindError("approx_percentile fraction must be a literal")
            p = float(arg2.value) / (10.0 ** (arg2.type.scale or 0)
                                     if arg2.type.is_decimal else 1.0)
            if not 0.0 <= p <= 1.0:
                raise BindError("approx_percentile fraction must be in [0, 1]")
            a = AggCall(fn=fn, arg=arg, type=arg.type,
                        arg2=Literal(type=DOUBLE, value=p), arg3=w)
            return agg.agg_ref(a)
        if fn == "numeric_histogram":
            # numeric_histogram(buckets, x): fixed-width bins over the
            # group's [min, max] span, keys = per-bin value means
            # (NumericHistogramAggregation's Ben-Haim/Tom-Tov role)
            if len(e.args) != 2:
                raise BindError("numeric_histogram takes (buckets, x)")
            b = self._bind(e.args[0], scope)
            if not isinstance(b, Literal) or not b.type.name == "bigint":
                raise BindError("numeric_histogram bucket count must be an "
                                "integer literal")
            from presto_tpu.ops.aggregate import ARRAY_AGG_CAP

            if not 1 <= int(b.value) <= ARRAY_AGG_CAP:
                raise BindError(
                    f"numeric_histogram bucket count must be in "
                    f"[1, {ARRAY_AGG_CAP}]")
            arg = self._bind(e.args[1], scope)
            a = AggCall(fn=fn, arg=arg, type=arg.type, arg2=b)
            a = dataclasses.replace(a, type=output_type(a))
            return agg.agg_ref(a)
        if fn in ("min", "max") and len(e.args) == 2:
            # max(x, n) / min(x, n): the n extreme values as an array,
            # descending for max, ascending for min
            # (Max/MinNAggregationFunction.java)
            if distinct:
                raise BindError(f"DISTINCT unsupported for {fn}(x, n)")
            arg = self._bind(e.args[0], scope)
            nn = self._bind(e.args[1], scope)
            self._check_topn_count(fn, nn)
            if not (arg.type.is_numeric or arg.type.name in
                    ("date", "timestamp", "time")) or arg.type.is_long_decimal:
                raise BindError(
                    f"{fn}(x, n) requires a fixed-width orderable x "
                    f"(got {arg.type})")
            a = AggCall(fn=f"{fn}_n", arg=arg, type=arg.type, arg2=nn)
            a = dataclasses.replace(a, type=output_type(a))
            return agg.agg_ref(a)
        if fn in ("min_by", "max_by") and len(e.args) == 3:
            # max_by(x, y, n) / min_by(x, y, n): the x values paired
            # with the n extreme y keys (Max/MinByNAggregationFunction)
            if distinct:
                raise BindError(f"DISTINCT unsupported for {fn}(x, y, n)")
            arg = self._bind(e.args[0], scope)
            key = self._bind(e.args[1], scope)
            nn = self._bind(e.args[2], scope)
            self._check_topn_count(fn, nn)
            for name, t in (("x", arg.type), ("y", key.type)):
                if not (t.is_numeric or t.name in
                        ("date", "timestamp", "time")) or t.is_long_decimal:
                    raise BindError(
                        f"{fn}(x, y, n) requires fixed-width orderable "
                        f"arguments (got {t} for {name})")
            a = AggCall(fn=f"{fn}_n", arg=arg, type=arg.type, arg2=key,
                        arg3=nn)
            a = dataclasses.replace(a, type=output_type(a))
            return agg.agg_ref(a)
        if fn == "map_union":
            if len(e.args) != 1:
                raise BindError("map_union takes one argument")
            if distinct:
                raise BindError("DISTINCT unsupported for map_union")
            arg = self._bind(e.args[0], scope)
            # strictly scalar-valued maps: is_map also admits HLL
            # sketches (union those via merge()) and multimap results,
            # whose array-valued lanes this kernel cannot slice
            if arg.type.name != "map" or (
                    arg.type.element is not None and arg.type.element.is_array):
                raise BindError(
                    f"map_union requires a scalar-valued map argument "
                    f"(got {arg.type})")
            a = AggCall(fn=fn, arg=arg, type=arg.type)
            a = dataclasses.replace(a, type=output_type(a))
            return agg.agg_ref(a)
        if fn in ("learn_libsvm_regressor", "learn_libsvm_classifier"):
            # libsvm-parameterized variants (presto-ml
            # LearnLibSvm*Aggregation): the params string configures a
            # libsvm trainer there; the trainers here are the
            # closed-form TPU redesigns (normal equations / Gaussian
            # NB), so the params argument is accepted and ignored
            if len(e.args) == 3:
                e = dataclasses.replace(e, args=e.args[:2])
            fn = fn.replace("_libsvm", "")
        if fn in ("min_by", "max_by", "approx_percentile", "map_agg",
                  "multimap_agg",
                  "covar_pop", "covar_samp", "corr", "regr_slope",
                  "regr_intercept",
                  "learn_regressor", "learn_classifier",
                  "evaluate_classifier_predictions"):
            if len(e.args) != 2:
                raise BindError(f"aggregate {fn} takes two arguments")
            if distinct:
                raise BindError(f"DISTINCT unsupported for {fn}")
            arg = self._bind(e.args[0], scope)
            arg2 = self._bind(e.args[1], scope)
            if fn == "approx_percentile":
                if not isinstance(arg2, Literal) or arg2.value is None:
                    raise BindError("approx_percentile fraction must be a literal")
                p = float(arg2.value) / (10.0 ** (arg2.type.scale or 0)
                                         if arg2.type.is_decimal else 1.0)
                if not 0.0 <= p <= 1.0:
                    raise BindError("approx_percentile fraction must be in [0, 1]")
                arg2 = Literal(type=DOUBLE, value=p)
            a = AggCall(fn=fn, arg=arg, type=arg.type, distinct=distinct, arg2=arg2)
            a = dataclasses.replace(a, type=output_type(a))
            return agg.agg_ref(a)
        if len(e.args) != 1:
            raise BindError(f"aggregate {e.name} takes one argument")
        arg = self._bind(e.args[0], scope)
        a = AggCall(fn=fn, arg=arg, type=arg.type, distinct=distinct)
        a = AggCall(fn=a.fn, arg=a.arg, type=output_type(a), distinct=a.distinct)
        return agg.agg_ref(a)

    @staticmethod
    def _fold_literal_call(fn, args):
        """Constant-fold scalar calls whose column forms run through
        dictionary LUTs — with literal arguments there is no dictionary
        to transform, so the value computes at bind time (the
        reference's constant folding in ExpressionInterpreter.java)."""
        from presto_tpu.expr.compile import (
            STRING_TRANSFORM_FNS, _levenshtein, _string_transform,
            iso_date_days, mysql_datetime_micros, xxh64_signed,
        )

        def lit_val(a):
            return a.value if isinstance(a, Literal) else None

        if fn in ("crc32", "xxhash64") and len(args) == 1 \
                and isinstance(args[0], Call) and args[0].fn == "to_utf8" \
                and isinstance(args[0].args[0], Literal):
            s = args[0].args[0].value
            if s is None:
                return Literal(type=BIGINT, value=None)
            import zlib

            if fn == "crc32":
                return Literal(type=BIGINT, value=zlib.crc32(s.encode()))
            return Literal(type=BIGINT, value=xxh64_signed(s.encode()))
        if not args or not all(isinstance(a, Literal) for a in args):
            return None
        v0 = lit_val(args[0])
        _null_out = {"from_base": BIGINT, "levenshtein_distance": BIGINT,
                     "hamming_distance": BIGINT, "date_parse": TIMESTAMP,
                     "from_iso8601_date": DATE, "json_size": BIGINT}
        if fn in _null_out and any(a.value is None for a in args):
            # NULL in ANY argument is NULL out (reference convention)
            return Literal(type=_null_out[fn], value=None)
        if fn in STRING_TRANSFORM_FNS and isinstance(v0, (str, type(None))) \
                and args[0].type.is_string:
            if any(a.value is None for a in args):
                return Literal(type=VARCHAR, value=None)
            tf = _string_transform(Call(type=args[0].type, fn=fn,
                                        args=tuple(args)))
            if tf is None:
                return None
            f, _ = tf
            out = None if v0 is None else f(v0)
            return Literal(type=VARCHAR, value=out)
        if v0 is None:
            return None
        if fn == "json_size":
            from presto_tpu.expr.compile import _json_path_lookup

            found, got = _json_path_lookup(v0, args[1].value)
            if not found:
                return Literal(type=BIGINT, value=None)
            return Literal(
                type=BIGINT,
                value=len(got) if isinstance(got, (dict, list)) else 0)
        if fn == "from_base":
            try:
                return Literal(type=BIGINT,
                               value=int(v0, int(args[1].value)))
            except ValueError as ex:
                raise BindError(f"from_base: {ex}")
        if fn == "date_parse":
            return Literal(type=TIMESTAMP,
                           value=mysql_datetime_micros(v0, args[1].value))
        if fn == "from_iso8601_date":
            return Literal(type=DATE, value=iso_date_days(v0))
        if fn == "levenshtein_distance":
            return Literal(type=BIGINT,
                           value=_levenshtein(v0, args[1].value))
        if fn == "hamming_distance":
            b = args[1].value
            if len(v0) != len(b):
                # deviation (documented): NULL where the reference
                # raises — matches the column LUT path
                return Literal(type=BIGINT, value=None)
            return Literal(type=BIGINT,
                           value=sum(x != y for x, y in zip(v0, b)))
        return None

    @staticmethod
    def _check_topn_count(fn, nn):
        """n of max/min(x, n) and max_by/min_by(x, y, n) must be a
        positive integer literal within the container cap."""
        from presto_tpu.ops.aggregate import ARRAY_AGG_CAP

        if not isinstance(nn, Literal) or nn.value is None \
                or not nn.type.is_integerlike:
            raise BindError(f"{fn}'s n must be an integer literal")
        if not 1 <= int(nn.value) <= ARRAY_AGG_CAP:
            raise BindError(f"{fn}'s n must be in [1, {ARRAY_AGG_CAP}]")

    # ------------------------------------------------------------------
    def _substitute_aliases(self, e: ast.Node, alias_map: Dict[str, ast.Node],
                            scope) -> ast.Node:
        """Replace bare identifiers that name a select alias (and do NOT
        resolve as real columns — columns win) with the aliased
        expression; descends expressions but not subquery bodies."""
        if isinstance(e, ast.Identifier) and e.qualifier is None \
                and e.name in alias_map:
            try:
                scope.resolve(None, e.name)
                return e  # a real column shadows the alias
            except Exception:
                return alias_map[e.name]
        if isinstance(e, (ast.Query, ast.Union, ast.ScalarSubquery,
                          ast.Exists, ast.InSubquery)):
            return e
        if dataclasses.is_dataclass(e) and isinstance(e, ast.Node):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                nv = self._sub_alias_value(v, alias_map, scope)
                if nv is not v:
                    changes[f.name] = nv
            return dataclasses.replace(e, **changes) if changes else e
        return e

    def _sub_alias_value(self, v, alias_map, scope):
        if isinstance(v, ast.Node):
            return self._substitute_aliases(v, alias_map, scope)
        if isinstance(v, tuple):
            out = tuple(self._sub_alias_value(x, alias_map, scope) for x in v)
            return out if any(a is not b for a, b in zip(out, v)) else v
        return v

    def _bind_order(self, order_items, items, out_irs, scope) -> List[Expr]:
        order_irs: List[Expr] = []
        for o in order_items:
            e = o.expr
            if isinstance(e, ast.NumberLit):
                order_irs.append(out_irs[int(e.text) - 1])
                continue
            hit = next(
                (out_irs[i] for i, (se, n) in enumerate(items)
                 if (isinstance(e, ast.Identifier) and e.qualifier is None and e.name == n) or se == e),
                None,
            )
            if hit is not None:
                order_irs.append(hit)
            else:
                order_irs.append(self._bind(e, scope))
        return order_irs


def _is_row_ast(e: ast.Node) -> bool:
    """Row-constructor syntax: (a, b) or row(a, b)."""
    return isinstance(e, ast.RowCtor) or (
        isinstance(e, ast.FuncCall) and e.name == "row" and bool(e.args))


def _row_items(e: ast.Node):
    return e.items if isinstance(e, ast.RowCtor) else e.args


def _row_comparison(left: ast.Node, right: ast.Node, op: str) -> ast.Node:
    """(a, b) = (c, d) -> a = c AND b = d; <> negates the conjunction.
    Accepts both the (a, b) and row(a, b) constructor forms."""
    if not (_is_row_ast(left) and _is_row_ast(right)):
        raise BindError("row comparison needs row constructors on both sides")
    left = ast.RowCtor(tuple(_row_items(left)))
    right = ast.RowCtor(tuple(_row_items(right)))
    if len(left.items) != len(right.items):
        raise BindError(
            f"row arity mismatch: {len(left.items)} vs {len(right.items)}")
    conj = None
    for l, r in zip(left.items, right.items):
        eq = ast.Binary("=", l, r)
        conj = eq if conj is None else ast.Binary("and", conj, eq)
    return ast.Unary("not", conj) if op == "<>" else conj


def term_of_ref(terms: List[Term], ref: int) -> int:
    for i, t in enumerate(terms):
        if t.offset <= ref < t.offset + len(t.scope):
            return i
    raise BindError(
        f"internal: channel reference ${ref} falls outside every join "
        "term's scope (binder channel-offset bug)")
