"""SQL abstract syntax tree.

Reference analog: ``presto-parser/src/main/java/com/facebook/presto/sql/tree/``
(155 node classes — Query.java, QuerySpecification.java, Select.java,
ComparisonExpression.java, FunctionCall.java, ...).  Collapsed to the
node set the TPU engine's dialect needs; growth model is the same
(one dataclass per syntactic form).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# -- expressions -------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Identifier(Node):
    parts: Tuple[str, ...]  # possibly qualified: ("l", "shipdate") or ("revenue",)
    # character offset in the statement text (NodeLocation analog);
    # excluded from eq/hash so GROUP BY / select-item matching still
    # compares structurally
    pos: Optional[int] = dataclasses.field(default=None, compare=False)

    @property
    def name(self) -> str:
        return self.parts[-1]

    @property
    def qualifier(self) -> Optional[str]:
        return self.parts[0] if len(self.parts) > 1 else None


@dataclasses.dataclass(frozen=True)
class NumberLit(Node):
    text: str  # raw literal; binder decides bigint vs decimal vs double


@dataclasses.dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclasses.dataclass(frozen=True)
class DateLit(Node):
    value: str  # 'YYYY-MM-DD'


@dataclasses.dataclass(frozen=True)
class TimestampLit(Node):
    value: str  # 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]'


@dataclasses.dataclass(frozen=True)
class TimeLit(Node):
    value: str  # 'HH:MM:SS[.ffffff]'


@dataclasses.dataclass(frozen=True)
class IntervalLit(Node):
    value: str  # e.g. '3'
    unit: str  # second | minute | hour | day | month | year
    negative: bool = False


@dataclasses.dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Unary(Node):
    op: str  # '-' | 'not'
    operand: Node


@dataclasses.dataclass(frozen=True)
class Binary(Node):
    op: str  # + - * / % = <> < <= > >= and or
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Node):
    value: Node
    items: Tuple[Node, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class QuantifiedComparison(Node):
    """value op ANY|SOME|ALL (subquery)
    (sql/tree/QuantifiedComparisonExpression.java)."""

    op: str  # = <> < <= > >=
    value: Node = None
    quantifier: str = "any"  # any | all
    query: "Query" = None


@dataclasses.dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Like(Node):
    value: Node
    pattern: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull(Node):
    value: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ArrayCtor(Node):
    """ARRAY[e1, e2, ...] literal (sql/tree/ArrayConstructor.java)."""

    items: Tuple[Node, ...] = ()


@dataclasses.dataclass(frozen=True)
class Subscript(Node):
    """base[index] (sql/tree/SubscriptExpression.java)."""

    base: Node = None
    index: Node = None


@dataclasses.dataclass(frozen=True)
class FieldAccess(Node):
    """ROW field access: expr.name (sql/tree/DereferenceExpression.java
    when the base is row-typed)."""

    base: "Node" = None
    field: str = ""


@dataclasses.dataclass(frozen=True)
class RowCtor(Node):
    """(e1, e2, ...) row constructor (sql/tree/Row.java) — desugars to
    pairwise comparisons in =/<>/IN contexts."""

    items: Tuple[Node, ...] = ()


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    name: str = ""
    query: Node = None


@dataclasses.dataclass(frozen=True)
class Execute(Node):
    name: str = ""
    params: Tuple[Node, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    name: str = ""


@dataclasses.dataclass(frozen=True)
class Parameter(Node):
    """A ? placeholder (sql/tree/Parameter.java)."""

    index: int = 0


@dataclasses.dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowFunctions(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Describe(Node):
    table: str = ""


@dataclasses.dataclass(frozen=True)
class Lambda(Node):
    """param -> body / (p1, p2, ...) -> body
    (sql/tree/LambdaExpression.java).  ``params`` is the canonical
    parameter tuple; ``param`` mirrors params[0] for the single-
    parameter array-function surface."""

    param: str = ""
    body: Node = None
    params: tuple = ()

    @property
    def all_params(self) -> tuple:
        return self.params if self.params else (self.param,)


@dataclasses.dataclass(frozen=True)
class Case(Node):
    whens: Tuple[Tuple[Node, Node], ...]  # (condition, result)
    else_: Optional[Node]
    operand: Optional[Node] = None  # simple CASE x WHEN v THEN ...


@dataclasses.dataclass(frozen=True)
class Cast(Node):
    value: Node
    type_name: str


@dataclasses.dataclass(frozen=True)
class Extract(Node):
    field: str  # year | quarter | month | week | day | hour | minute | second | ...
    value: Node


@dataclasses.dataclass(frozen=True)
class Substring(Node):
    value: Node
    start: Node
    length: Optional[Node]


@dataclasses.dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: Tuple[Node, ...]
    distinct: bool = False
    star: bool = False  # count(*)
    ignore_nulls: bool = False  # lead/lag/first/last/nth IGNORE NULLS
    # character offset in the statement text (NodeLocation analog)
    pos: Optional[int] = dataclasses.field(default=None, compare=False)


@dataclasses.dataclass(frozen=True)
class WindowExpr(Node):
    """fn(...) OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE frame]).

    frame: None for the default, else (type, start, end) with type
    'rows'|'range' and each bound a (kind, n) pair, kind in
    unbounded_preceding | preceding | current | following |
    unbounded_following."""

    func: "FuncCall"
    partition_by: Tuple[Node, ...] = ()
    order_by: Tuple["OrderItem", ...] = ()
    frame: Optional[Tuple[str, Tuple[str, int], Tuple[str, int]]] = None


@dataclasses.dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None


# -- grouping-set group-by items ---------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rollup(Node):
    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Cube(Node):
    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class GroupingSets(Node):
    sets: Tuple[Tuple[Node, ...], ...]


# -- relations ---------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TableRef(Node):
    name: str
    alias: Optional[str] = None
    # TABLESAMPLE (method, percentage): ("bernoulli"|"system", pct)
    sample: Optional[Tuple[str, float]] = None


@dataclasses.dataclass(frozen=True)
class Grant(Node):
    """GRANT privs ON [TABLE] t TO u (sql/tree/Grant.java)."""

    privileges: Tuple[str, ...] = ()
    table: str = ""
    grantee: str = ""


@dataclasses.dataclass(frozen=True)
class Revoke(Node):
    privileges: Tuple[str, ...] = ()
    table: str = ""
    grantee: str = ""


@dataclasses.dataclass(frozen=True)
class AlterTableRename(Node):
    """ALTER TABLE t RENAME TO u (sql/tree/RenameTable.java)."""

    name: str = ""
    new_name: str = ""


@dataclasses.dataclass(frozen=True)
class SubqueryRel(Node):
    query: "Query"
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class JoinRel(Node):
    left: Node
    right: Node
    kind: str  # inner | left | cross
    on: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class Unnest(Node):
    """UNNEST(arr [, arr2...]) [WITH ORDINALITY] [AS alias (col, ...)]
    — lateral relation over columns of the preceding FROM terms
    (reference: sql/tree/Unnest.java + operator/UnnestOperator.java:35)."""

    args: Tuple[Node, ...] = ()
    ordinality: bool = False
    alias: Optional[str] = None
    column_names: Tuple[str, ...] = ()


# -- query -------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Node  # or Star
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class Explain(Node):
    query: "Query"
    analyze: bool = False
    distributed: bool = False  # EXPLAIN (TYPE DISTRIBUTED)
    # EXPLAIN ANALYZE VERBOSE: exclusive per-operator times by
    # re-running chain prefixes (fusion deliberately broken)
    verbose: bool = False
    # EXPLAIN (TYPE VALIDATE): parse+bind only, one boolean column
    validate: bool = False


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: str


@dataclasses.dataclass(frozen=True)
class DescribeOutput(Node):
    """DESCRIBE OUTPUT name (sql/tree/DescribeOutput.java)."""

    name: str = ""


@dataclasses.dataclass(frozen=True)
class DescribeInput(Node):
    """DESCRIBE INPUT name (sql/tree/DescribeInput.java)."""

    name: str = ""


@dataclasses.dataclass(frozen=True)
class ResetSession(Node):
    """RESET SESSION name (sql/tree/ResetSession.java)."""

    name: str = ""


@dataclasses.dataclass(frozen=True)
class ShowCreateTable(Node):
    """SHOW CREATE TABLE t (sql/tree/ShowCreate.java)."""

    table: str = ""


@dataclasses.dataclass(frozen=True)
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW v AS query
    (sql/tree/CreateView.java + execution/CreateViewTask.java:44).
    ``sql`` keeps the original query text: views are stored as SQL and
    re-bound at reference time (analyzer/StatementAnalyzer.java:789)."""

    name: str = ""
    sql: str = ""
    replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropView(Node):
    """DROP VIEW [IF EXISTS] v (sql/tree/DropView.java)."""

    name: str = ""
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Use(Node):
    """USE [catalog.]schema (sql/tree/Use.java +
    execution/UseTask.java:33)."""

    catalog: Optional[str] = None
    schema: str = ""


@dataclasses.dataclass(frozen=True)
class CreateSchema(Node):
    """CREATE SCHEMA [IF NOT EXISTS] [catalog.]name
    (execution/CreateSchemaTask.java:38)."""

    catalog: Optional[str] = None
    name: str = ""
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class DropSchema(Node):
    """DROP SCHEMA [IF EXISTS] [catalog.]name [RESTRICT|CASCADE]
    (execution/DropSchemaTask.java)."""

    catalog: Optional[str] = None
    name: str = ""
    if_exists: bool = False
    cascade: bool = False


@dataclasses.dataclass(frozen=True)
class RenameSchema(Node):
    """ALTER SCHEMA [catalog.]a RENAME TO b
    (execution/RenameSchemaTask.java)."""

    catalog: Optional[str] = None
    name: str = ""
    new_name: str = ""


@dataclasses.dataclass(frozen=True)
class AddColumn(Node):
    """ALTER TABLE t ADD COLUMN c type (execution/AddColumnTask.java)."""

    table: str = ""
    column: str = ""
    type_name: str = ""


@dataclasses.dataclass(frozen=True)
class DropColumn(Node):
    """ALTER TABLE t DROP COLUMN c (execution/DropColumnTask.java)."""

    table: str = ""
    column: str = ""


@dataclasses.dataclass(frozen=True)
class Call(Node):
    """CALL proc(arg, ...) (sql/tree/Call.java +
    execution/CallTask.java:60; args are literal expressions)."""

    name: str = ""
    args: Tuple["Node", ...] = ()


@dataclasses.dataclass(frozen=True)
class ShowPartitions(Node):
    """SHOW PARTITIONS FROM t (SqlBase.g4:89; the reference routes it
    to a partitions$ system table — a direct listing here)."""

    table: str = ""


@dataclasses.dataclass(frozen=True)
class SetPath(Node):
    """SET PATH spec (SqlBase.g4:98 + sql/tree/SetPath.java): the SQL
    function-resolution path; recorded on the session."""

    path: str = ""


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Node):
    """SHOW SCHEMAS [FROM catalog] (sql/tree/ShowSchemas.java)."""

    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShowStats(Node):
    """SHOW STATS FOR t (sql/tree/ShowStats.java)."""

    table: str = ""


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: str = ""


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclasses.dataclass(frozen=True)
class CreateTableAs(Node):
    name: str
    query: Node  # Query | Union
    # WITH (k = v, ...) table properties (e.g. partitioned_by)
    properties: tuple = ()


@dataclasses.dataclass(frozen=True)
class InsertInto(Node):
    name: str
    query: Node


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class StartTransaction(Node):
    """START TRANSACTION [READ ONLY] (sql/tree/StartTransaction.java)."""

    read_only: bool = False


@dataclasses.dataclass(frozen=True)
class Commit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Node):
    pass


@dataclasses.dataclass(frozen=True)
class With(Node):
    """WITH name AS (query), ... body (sql/tree/With.java + WithQuery;
    CTEs expand by inline substitution at planning, like the
    reference's pre-iterative expansion)."""

    ctes: Tuple[Tuple[str, "Node"], ...] = ()
    body: "Node" = None


@dataclasses.dataclass(frozen=True)
class ValuesRel(Node):
    """VALUES (r1...), (r2...) as a relation (sql/tree/Values.java)."""

    rows: Tuple[Tuple["Node", ...], ...] = ()
    alias: Optional[str] = None
    column_names: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM t [WHERE pred] (sql/tree/Delete.java;
    operator/DeleteOperator.java)."""

    table: str = ""
    where: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class Union(Node):
    left: Node  # Query or Union
    right: Node
    distinct: bool = False
    order_by: Tuple["OrderItem", ...] = ()
    limit: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class SetOp(Node):
    """INTERSECT / EXCEPT (sql/tree/Intersect.java, Except.java) —
    DISTINCT semantics (the reference's ALL variants are unsupported
    there too at 0.208 for except/intersect hash planning)."""

    kind: str = "intersect"  # intersect | except
    left: Node = None
    right: Node = None
    order_by: Tuple["OrderItem", ...] = ()
    limit: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Query(Node):
    select: Tuple[SelectItem, ...]
    distinct: bool = False
    from_: Tuple[Node, ...] = ()  # relations (comma list, possibly JoinRel trees)
    where: Optional[Node] = None
    group_by: Tuple[Node, ...] = ()
    having: Optional[Node] = None
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
