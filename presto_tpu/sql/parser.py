"""SQL lexer + recursive-descent parser.

Reference analog: ``presto-parser`` — the ANTLR4 grammar
``SqlBase.g4`` (765 lines) with ``AstBuilder.java`` lowering parse
trees to AST.  Re-done as a hand-rolled recursive-descent parser over
the dialect subset the engine executes (SELECT queries: joins,
subqueries, aggregates, CASE/CAST/EXTRACT, date/interval literals);
precedence mirrors the grammar's ``booleanExpression``/
``valueExpression`` ladder.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

from presto_tpu.sql import ast

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<number>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|\|\||->|[,().;+\-*/%<>=\[\]?])
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order",
    "limit", "as", "and", "or", "not", "in", "like", "between", "is", "null",
    "case", "when", "then", "else", "end", "cast", "extract", "exists",
    "join", "inner", "left", "right", "full", "outer", "cross", "on", "asc", "desc",
    "date", "timestamp", "interval", "year", "month", "day", "true", "false", "substring",
    "for", "nulls", "first", "last", "all", "any", "union",
    "over", "partition",
    "explain", "analyze", "set", "session", "show", "tables", "columns",
    "create", "table", "insert", "into", "drop",
}


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: str, pos: int):
        self.kind = kind  # number | string | ident | keyword | op | eof
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SyntaxError(f"cannot tokenize at {sql[i:i+20]!r}")
        i = m.end()
        if m.lastgroup == "ws":
            continue
        kind = m.lastgroup
        val = m.group()
        if kind == "ident" and val.lower() in KEYWORDS:
            kind, val = "keyword", val.lower()
        elif kind == "string":
            val = val[1:-1].replace("''", "'")
        out.append(Token(kind, val, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


# non-reserved words that end an expression/relation rather than alias it
_NON_ALIAS_WORDS = {"intersect", "except", "tablesample"}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql  # original text (views store their query verbatim)
        self.tokens = tokenize(sql)
        self.i = 0
        self.n_params = 0  # ? placeholders seen (PREPARE/EXECUTE)

    # -- token helpers -----------------------------------------------------
    @property
    def tok(self) -> Token:
        return self.tokens[self.i]

    def peek(self, *vals: str) -> bool:
        t = self.tok
        return (t.kind in ("keyword", "op")) and t.value in vals

    def peek2(self, val: str) -> bool:
        t = self.tokens[self.i + 1]
        return t.kind in ("keyword", "op") and t.value == val

    def accept(self, *vals: str) -> Optional[str]:
        if self.peek(*vals):
            v = self.tok.value
            self.i += 1
            return v
        return None

    def expect(self, val: str) -> None:
        if not self.accept(val):
            raise SyntaxError(f"expected {val!r}, got {self.tok!r}")

    def expect_word(self, val: str) -> None:
        if not self.accept_word(val):
            raise SyntaxError(f"expected {val!r}, got {self.tok!r}")

    def accept_word(self, *vals: str) -> Optional[str]:
        """Accept a keyword OR bare identifier matching one of ``vals``
        (case-insensitive) — for non-reserved words like interval units."""
        t = self.tok
        if t.kind in ("keyword", "ident") and t.value.lower() in vals:
            self.i += 1
            return t.value.lower()
        return None

    def _implicit_alias(self) -> Optional[str]:
        """Consume a bare identifier as an alias unless it is a
        non-reserved clause word (INTERSECT/EXCEPT)."""
        t = self.tok
        if t.kind == "ident" and t.value.lower() not in _NON_ALIAS_WORDS:
            self.i += 1
            return t.value
        return None

    def ident(self) -> str:
        t = self.tok
        if t.kind == "ident":
            self.i += 1
            return t.value
        # non-reserved keywords usable as identifiers
        if t.kind == "keyword" and t.value in ("year", "month", "day", "date", "timestamp", "first", "last"):
            self.i += 1
            return t.value
        raise SyntaxError(f"expected identifier, got {t!r}")

    # -- entry -------------------------------------------------------------
    def parse_query(self) -> ast.Query:
        q = self._query()
        self.accept(";")
        if self.tok.kind != "eof":
            raise SyntaxError(f"trailing input at {self.tok!r}")
        return q

    def _query(self) -> ast.Node:
        """query := [WITH ctes] select_query
        (UNION [ALL|DISTINCT] select_query)* with ORDER BY/LIMIT
        binding to the union result."""
        if self.tok.kind == "ident" and self.tok.value.lower() == "with" \
                and self.tokens[self.i + 1].kind == "ident":
            self.i += 1
            ctes = []
            while True:
                name = self.ident()
                self.expect("as")
                self.expect("(")
                sub = self._query()
                self.expect(")")
                ctes.append((name, sub))
                if not self.accept(","):
                    break
            body = self._query()
            return ast.With(tuple(ctes), body)
        q = self._set_term()
        while self.accept("union"):
            all_ = bool(self.accept("all"))
            if not all_:
                self.accept("distinct")
            distinct = not all_
            right = self._set_term()
            right, order_by, limit = _hoist_order_limit(right)
            q = ast.Union(left=q, right=right, distinct=distinct,
                          order_by=order_by, limit=limit)
        return q

    def _set_term(self) -> ast.Node:
        """INTERSECT/EXCEPT bind tighter than UNION (standard
        precedence; SqlBase.g4 queryTerm ladder)."""
        q = self._select_query()
        while True:
            kind = self.accept_word("intersect", "except")
            if kind is None:
                return q
            self.accept("distinct")
            if self.accept("all"):
                raise SyntaxError(f"{kind.upper()} ALL unsupported")
            right = self._select_query()
            right, order_by, limit = _hoist_order_limit(right)
            q = ast.SetOp(kind=kind, left=q, right=right,
                          order_by=order_by, limit=limit)

    def _select_query(self) -> ast.Query:
        if self.tok.kind == "ident" and self.tok.value.lower() == "values":
            # VALUES as a query term (SqlBase.g4:89 queryPrimary):
            # planned as SELECT * over the VALUES relation
            rel = self._relation_primary()
            order_by: Tuple[ast.OrderItem, ...] = ()
            if self.accept("order"):
                self.expect("by")
                o = [self._order_item()]
                while self.accept(","):
                    o.append(self._order_item())
                order_by = tuple(o)
            limit = None
            if self.accept("limit"):
                t = self.tok
                if t.kind != "number":
                    raise SyntaxError(f"expected number after LIMIT, got {t!r}")
                self.i += 1
                limit = int(t.value)
            return ast.Query(select=(ast.SelectItem(ast.Star(None)),),
                             from_=(rel,), order_by=order_by, limit=limit)
        self.expect("select")
        distinct = bool(self.accept("distinct"))
        self.accept("all")
        items = [self._select_item()]
        while self.accept(","):
            items.append(self._select_item())

        from_: Tuple[ast.Node, ...] = ()
        if self.accept("from"):
            rels = [self._relation()]
            while self.accept(","):
                rels.append(self._relation())
            from_ = tuple(rels)

        where = self._expr() if self.accept("where") else None

        group_by: Tuple[ast.Node, ...] = ()
        if self.accept("group"):
            self.expect("by")
            g = [self._group_item()]
            while self.accept(","):
                g.append(self._group_item())
            group_by = tuple(g)

        having = self._expr() if self.accept("having") else None

        order_by: Tuple[ast.OrderItem, ...] = ()
        if self.accept("order"):
            self.expect("by")
            o = [self._order_item()]
            while self.accept(","):
                o.append(self._order_item())
            order_by = tuple(o)

        limit = None
        if self.accept("limit"):
            t = self.tok
            if t.kind != "number":
                raise SyntaxError(f"expected number after LIMIT, got {t!r}")
            self.i += 1
            limit = int(t.value)

        return ast.Query(
            select=tuple(items), distinct=distinct, from_=from_, where=where,
            group_by=group_by, having=having, order_by=order_by, limit=limit,
        )

    def _frame_bound(self) -> Tuple[str, int]:
        if self.accept_word("unbounded"):
            w = self.accept_word("preceding", "following")
            if w is None:
                raise SyntaxError("expected PRECEDING/FOLLOWING after UNBOUNDED")
            return (f"unbounded_{w}", 0)
        if self.accept_word("current"):
            if self.accept_word("row") is None:
                raise SyntaxError("expected ROW after CURRENT")
            return ("current", 0)
        t = self.tok
        if t.kind != "number":
            raise SyntaxError(f"expected frame bound, got {t!r}")
        self.i += 1
        w = self.accept_word("preceding", "following")
        if w is None:
            raise SyntaxError("expected PRECEDING/FOLLOWING after frame offset")
        return (w, int(t.value))

    def _group_item(self) -> ast.Node:
        """GROUP BY item: expr | ROLLUP(...) | CUBE(...) |
        GROUPING SETS ((a, b), (a), ())."""
        t = self.tok
        if t.kind == "ident" and t.value.lower() in ("rollup", "cube") and self.peek2("("):
            name = t.value.lower()
            self.i += 1
            self.expect("(")
            items = [self._expr()]
            while self.accept(","):
                items.append(self._expr())
            self.expect(")")
            return ast.Rollup(tuple(items)) if name == "rollup" else ast.Cube(tuple(items))
        nxt = self.tokens[self.i + 1]
        if (t.kind == "ident" and t.value.lower() == "grouping"
                and nxt.kind == "ident" and nxt.value.lower() == "sets"):
            self.i += 2
            self.expect("(")
            sets = []
            while True:
                if self.accept("("):
                    s: List[ast.Node] = []
                    if not self.peek(")"):
                        s.append(self._expr())
                        while self.accept(","):
                            s.append(self._expr())
                    self.expect(")")
                    sets.append(tuple(s))
                else:
                    sets.append((self._expr(),))
                if not self.accept(","):
                    break
            self.expect(")")
            return ast.GroupingSets(tuple(sets))
        return self._expr()

    def _select_item(self) -> ast.SelectItem:
        if self.peek("*"):
            self.i += 1
            return ast.SelectItem(ast.Star())
        # qualified star: ident.*
        t = self.tok
        if t.kind == "ident" and self.peek2(".") and self.tokens[self.i + 2].value == "*":
            self.i += 3
            return ast.SelectItem(ast.Star(qualifier=t.value))
        e = self._expr()
        alias = None
        if self.accept("as"):
            alias = self.ident()
        else:
            alias = self._implicit_alias()
        return ast.SelectItem(e, alias)

    def _order_item(self) -> ast.OrderItem:
        e = self._expr()
        asc = True
        if self.accept("desc"):
            asc = False
        else:
            self.accept("asc")
        nulls_first = None
        if self.accept("nulls"):
            if self.accept("first"):
                nulls_first = True
            else:
                self.expect("last")
                nulls_first = False
        return ast.OrderItem(e, asc, nulls_first)

    # -- relations ---------------------------------------------------------
    def _relation(self) -> ast.Node:
        rel = self._relation_primary()
        while True:
            if self.accept("cross"):
                self.expect("join")
                right = self._relation_primary()
                rel = ast.JoinRel(rel, right, "cross")
                continue
            kind = None
            if self.peek("join"):
                kind = "inner"
            elif self.peek("inner") and self.peek2("join"):
                kind = "inner"
                self.i += 1
            elif self.peek("left"):
                kind = "left"
                self.i += 1
                self.accept("outer")
            elif self.peek("right"):
                kind = "right"
                self.i += 1
                self.accept("outer")
            elif self.peek("full"):
                kind = "full"
                self.i += 1
                self.accept("outer")
            if kind is None:
                return rel
            self.expect("join")
            right = self._relation_primary()
            self.expect("on")
            cond = self._expr()
            if kind == "right":  # normalize: right join = left join flipped
                rel = ast.JoinRel(right, rel, "left", cond)
            else:
                rel = ast.JoinRel(rel, right, kind, cond)

    def _relation_primary(self) -> ast.Node:
        t = self.tok
        if t.kind == "ident" and t.value.lower() == "values":
            self.i += 1
            rows = []
            while True:
                if self.accept("("):
                    row = [self._expr()]
                    while self.accept(","):
                        row.append(self._expr())
                    self.expect(")")
                else:
                    # bare single-column row: VALUES 1, 2 (SqlBase.g4:145
                    # rowValue := expression | '(' expression... ')')
                    row = [self._expr()]
                rows.append(tuple(row))
                if not self.accept(","):
                    break
            alias = None
            cols = []
            if self.accept("as"):
                alias = self.ident()
            else:
                alias = self._implicit_alias()
            if alias is not None and self.accept("("):
                cols.append(self.ident())
                while self.accept(","):
                    cols.append(self.ident())
                self.expect(")")
            return ast.ValuesRel(tuple(rows), alias, tuple(cols))
        if t.kind == "ident" and t.value.lower() == "unnest" and self.peek2("("):
            self.i += 2  # 'unnest' '('
            args = [self._expr()]
            while self.accept(","):
                args.append(self._expr())
            self.expect(")")
            ordinality = False
            if self.accept_word("with"):
                if self.accept_word("ordinality") is None:
                    raise SyntaxError("expected ORDINALITY after WITH")
                ordinality = True
            alias = None
            cols: List[str] = []
            if self.accept("as"):
                alias = self.ident()
            else:
                alias = self._implicit_alias()
            if alias is not None and self.accept("("):
                cols.append(self.ident())
                while self.accept(","):
                    cols.append(self.ident())
                self.expect(")")
            return ast.Unnest(tuple(args), ordinality, alias, tuple(cols))
        if self.accept("("):
            if self.peek("select"):
                q = self._query()
                self.expect(")")
                alias = None
                if self.accept("as"):
                    alias = self.ident()
                else:
                    alias = self._implicit_alias()
                return ast.SubqueryRel(q, alias)
            rel = self._relation()
            self.expect(")")
            if isinstance(rel, ast.ValuesRel):
                # (VALUES ...) AS t (c1, c2): the alias binds the rows
                alias = None
                cols: List[str] = []
                if self.accept("as"):
                    alias = self.ident()
                else:
                    alias = self._implicit_alias()
                if alias is not None and self.accept("("):
                    cols.append(self.ident())
                    while self.accept(","):
                        cols.append(self.ident())
                    self.expect(")")
                if alias is not None:
                    import dataclasses as _dc

                    rel = _dc.replace(rel, alias=alias,
                                      column_names=tuple(cols) or rel.column_names)
            return rel
        name = _qualified_name(self)  # catalog-qualified: catalog.table

        def _sample_clause():
            if not self.accept_word("tablesample"):
                return None
            method = self.accept_word("bernoulli", "system")
            if method is None:
                raise SyntaxError("expected BERNOULLI or SYSTEM")
            self.expect("(")
            pct = float(self.tok.value)
            self.i += 1
            self.expect(")")
            return (method, pct)

        # reference grammar: sampledRelation wraps aliasedRelation, so
        # TABLESAMPLE follows the alias; the pre-alias position is also
        # accepted
        sample = _sample_clause()
        alias = None
        if self.accept("as"):
            alias = self.ident()
        else:
            alias = self._implicit_alias()
        if sample is None:
            sample = _sample_clause()
        return ast.TableRef(name, alias, sample)

    # -- expressions (precedence ladder) ------------------------------------
    def _expr(self) -> ast.Node:
        # lambda: ident -> body | (a, b, ...) -> body (valid only as a
        # function argument; the binder rejects stray lambdas)
        if self.tok.kind == "ident" and self.peek2("->"):
            param = self.ident()
            self.i += 1  # '->'
            return ast.Lambda(param, self._expr(), (param,))
        if self.tok.kind == "op" and self.tok.value == "(":
            params = self._try_lambda_params()
            if params is not None:
                return ast.Lambda(params[0], self._expr(), params)
        return self._or()

    def _try_lambda_params(self):
        """Lookahead for '(' ident (',' ident)* ')' '->'; consumes the
        tokens (including '->') and returns the parameter tuple only
        when the full pattern matches — else leaves the position
        untouched (a parenthesized expression)."""
        j = self.i + 1
        params = []
        toks = self.tokens
        while True:
            if j >= len(toks) or toks[j].kind != "ident":
                return None  # covers '()' and trailing-comma forms
            params.append(toks[j].value)
            j += 1
            if j < len(toks) and toks[j].kind == "op" and toks[j].value == ",":
                j += 1
                continue
            break
        if (j + 1 < len(toks)
                and toks[j].kind == "op" and toks[j].value == ")"
                and toks[j + 1].kind == "op" and toks[j + 1].value == "->"):
            self.i = j + 2
            return tuple(params)
        return None

    def _or(self) -> ast.Node:
        e = self._and()
        while self.accept("or"):
            e = ast.Binary("or", e, self._and())
        return e

    def _and(self) -> ast.Node:
        e = self._not()
        while self.accept("and"):
            e = ast.Binary("and", e, self._not())
        return e

    def _not(self) -> ast.Node:
        if self.accept("not"):
            return ast.Unary("not", self._not())
        return self._predicate()

    def _predicate(self) -> ast.Node:
        e = self._concat()
        while True:
            if self.peek("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.tok.value
                self.i += 1
                op = {"!=": "<>"}.get(op, op)
                quant = self.accept_word("any", "some", "all")
                if quant is not None:
                    self.expect("(")
                    q = self._query()
                    self.expect(")")
                    e = ast.QuantifiedComparison(
                        op, e, "all" if quant == "all" else "any", q)
                    continue
                rhs = self._concat()
                e = ast.Binary(op, e, rhs)
                continue
            negated = False
            save = self.i
            if self.accept("not"):
                if self.peek("in", "like", "between"):
                    negated = True
                else:
                    self.i = save
                    return e
            if self.accept("between"):
                lo = self._concat()
                self.expect("and")
                hi = self._concat()
                e = ast.Between(e, lo, hi, negated)
                continue
            if self.accept("in"):
                self.expect("(")
                if self.peek("select"):
                    q = self._query()
                    self.expect(")")
                    e = ast.InSubquery(e, q, negated)
                else:
                    items = [self._expr()]
                    while self.accept(","):
                        items.append(self._expr())
                    self.expect(")")
                    e = ast.InList(e, tuple(items), negated)
                continue
            if self.accept("like"):
                e = ast.Like(e, self._concat(), negated)
                continue
            if self.accept("is"):
                neg = bool(self.accept("not"))
                self.expect("null")
                e = ast.IsNull(e, neg)
                continue
            return e

    def _concat(self) -> ast.Node:
        e = self._addsub()
        while self.peek("||"):
            self.i += 1
            e = ast.FuncCall("concat", (e, self._addsub()))
        return e

    def _addsub(self) -> ast.Node:
        e = self._muldiv()
        while self.peek("+", "-"):
            op = self.tok.value
            self.i += 1
            e = ast.Binary(op, e, self._muldiv())
        return e

    def _muldiv(self) -> ast.Node:
        e = self._unary()
        while self.peek("*", "/", "%"):
            op = self.tok.value
            self.i += 1
            e = ast.Binary(op, e, self._unary())
        return e

    def _unary(self) -> ast.Node:
        if self.accept("-"):
            return ast.Unary("-", self._unary())
        if self.accept("+"):
            return self._unary()
        return self._postfix()

    def _postfix(self) -> ast.Node:
        e = self._primary()
        while True:
            if self.accept("["):
                idx = self._expr()
                self.expect("]")
                e = ast.Subscript(e, idx)
                continue
            # row-field access on non-identifier primaries:
            # CAST(... AS ROW(x ...)).x — identifier dots are consumed
            # by _primary's qualified-name path
            if not isinstance(e, ast.Identifier) and self.peek(".") \
                    and self.tokens[self.i + 1].kind in ("ident", "keyword"):
                self.i += 1
                e = ast.FieldAccess(e, self.ident())
                continue
            return e

    def _primary(self) -> ast.Node:
        t = self.tok

        if t.kind == "number":
            self.i += 1
            return ast.NumberLit(t.value)
        if t.kind == "string":
            self.i += 1
            return ast.StringLit(t.value)
        if self.accept("null"):
            return ast.NullLit()
        if self.accept("true"):
            return ast.NumberLit("1")  # boolean literal folded
        if self.accept("false"):
            return ast.NumberLit("0")

        if self.accept("date"):
            s = self.tok
            if s.kind != "string":
                raise SyntaxError("expected string after DATE")
            self.i += 1
            return ast.DateLit(s.value)

        if self.peek("timestamp") and self.tokens[self.i + 1].kind == "string":
            self.i += 1
            s = self.tok
            self.i += 1
            return ast.TimestampLit(s.value)

        if (self.tok.kind in ("ident", "keyword") and self.tok.value.lower() == "time"
                and self.tokens[self.i + 1].kind == "string"):
            self.i += 1
            s = self.tok
            self.i += 1
            return ast.TimeLit(s.value)

        if self.accept("interval"):
            neg = bool(self.accept("-"))
            s = self.tok
            if s.kind != "string":
                raise SyntaxError("expected string after INTERVAL")
            self.i += 1
            unit = self.accept_word("year", "month", "day", "hour", "minute", "second",
                                    "years", "months", "days", "hours", "minutes", "seconds")
            if unit is None:
                raise SyntaxError(f"unsupported interval unit {self.tok.value!r}")
            return ast.IntervalLit(s.value, unit.rstrip("s"), neg)

        if self.accept("case"):
            operand = None
            if not self.peek("when"):
                operand = self._expr()
            whens = []
            while self.accept("when"):
                c = self._expr()
                self.expect("then")
                r = self._expr()
                whens.append((c, r))
            else_ = self._expr() if self.accept("else") else None
            self.expect("end")
            return ast.Case(tuple(whens), else_, operand)

        is_try_cast = (self.tok.kind == "ident"
                       and self.tok.value.lower() == "try_cast"
                       and self.peek2("("))
        if is_try_cast:
            self.i += 1
        if is_try_cast or self.accept("cast"):
            # try_cast == cast here: failed conversions already yield
            # NULL engine-wide (the try() identity rationale)
            self.expect("(")
            v = self._expr()
            self.expect("as")
            # type name: ident or keyword like DATE, possibly with (p, s)
            tt = self.tok
            self.i += 1
            type_name = tt.value
            if self.accept("("):
                # nested type text (row(x bigint, y row(...)), ...):
                # word tokens keep a separating space so field names
                # survive ("x bigint", not "xbigint")
                type_name += "("
                depth = 1
                prev_word = False
                while depth > 0:
                    t = self.tok
                    if t.kind == "eof":
                        raise SyntaxError("unterminated type in CAST")
                    self.i += 1
                    if t.value == "(":
                        depth += 1
                        type_name += "("
                        prev_word = False
                    elif t.value == ")":
                        depth -= 1
                        type_name += ")"
                        prev_word = False
                    elif t.value == ",":
                        type_name += ","
                        prev_word = False
                    else:
                        if prev_word:
                            type_name += " "
                        type_name += t.value
                        prev_word = t.kind in ("ident", "keyword", "number")
                self.expect(")")
                return ast.Cast(v, type_name)
            self.expect(")")
            return ast.Cast(v, type_name)

        if self.accept("extract"):
            self.expect("(")
            field = self.accept_word("year", "quarter", "month", "week", "day",
                                     "hour", "minute", "second", "day_of_week",
                                     "dow", "day_of_year", "doy")
            if field is None:
                raise SyntaxError(f"unsupported extract field {self.tok.value!r}")
            self.expect("from")
            v = self._expr()
            self.expect(")")
            return ast.Extract(field, v)

        if self.tok.kind == "ident" and self.tok.value.lower() == "position" \
                and self.peek2("("):
            # position(needle IN haystack) = strpos(haystack, needle);
            # operands parse at additive precedence so the IN separator
            # is not mistaken for an IN-list predicate
            self.i += 2
            needle = self._concat()
            self.expect("in")
            hay = self._concat()
            self.expect(")")
            return ast.FuncCall("strpos", (hay, needle))

        if self.accept("substring"):
            self.expect("(")
            v = self._expr()
            if self.accept("from"):
                start = self._expr()
                length = self._expr() if self.accept("for") else None
            else:
                self.expect(",")
                start = self._expr()
                length = self._expr() if self.accept(",") else None
            self.expect(")")
            return ast.Substring(v, start, length)

        if self.accept("exists"):
            self.expect("(")
            q = self._query()
            self.expect(")")
            return ast.Exists(q)

        if self.accept("?"):
            self.n_params += 1
            return ast.Parameter(self.n_params - 1)

        if self.accept("("):
            if self.peek("select"):
                q = self._query()
                self.expect(")")
                return ast.ScalarSubquery(q)
            e = self._expr()
            if self.peek(","):  # row constructor: (a, b, ...)
                items = [e]
                while self.accept(","):
                    items.append(self._expr())
                self.expect(")")
                return ast.RowCtor(tuple(items))
            self.expect(")")
            return e

        if t.kind == "ident" and t.value.lower() == "array" and self.peek2("["):
            self.i += 2  # 'array' '['
            items: List[ast.Node] = []
            if not self.peek("]"):
                items.append(self._expr())
                while self.accept(","):
                    items.append(self._expr())
            self.expect("]")
            return ast.ArrayCtor(tuple(items))

        if t.kind == "ident" or (t.kind == "keyword" and t.value in ("year", "month", "day")):
            name = t.value
            pos = t.pos  # statement offset for binder diagnostics
            self.i += 1
            if self.accept("("):  # function call
                if self.accept("*"):
                    self.expect(")")
                    fc = ast.FuncCall(name.lower(), (), star=True, pos=pos)
                else:
                    distinct = bool(self.accept("distinct"))
                    args: List[ast.Node] = []
                    if not self.peek(")"):
                        args.append(self._expr())
                        while self.accept(","):
                            args.append(self._expr())
                    self.expect(")")
                    fc = ast.FuncCall(name.lower(), tuple(args),
                                      distinct=distinct, pos=pos)
                # null treatment clause (window value functions):
                # fn(...) [IGNORE NULLS | RESPECT NULLS] OVER (...) —
                # two-token lookahead so a bare alias named ignore/
                # respect still parses
                t0 = self.tok
                if t0.kind in ("ident", "keyword") \
                        and t0.value.lower() in ("ignore", "respect") \
                        and self.tokens[self.i + 1].kind in ("ident", "keyword") \
                        and self.tokens[self.i + 1].value.lower() == "nulls":
                    word = t0.value.lower()
                    self.i += 2
                    if word == "ignore":
                        fc = dataclasses.replace(fc, ignore_nulls=True)
                if self.accept("over"):
                    self.expect("(")
                    partition: List[ast.Node] = []
                    if self.accept("partition"):
                        self.expect("by")
                        partition.append(self._expr())
                        while self.accept(","):
                            partition.append(self._expr())
                    order: List[ast.OrderItem] = []
                    if self.accept("order"):
                        self.expect("by")
                        order.append(self._order_item())
                        while self.accept(","):
                            order.append(self._order_item())
                    frame = None
                    ft = self.accept_word("rows", "range")
                    if ft is not None:
                        if self.accept("between"):
                            fs = self._frame_bound()
                            self.expect("and")
                            fe = self._frame_bound()
                        else:
                            fs = self._frame_bound()
                            fe = ("current", 0)
                        frame = (ft, fs, fe)
                    self.expect(")")
                    return ast.WindowExpr(fc, tuple(partition), tuple(order), frame)
                return fc
            parts = [name]
            while self.peek(".") :
                self.i += 1
                parts.append(self.ident())
            return ast.Identifier(tuple(parts), pos=pos)

        raise SyntaxError(f"unexpected token {t!r}")


def parse_query(sql: str) -> ast.Query:
    return Parser(sql).parse_query()


def _hoist_order_limit(q: ast.Node):
    """Trailing ORDER BY/LIMIT of a set-operation arm bind to the whole
    operation (SELECT-level grammar has no lookahead for that); an
    inner SetOp arm re-hoists what its own parse attached."""
    if isinstance(q, ast.Query) and (q.order_by or q.limit is not None):
        order_by, limit = q.order_by, q.limit
        q = ast.Query(
            select=q.select, distinct=q.distinct, from_=q.from_,
            where=q.where, group_by=q.group_by, having=q.having,
        )
        return q, order_by, limit
    if isinstance(q, ast.SetOp) and (q.order_by or q.limit is not None):
        import dataclasses as _dc

        order_by, limit = q.order_by, q.limit
        return _dc.replace(q, order_by=(), limit=None), order_by, limit
    return q, (), None


def _qualified_name(p: Parser) -> str:
    name = p.ident()
    while p.peek("."):
        p.i += 1
        name += "." + p.ident()
    return name


def _finish(p: Parser, node: ast.Node) -> ast.Node:
    """Require end of input (trailing tokens would silently change the
    statement's meaning, e.g. COMMIT AND CHAIN)."""
    p.accept(";")
    if p.tok.kind != "eof":
        raise SyntaxError(f"trailing input at {p.tok!r}")
    return node


def parse_statement(sql: str) -> ast.Node:
    """Statement-level entry (SqlParser.createStatement analog):
    SELECT | EXPLAIN [ANALYZE] | SET SESSION | SHOW TABLES/COLUMNS/SESSION."""
    p = Parser(sql)
    if p.accept("explain"):
        analyze = bool(p.accept("analyze"))
        verbose = analyze and bool(p.accept_word("verbose"))
        distributed = False
        validate = False
        if p.accept("("):
            while not p.accept(")"):
                if p.accept_word("type"):
                    kind = p.accept_word("distributed", "logical",
                                         "validate")
                    if kind is None:
                        raise SyntaxError("EXPLAIN (TYPE ...) supports "
                                          "LOGICAL | DISTRIBUTED | "
                                          "VALIDATE")
                    distributed = kind == "distributed"
                    validate = kind == "validate"
                elif p.accept(",") is None:
                    raise SyntaxError(f"bad EXPLAIN option at {p.tok!r}")
        q = p._query()
        p.accept(";")
        return ast.Explain(q, analyze, distributed, verbose, validate)
    if p.accept("set"):
        if p.accept_word("path"):
            # pathSpecification (SqlBase.g4:98): comma-separated
            # elements, each a dotted name — both separators kept
            # distinct in the recorded string
            def element() -> str:
                parts = [p.ident()]
                while p.accept("."):
                    parts.append(p.ident())
                return ".".join(parts)

            elems = [element()]
            while p.accept(","):
                elems.append(element())
            p.accept(";")
            return ast.SetPath(", ".join(elems))
        p.expect("session")
        name = p.ident()
        p.expect("=")
        t = p.tok
        if t.kind in ("number", "string", "ident", "keyword"):
            p.i += 1
            value = t.value
        else:
            raise SyntaxError(f"bad SET SESSION value {t!r}")
        p.accept(";")
        return ast.SetSession(name, value)
    if p.accept("create"):
        if p.accept_word("or"):
            if p.accept_word("replace") is None:
                raise SyntaxError("expected REPLACE after CREATE OR")
            if p.accept_word("view") is None:
                raise SyntaxError("expected VIEW after CREATE OR REPLACE")
            return _create_view(p, replace=True)
        if p.accept_word("view"):
            return _create_view(p, replace=False)
        if p.accept_word("schema"):
            if_not_exists = False
            if p.accept_word("if"):
                p.expect("not")
                p.expect("exists")
                if_not_exists = True
            cat, name = _schema_name(p)
            return _finish(p, ast.CreateSchema(cat, name, if_not_exists))
        p.expect("table")
        name = _qualified_name(p)
        props = []
        if p.accept_word("with"):
            # WITH (partitioned_by = 'col' | ARRAY['a','b'], ...) —
            # the reference's table properties (HiveTableProperties)
            p.expect("(")
            while True:
                key = p.tok.value
                p.i += 1
                p.expect("=")
                if p.accept_word("array"):
                    p.expect("[")
                    vals = []
                    while not p.accept("]"):
                        vals.append(p.tok.value)
                        p.i += 1
                        p.accept(",")
                    props.append((key, tuple(vals)))
                else:
                    props.append((key, p.tok.value))
                    p.i += 1
                if not p.accept(","):
                    break
            p.expect(")")
        p.expect("as")
        q = p._query()
        return _finish(p, ast.CreateTableAs(name, q, tuple(props)))
    if p.accept("insert"):
        p.expect("into")
        name = _qualified_name(p)
        q = p._query()
        return _finish(p, ast.InsertInto(name, q))
    if p.accept("drop"):
        if p.accept_word("view"):
            if_exists = False
            if p.accept_word("if"):
                p.expect("exists")
                if_exists = True
            return _finish(p, ast.DropView(_qualified_name(p), if_exists))
        if p.accept_word("schema"):
            if_exists = False
            if p.accept_word("if"):
                p.expect("exists")
                if_exists = True
            cat, name = _schema_name(p)
            cascade = p.accept_word("cascade") is not None
            if not cascade:
                p.accept_word("restrict")
            return _finish(p, ast.DropSchema(cat, name, if_exists, cascade))
        p.expect("table")
        name = _qualified_name(p)
        return _finish(p, ast.DropTable(name))
    quals = p.accept_word("grant", "revoke")
    if quals is not None:
        is_grant = quals == "grant"
        privs = []
        if p.accept("all"):
            p.accept_word("privileges")
            privs = ["select", "insert", "delete"]
        else:
            while True:
                w = p.accept_word("select", "insert", "delete")
                if w is None:
                    raise SyntaxError("expected privilege name")
                privs.append(w)
                if not p.accept(","):
                    break
        p.expect("on")
        p.accept("table")
        table = _qualified_name(p)
        ok = (p.accept_word("to") is not None) if is_grant \
            else p.accept("from")
        if not ok:
            raise SyntaxError("expected TO/FROM")
        p.accept_word("user")
        grantee = p.ident()
        cls = ast.Grant if is_grant else ast.Revoke
        return _finish(p, cls(tuple(privs), table, grantee))
    if p.accept_word("alter"):
        if p.accept_word("schema"):
            cat, name = _schema_name(p)
            if p.accept_word("rename") is None or p.accept_word("to") is None:
                raise SyntaxError("expected RENAME TO after ALTER SCHEMA")
            _, new_name = _schema_name(p)
            return _finish(p, ast.RenameSchema(cat, name, new_name))
        p.expect("table")
        name = _qualified_name(p)
        if p.accept_word("add"):
            p.accept_word("column")
            col = p.ident()
            type_name = _type_text(p)
            return _finish(p, ast.AddColumn(name, col, type_name))
        if p.accept("drop"):
            p.accept_word("column")
            return _finish(p, ast.DropColumn(name, p.ident()))
        if p.accept_word("rename") is None:
            raise SyntaxError(
                "ALTER TABLE supports RENAME TO / ADD COLUMN / DROP COLUMN")
        if p.accept_word("to") is None:
            raise SyntaxError("expected TO")
        new_name = _qualified_name(p)
        return _finish(p, ast.AlterTableRename(name, new_name))
    if p.accept_word("delete"):
        if p.accept("from") is None:
            p.expect("from")
        name = _qualified_name(p)
        where = p._expr() if p.accept("where") else None
        return _finish(p, ast.Delete(name, where))
    if p.accept_word("start"):
        if p.accept_word("transaction") is None:
            raise SyntaxError("expected TRANSACTION after START")
        read_only = False
        if p.accept_word("read"):
            if p.accept_word("only"):
                read_only = True
            elif p.accept_word("write"):
                read_only = False
            else:
                raise SyntaxError("expected ONLY/WRITE after READ")
        return _finish(p, ast.StartTransaction(read_only))
    if p.accept_word("commit"):
        p.accept_word("work")
        return _finish(p, ast.Commit())
    if p.accept_word("rollback"):
        p.accept_word("work")
        return _finish(p, ast.Rollback())
    if p.accept_word("reset"):
        p.expect("session")
        return _finish(p, ast.ResetSession(p.ident()))
    if p.accept("show"):
        if p.accept("create"):
            p.expect("table")
            return _finish(p, ast.ShowCreateTable(_qualified_name(p)))
        if p.accept_word("stats"):
            p.expect("for")
            return _finish(p, ast.ShowStats(_qualified_name(p)))
        if p.accept("tables"):
            return _finish(p, ast.ShowTables())
        if p.accept("session"):
            return _finish(p, ast.ShowSession())
        if p.accept_word("catalogs"):
            return _finish(p, ast.ShowCatalogs())
        if p.accept_word("functions"):
            return _finish(p, ast.ShowFunctions())
        if p.accept_word("partitions"):
            if p.accept("from") is None and p.accept_word("in") is None:
                raise SyntaxError("expected FROM after SHOW PARTITIONS")
            return _finish(p, ast.ShowPartitions(_qualified_name(p)))
        if p.accept_word("schemas"):
            cat = None
            if p.accept("from") or p.accept_word("in"):
                cat = p.ident()
            return _finish(p, ast.ShowSchemas(cat))
        p.expect("columns")
        p.expect("from")
        table = _qualified_name(p)
        return _finish(p, ast.ShowColumns(table))
    if p.accept_word("describe") or p.accept_word("desc"):
        if p.accept_word("output"):
            return _finish(p, ast.DescribeOutput(p.ident()))
        if p.accept_word("input"):
            return _finish(p, ast.DescribeInput(p.ident()))
        return _finish(p, ast.Describe(_qualified_name(p)))
    if p.accept_word("prepare"):
        name = p.ident()
        if p.accept_word("from") is None:
            p.expect("from")
        q = parse_statement_body(p)
        return _finish(p, ast.Prepare(name, q))
    if p.accept_word("execute"):
        name = p.ident()
        params = []
        if p.accept_word("using"):
            params.append(p._expr())
            while p.accept(","):
                params.append(p._expr())
        return _finish(p, ast.Execute(name, tuple(params)))
    if p.accept_word("deallocate"):
        p.accept_word("prepare")
        return _finish(p, ast.Deallocate(p.ident()))
    if p.accept_word("use"):
        name = _qualified_name(p)
        parts = name.split(".")
        if len(parts) == 1:
            return _finish(p, ast.Use(None, parts[0]))
        if len(parts) == 2:
            return _finish(p, ast.Use(parts[0], parts[1]))
        raise SyntaxError("USE takes [catalog.]schema")
    if p.accept_word("call"):
        name = _qualified_name(p)
        p.expect("(")
        args = []
        if not p.accept(")"):
            args.append(p._expr())
            while p.accept(","):
                args.append(p._expr())
            p.expect(")")
        return _finish(p, ast.Call(name, tuple(args)))
    return p.parse_query()


def _create_view(p: Parser, replace: bool) -> ast.Node:
    """CREATE [OR REPLACE] VIEW v AS query — the query's original TEXT
    is what gets stored (views re-bind at reference time, the way
    metadata.createView persists ViewDefinition JSON with the SQL)."""
    name = _qualified_name(p)
    p.expect("as")
    start = p.tok.pos
    p._query()  # validate it parses; the stored form is the text
    sql_text = p.sql[start:p.tok.pos].strip().rstrip(";").strip()
    return _finish(p, ast.CreateView(name, sql_text, replace))


def _schema_name(p: Parser) -> tuple:
    """[catalog.]schema -> (catalog | None, schema)."""
    name = _qualified_name(p)
    parts = name.split(".")
    if len(parts) == 1:
        return None, parts[0]
    if len(parts) == 2:
        return parts[0], parts[1]
    raise SyntaxError("schema names take [catalog.]name")


def _type_text(p: Parser) -> str:
    """A type name as written: ident/keyword plus optional (p[,s])
    (ALTER TABLE ADD COLUMN re-uses the binder's type parser on it)."""
    t = p.tok
    if t.kind not in ("ident", "keyword"):
        raise SyntaxError(f"expected type name, got {t!r}")
    p.i += 1
    text = t.value
    if p.accept("("):
        text += "("
        first = True
        while not p.accept(")"):
            if not first:
                p.expect(",")
                text += ","
            first = False
            if p.tok.kind == "eof":
                raise SyntaxError("unterminated type parameters")
            text += p.tok.value
            p.i += 1
        text += ")"
    return text


def parse_statement_body(p: Parser) -> ast.Node:
    """The statement after PREPARE name FROM (query subset)."""
    return p._query()
