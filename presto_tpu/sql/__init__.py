from presto_tpu.sql.parser import parse_query  # noqa: F401
