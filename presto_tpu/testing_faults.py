"""Deterministic fault-injection harness for chaos testing.

Reference analog: the reference validates its failure paths with
``TestingPrestoServer`` clusters whose nodes are killed mid-query
(presto-tests) — ad hoc and time-dependent.  This harness makes the
chaos *deterministic*: named fault points are armed with explicit
schedules (fire on the Nth pass, at most K times, on a named node),
and any randomized decision draws from ONE seeded RNG, so a chaos test
reproduces byte-for-byte from its seed.

Fault points (the catalog; docs/fault-tolerance.md):

``worker.refuse_connect``     the worker drops the TCP connection of a
                              matching request without a response
                              (connection-refused/reset from the
                              client's perspective).  Heartbeat probes
                              (``GET /v1/info``) are exempt from the
                              request-gated points: wall-clock-timed
                              detector probes must not race query
                              traffic for schedule slots.
``worker.die_after_n_pages``  the worker produces ``pages`` task-output
                              pages, then "dies": every subsequent
                              request on that worker is dropped — the
                              mid-query crash scenario.
``worker.slow_response_ms``   the worker sleeps ``ms`` before handling
                              a matching request (straggler/timeout
                              scenario).
``page.corrupt_crc``          a produced page's payload byte is flipped
                              before it enters the output buffer; the
                              consumer's CRC check rejects it
                              (PageIntegrityError — transient, retried).
``net.duplicate_page``        the shuffle client re-processes a results
                              response it already consumed — the delayed
                              duplicate reply of a retried token GET.
                              The client's seq-based dedupe must drop
                              the duplicated pages (protocol invariant
                              exchange.at-most-once-delivery).
``net.drop_ack``              the worker accepts an acknowledge request
                              but discards it (the ack is lost en
                              route); the unacked pages re-serve at the
                              same token and a later, higher ack
                              supersedes — delivery must stay
                              exactly-once under replay.

Arming::

    from presto_tpu.testing_faults import FAULTS
    FAULTS.arm("worker.die_after_n_pages", node="worker-a-8080", pages=2)

or from the environment (the CI chaos leg)::

    PRESTO_TPU_FAULTS="worker.slow_response_ms:ms=50,count=3"
    PRESTO_TPU_FAULT_SEED=1234

The registry is process-global and INERT unless armed — the worker
server's checks are one ``enabled`` attribute read when no fault was
ever armed, so production paths pay nothing.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict, List, Optional

from presto_tpu.sync import named_lock

_log = logging.getLogger("presto_tpu.faults")

FAULT_POINTS = (
    "worker.refuse_connect",
    "worker.die_after_n_pages",
    "worker.slow_response_ms",
    "page.corrupt_crc",
    "net.duplicate_page",
    "net.drop_ack",
)


class FaultSpec:
    """One armed fault: a point, a match scope, and a schedule."""

    __slots__ = ("point", "node", "after", "count", "ms", "pages",
                 "probability", "hits", "fired")

    def __init__(self, point: str, node: Optional[str] = None,
                 after: int = 0, count: Optional[int] = None,
                 ms: int = 0, pages: int = 0, probability: float = 1.0):
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(known: {list(FAULT_POINTS)})")
        self.point = point
        self.node = node          # substring match on node id/uri; None = any
        self.after = int(after)   # skip the first N matching passes
        self.count = None if count is None else int(count)  # max firings
        self.ms = int(ms)
        self.pages = int(pages)
        # die_after_n_pages: the worker evaluates the point once per
        # page it is about to produce, so "survive N pages" is exactly
        # an after=N schedule
        if point == "worker.die_after_n_pages" and self.pages and not after:
            self.after = self.pages
        self.probability = float(probability)
        self.hits = 0             # matching passes observed
        self.fired = 0            # times actually fired

    def matches(self, node: Optional[str]) -> bool:
        return self.node is None or (node is not None and self.node in node)


class FaultRegistry:
    """Process-global set of armed faults + the seeded RNG all
    probabilistic decisions draw from."""

    def __init__(self, seed: int = 0):
        self._lock = named_lock("testing_faults.FaultRegistry._lock")
        self._specs: List[FaultSpec] = []
        self._rng = random.Random(seed)
        self.seed = seed
        #: fast-path gate: False means no fault was ever armed and
        #: every check is a single attribute read
        self.enabled = False

    def reseed(self, seed: int) -> None:
        with self._lock:
            self.seed = seed
            self._rng = random.Random(seed)

    def arm(self, point: str, **kw) -> FaultSpec:
        spec = FaultSpec(point, **kw)
        with self._lock:
            self._specs.append(spec)
            self.enabled = True
        _log.warning("fault armed: %s %s", point,
                     {k: getattr(spec, k) for k in
                      ("node", "after", "count", "ms", "pages")
                      if getattr(spec, k) not in (None, 0)})
        return spec

    def disarm_all(self) -> None:
        with self._lock:
            self._specs.clear()
            self.enabled = False

    def specs(self, point: Optional[str] = None) -> List[FaultSpec]:
        with self._lock:
            return [s for s in self._specs
                    if point is None or s.point == point]

    # -- evaluation ---------------------------------------------------------
    def should_fire(self, point: str,
                    node: Optional[str] = None) -> Optional[FaultSpec]:
        """Evaluate one pass through a fault point; returns the firing
        spec (with its parameters) or None.  Counting is per-spec and
        lock-protected, so ``after``/``count`` schedules are exact."""
        if not self.enabled:
            return None
        with self._lock:
            for spec in self._specs:
                if spec.point != point or not spec.matches(node):
                    continue
                spec.hits += 1
                if spec.hits <= spec.after:
                    continue
                if spec.count is not None and spec.fired >= spec.count:
                    continue
                if spec.probability < 1.0 \
                        and self._rng.random() >= spec.probability:
                    continue
                spec.fired += 1
                self._count(point)
                return spec
        return None

    @staticmethod
    def _count(point: str) -> None:
        from presto_tpu.obs import METRICS

        METRICS.counter("fault.injections_total").inc()
        METRICS.counter(f"fault.{point}").inc()  # metrics: allow
        _log.warning("fault fired: %s", point)

    def maybe_corrupt_page(self, raw: bytes,
                           node: Optional[str] = None) -> bytes:
        """page.corrupt_crc hook: flip one payload byte past the frame
        header so the consumer's CRC check rejects the page."""
        spec = self.should_fire("page.corrupt_crc", node)
        if spec is None or len(raw) < 8:
            return raw
        i = len(raw) - 1  # last byte is always payload, never header
        return raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]


#: the process-global registry every hook consults
FAULTS = FaultRegistry()


def parse_fault_env(spec_text: str, registry: FaultRegistry) -> None:
    """Arm from ``PRESTO_TPU_FAULTS`` syntax:
    ``point[:k=v[,k=v...]][;point...]`` — e.g.
    ``worker.slow_response_ms:ms=50,count=3;page.corrupt_crc:count=1``."""
    for part in spec_text.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, args = part.partition(":")
        kw: Dict[str, object] = {}
        for pair in filter(None, (a.strip() for a in args.split(","))):
            k, _, v = pair.partition("=")
            if k in ("after", "count", "ms", "pages"):
                kw[k] = int(v)
            elif k == "probability":
                kw[k] = float(v)
            else:
                kw[k] = v
        registry.arm(point.strip(), **kw)


def arm_from_env(registry: Optional[FaultRegistry] = None) -> FaultRegistry:
    """Resolve the PRESTO_TPU_FAULTS / PRESTO_TPU_FAULT_SEED pair once
    (launcher/test bootstrap; the engine-lint env-read convention)."""
    import os

    reg = registry or FAULTS
    seed = os.environ.get("PRESTO_TPU_FAULT_SEED")
    if seed:
        reg.reseed(int(seed))
    spec = os.environ.get("PRESTO_TPU_FAULTS")
    if spec:
        parse_fault_env(spec, reg)
    return reg
