"""Live per-query progress: stage split counts + a monotone percentage.

Reference analog: the driver/split counters behind Presto's
``StatementStats.progressPercentage`` (``QueryStats.java``'s
completedDrivers/totalDrivers) — the coordinator derives a 0..100
figure from per-stage splits-done/total, and every surface (statement
protocol, CLI progress line, web UI) reads the same numbers.

Publication mirrors the tracer's design: execution code calls
``current_progress()`` (one thread-local read; ``None`` when nothing
was registered — queries outside the runner lifecycle cost nothing)
and updates the active :class:`QueryProgress`.  A process-wide bounded
registry keyed by query id serves readers (the statement protocol's
page responses, ``GET /v1/query/<id>/progress``).

Monotonicity contract: :meth:`QueryProgress.percentage` NEVER
decreases — stages appear dynamically (a scan discovered mid-query
adds a denominator), so the raw ratio can dip; the reported figure is
the running maximum, pinned to 100 only when the query reaches a
terminal state.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from presto_tpu.sync import named_lock


class StageProgress:
    __slots__ = ("name", "splits_total", "splits_done", "rows", "bytes",
                 "state")

    def __init__(self, name: str, splits_total: Optional[int] = None):
        self.name = name
        self.splits_total = splits_total
        self.splits_done = 0
        self.rows = 0
        self.bytes = 0
        self.state = "RUNNING"

    def snapshot(self) -> Dict:
        return {
            "stage": self.name,
            "state": self.state,
            "splitsDone": self.splits_done,
            "splitsTotal": self.splits_total,
            "rows": self.rows,
            "bytes": self.bytes,
        }


class QueryProgress:
    """One query's stage table + the monotone completion percentage."""

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.t0 = time.perf_counter()
        self._lock = named_lock("progress.QueryProgress._lock")
        self._stages: "collections.OrderedDict[str, StageProgress]" = (
            collections.OrderedDict())
        self._max_pct = 0.0
        self._done = False
        self._seq = 0

    # -- writers --------------------------------------------------------
    def stage(self, name: str,
              splits_total: Optional[int] = None) -> StageProgress:
        """Get-or-create a stage entry.  Passing ``splits_total`` for an
        existing stage RESETS its counters: a capacity retry re-runs the
        stage from split zero, and stale done-counts would overshoot."""
        with self._lock:
            st = self._stages.get(name)
            if st is None:
                st = self._stages[name] = StageProgress(name, splits_total)
            elif splits_total is not None:
                st.splits_total = splits_total
                st.splits_done = 0
                st.rows = 0
                st.bytes = 0
                st.state = "RUNNING"
            return st

    def new_stage_name(self, prefix: str) -> str:
        """Unique stage key for dynamically discovered stages
        (``mh:chain#0``, ``dist:aggregation#2``...)."""
        with self._lock:
            n = self._seq
            self._seq += 1
        return f"{prefix}#{n}"

    def split_done(self, name: str, rows: int = 0, nbytes: int = 0,
                   n: int = 1) -> None:
        with self._lock:
            st = self._stages.get(name)
            if st is None:
                st = self._stages[name] = StageProgress(name)
            st.splits_done += int(n)
            st.rows += int(rows)
            st.bytes += int(nbytes)

    def finish_stage(self, name: str) -> None:
        with self._lock:
            st = self._stages.get(name)
            if st is not None:
                st.state = "FINISHED"
                if st.splits_total is None:
                    st.splits_total = st.splits_done
                st.splits_done = max(st.splits_done, st.splits_total or 0)

    def mark_done(self) -> None:
        """Terminal: the query finished (or failed/was killed) — the
        percentage pins to 100 and every open stage closes."""
        with self._lock:
            self._done = True
            for st in self._stages.values():
                if st.state == "RUNNING":
                    st.state = "FINISHED"
                    if st.splits_total is None:
                        st.splits_total = st.splits_done

    # -- readers --------------------------------------------------------
    def percentage(self) -> float:
        """0..100, never decreasing (running maximum; see module doc)."""
        with self._lock:
            if self._done:
                self._max_pct = 100.0
                return 100.0
            ratios: List[float] = []
            for st in self._stages.values():
                if st.state == "FINISHED":
                    ratios.append(1.0)
                elif st.splits_total:
                    ratios.append(min(st.splits_done / st.splits_total, 1.0))
                else:
                    ratios.append(0.0)
            # cap at 99.9 while live: only mark_done may report 100
            pct = min(99.9, 100.0 * sum(ratios) / len(ratios)) if ratios \
                else 0.0
            self._max_pct = max(self._max_pct, pct)
            return round(self._max_pct, 1)

    def snapshot(self) -> Dict:
        pct = self.percentage()
        with self._lock:
            stages = [st.snapshot() for st in self._stages.values()]
            done = self._done
        return {
            "queryId": self.query_id,
            "done": done,
            "progressPercentage": pct,
            "elapsedMs": round((time.perf_counter() - self.t0) * 1e3, 1),
            "stages": stages,
        }


# ---------------------------------------------------------------------------
# process registry + thread-local activation (mirrors obs/trace.py)
# ---------------------------------------------------------------------------

_REGISTRY_MAX = 256
_REGISTRY: "collections.OrderedDict[str, QueryProgress]" = (
    collections.OrderedDict())
_REGISTRY_LOCK = named_lock("progress._REGISTRY_LOCK")

_ACTIVE = threading.local()


def register_progress(progress: QueryProgress) -> QueryProgress:
    with _REGISTRY_LOCK:
        _REGISTRY[progress.query_id] = progress
        _REGISTRY.move_to_end(progress.query_id)
        while len(_REGISTRY) > _REGISTRY_MAX:
            _REGISTRY.popitem(last=False)
    return progress


def progress_for(query_id: str) -> Optional[QueryProgress]:
    with _REGISTRY_LOCK:
        return _REGISTRY.get(query_id)


def current_progress() -> Optional[QueryProgress]:
    return getattr(_ACTIVE, "progress", None)


class _Activation:
    __slots__ = ("_progress", "_prev")

    def __init__(self, progress: Optional[QueryProgress]):
        self._progress = progress

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "progress", None)
        if self._progress is not None:
            _ACTIVE.progress = self._progress
        return self._progress

    def __exit__(self, *exc):
        if self._progress is not None:
            _ACTIVE.progress = self._prev
        return False


def publishing(progress: Optional[QueryProgress]) -> _Activation:
    """Bind a progress object to the current thread (``None`` = no-op),
    exactly like ``obs.tracing``."""
    return _Activation(progress)
