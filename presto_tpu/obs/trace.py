"""Query-lifecycle tracing: nestable spans, Chrome-trace export.

Reference analog: the per-operator timing spine of
``operator/OperatorStats.java`` + the request-correlation trace token
of ``server/GenerateTraceTokenRequestFilter.java:29`` — generalized
into Dapper-style spans so one query's life (parse -> bind -> plan ->
program-registry lookup/XLA compile -> per-operator execute ->
exchange -> device sync) is one exportable tree.

Design constraints:

- ~zero cost when disabled: ``span()`` with no active tracer is one
  thread-local read returning a shared no-op context manager — no
  allocation, no clock read.
- thread-safe: spans complete into one list under a lock and carry
  their thread id; nesting is implicit in (tid, t0, dur) containment,
  so concurrent stage threads interleave without corrupting parents.
- stitchable: tracers register process-wide under BOTH the query id
  and the trace token.  A worker task that receives the coordinator's
  ``X-Presto-Trace-Token`` activates ``tracer_for(token)`` — in a
  co-resident process (tests, single-box clusters) that is the SAME
  tracer object, so distributed stages land in one trace.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from presto_tpu.sync import named_lock


class Span:
    """One completed (or in-flight) trace span.  ``t0``/``dur`` are
    ``time.perf_counter()`` based — durations, never wall-clock."""

    __slots__ = ("name", "cat", "t0", "dur", "tid", "args")

    def __init__(self, name: str, cat: str, t0: float, dur: float,
                 tid: int, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.dur = dur
        self.tid = tid
        self.args = args

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Span({self.name!r}, {self.dur * 1e3:.2f}ms)"


class _NullSpan:
    """Shared no-op context manager: the disabled-tracing fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kwargs):  # matches _LiveSpan.set
        return self


NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager recording one span into its tracer on exit."""

    __slots__ = ("_tracer", "name", "cat", "_t0", "_args")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self._args = args

    def set(self, **kwargs):
        """Attach args discovered mid-span (row counts, capacities)."""
        if self._args is None:
            self._args = {}
        self._args.update(kwargs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        # StopIteration is generator flow control (the executor wraps
        # page pulls in spans), not a failure worth flagging
        if exc_type is not None and not issubclass(exc_type, StopIteration):
            self.set(error=exc_type.__name__)
        self._tracer._append(
            Span(self.name, self.cat, self._t0, dur,
                 threading.get_ident(), self._args))
        return False


class Tracer:
    """Per-query span collector.

    Completed spans collect into one list under a lock; nesting needs
    no explicit stack — spans record (tid, t0, dur), and containment
    within a thread lane IS the nesting (how Chrome/Perfetto render).

    Bounded: a huge scan emits one span per page pull per operator,
    and the process registry keeps the last ~64 tracers alive — an
    unbounded list would make always-on tracing (query.trace-dir) a
    slow leak on a serving coordinator.  Past ``max_spans`` new spans
    are counted in ``dropped`` instead of retained.
    """

    DEFAULT_MAX_SPANS = 100_000

    def __init__(self, query_id: str, trace_token: Optional[str] = None,
                 max_spans: Optional[int] = None):
        self.query_id = query_id
        self.trace_token = trace_token
        self.t_start = time.perf_counter()
        self.create_time = time.time()  # epoch anchor for export only
        self.spans: List[Span] = []
        self.max_spans = (self.DEFAULT_MAX_SPANS
                          if max_spans is None else max_spans)
        self.dropped = 0
        self._lock = named_lock("trace.Tracer._lock")

    # -- recording ------------------------------------------------------
    def span(self, name: str, cat: str = "engine",
             **args: Any) -> _LiveSpan:
        return _LiveSpan(self, name, cat, args or None)

    def add_complete(self, name: str, cat: str, t0: float, dur: float,
                     **args: Any) -> None:
        """Record a span measured externally (retroactive: e.g. the
        parse that ran before the tracer existed, or an XLA compile
        detected after the fact by the program registry)."""
        self._append(Span(name, cat, t0, dur, threading.get_ident(),
                          args or None))

    def _append(self, s: Span) -> None:
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return
            self.spans.append(s)

    # -- queries --------------------------------------------------------
    def total_s(self, name: str) -> float:
        """Summed duration of all spans with ``name``.  Note: nested
        same-name spans double count; lifecycle/compile span names are
        non-recursive by construction."""
        with self._lock:
            return sum(s.dur for s in self.spans if s.name == name)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name rollup: {name: {count, total_ms}} — the compact
        span-tree digest the query-log JSONL sink carries."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            e = out.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            e["count"] += 1
            e["total_ms"] += s.dur * 1e3
        for e in out.values():
            e["total_ms"] = round(e["total_ms"], 3)
        return out


# ---------------------------------------------------------------------------
# the active tracer (per-thread) + the process-wide trace registry
# ---------------------------------------------------------------------------

_ACTIVE = threading.local()


def current_tracer() -> Optional[Tracer]:
    return getattr(_ACTIVE, "tracer", None)


class _Activation:
    """Context manager binding a tracer to the current thread.  A None
    tracer is a no-op (callers need no branch)."""

    __slots__ = ("_tracer", "_prev")

    def __init__(self, tracer: Optional[Tracer]):
        self._tracer = tracer

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "tracer", None)
        if self._tracer is not None:
            _ACTIVE.tracer = self._tracer
        return self._tracer

    def __exit__(self, *exc):
        if self._tracer is not None:
            _ACTIVE.tracer = self._prev
        return False


def tracing(tracer: Optional[Tracer]) -> _Activation:
    return _Activation(tracer)


def span(name: str, cat: str = "engine", **args: Any):
    """A span under the current thread's tracer — the shared no-op
    when tracing is disabled (one thread-local read)."""
    tr = getattr(_ACTIVE, "tracer", None)
    if tr is None:
        return NULL_SPAN
    return tr.span(name, cat, **args)


# Completed/live tracers, retrievable by query id OR trace token for
# the coordinator's /v1/query/<id>/trace endpoint and for stitching
# worker-side spans into the coordinator's trace.  Bounded: a serving
# process must not accumulate one tracer per query forever — with the
# per-tracer span cap the worst-case retained heap is
# _REGISTRY_MAX/2 tracers x max_spans spans (generated tokens are
# unique, so a tracer usually occupies two keys: ~64 tracers).
_REGISTRY_MAX = 128
_REGISTRY: "collections.OrderedDict[str, Tracer]" = collections.OrderedDict()
_REGISTRY_LOCK = named_lock("trace._REGISTRY_LOCK")


def register(tracer: Tracer) -> Tracer:
    with _REGISTRY_LOCK:
        _REGISTRY[tracer.query_id] = tracer
        _REGISTRY.move_to_end(tracer.query_id)
        token = tracer.trace_token
        if token:
            # first binding wins for the TOKEN key: generated tokens
            # are unique, and when a client deliberately shares one
            # across queries (session-fixed X-Presto-Trace-Token) the
            # token names a correlation context — a later query must
            # not steal the binding mid-flight and corrupt another
            # query's worker-span stitching.  Per-query lookups always
            # work via the query id.
            if token not in _REGISTRY:
                _REGISTRY[token] = tracer
            _REGISTRY.move_to_end(token)
        while len(_REGISTRY) > _REGISTRY_MAX:
            _REGISTRY.popitem(last=False)
    return tracer


def lookup(key: str) -> Optional[Tracer]:
    """Tracer registered under a query id or trace token, if any."""
    with _REGISTRY_LOCK:
        return _REGISTRY.get(key)


def tracer_for(token: str, create: bool = False) -> Optional[Tracer]:
    """The tracer stitching spans for ``token``.  With ``create``,
    a worker that received a token it has never seen (remote
    coordinator) starts a local tracer so its spans are retrievable
    per-node; co-resident processes get the coordinator's own tracer
    and stitch into one trace."""
    tr = lookup(token)
    if tr is None and create:
        tr = register(Tracer(query_id=token, trace_token=token))
    return tr
