"""Process-wide engine metrics: counters, gauges, log2 histograms.

Reference analog: the JMX MBean surface of ``presto-main`` (every
operator/memory/exchange bean the jmx connector exposes as tables) —
here one flat registry, fed by the same instrumentation as the span
tracer (obs/trace.py) and queryable via the ``system_metrics`` table
(connectors/system.py).

Everything is process-global on purpose: coordinator executor, worker
task runners and rebuilt executors all account into one place, the
same sharing model as the process-wide program registry.  The
documented counter catalog lives in docs/observability.md; every name
below is pre-registered so ``SELECT * FROM system_metrics`` shows the
full catalog (at zero) even on a fresh process.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from presto_tpu.sync import named_lock


class Counter:
    """Monotonic counter (float-valued so *_seconds totals fit)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    """Point-in-time value: ``set()`` a sample or ``set_fn()`` a
    callback sampled at snapshot time (registry sizes, pool bytes)."""

    __slots__ = ("name", "_value", "_fn")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value


class Histogram:
    """Fixed log2-bucketed histogram (no per-query allocation, no
    unbounded label space).  Bucket k counts observations with
    ``2^(k-1) < v <= 2^k`` in the histogram's unit; bucket 0 catches
    v <= 1.  32 buckets cover 1ms..49 days when the unit is ms."""

    NUM_BUCKETS = 32

    __slots__ = ("name", "buckets", "count", "total", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.buckets = [0] * self.NUM_BUCKETS
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        import math

        v = max(float(value), 0.0)
        # ceil, not int: 2.9 belongs in bucket_le_4 (2 < v <= 4), and
        # int() would undercount every value in (2^k, 2^k + 1)
        k = 0 if v <= 1.0 else min(
            self.NUM_BUCKETS - 1, (math.ceil(v) - 1).bit_length())
        with self._lock:
            self.buckets[k] += 1
            self.count += 1
            self.total += v

    def rows(self) -> List[Tuple[str, float]]:
        with self._lock:
            out = [(f"{self.name}.count", float(self.count)),
                   (f"{self.name}.sum", round(self.total, 3))]
            for k, n in enumerate(self.buckets):
                if n:
                    out.append((f"{self.name}.bucket_le_{1 << k}", float(n)))
            count, buckets = self.count, list(self.buckets)
        if count:
            # derived quantiles ride the flat rows so system_metrics and
            # the ?format=json twin carry them; note merge_rows SUMS
            # across nodes — per-node reads are the meaningful ones
            out.extend((f"{self.name}.{p}", v)
                       for p, v in bucket_percentiles(buckets, count).items())
        return out

    def percentiles(self) -> Dict[str, float]:
        """Current p50/p95/p99 upper-bound estimates (doctor evidence)."""
        count, _, buckets = self.snapshot_raw()
        return bucket_percentiles(buckets, count)

    def snapshot_raw(self) -> Tuple[int, float, List[int]]:
        """(count, sum, per-bucket counts) under one lock acquisition —
        the structured form the OpenMetrics renderer needs to emit
        cumulative ``_bucket`` series."""
        with self._lock:
            return self.count, self.total, list(self.buckets)


def bucket_percentiles(
    buckets: List[int], count: int,
    qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
) -> Dict[str, float]:
    """{"p50": v, ...} from log2 bucket counts.  Each estimate is the
    UPPER bound (2^k) of the bucket containing the quantile rank — a
    deterministic, allocation-free derivation whose error is bounded by
    the bucket width (one octave), the Monarch/Prometheus fixed-bucket
    tradeoff.  Empty histograms report 0."""
    out: Dict[str, float] = {}
    for q in qs:
        label = f"p{int(round(q * 100))}"
        if count <= 0:
            out[label] = 0.0
            continue
        rank = q * count
        cum = 0
        value = float(1 << (len(buckets) - 1))
        for k, n in enumerate(buckets):
            cum += n
            if cum >= rank:
                value = float(1 << k)
                break
        out[label] = value
    return out


class MetricsRegistry:
    def __init__(self):
        self._lock = named_lock("metrics.MetricsRegistry._lock")
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> List[Tuple[str, float]]:
        """(name, value) rows — the system_metrics table's content.
        Histograms flatten to .count/.sum/.bucket_le_N rows."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
        rows = [(c.name, c.value) for c in counters]
        rows += [(g.name, g.value) for g in gauges]
        for h in histograms:
            rows += h.rows()
        return sorted(rows)

    def export(self) -> Dict[str, Dict]:
        """Typed snapshot keeping the instrument kinds apart — the
        OpenMetrics exposition (obs/openmetrics.py) needs to know
        counter from gauge from histogram, which the flat ``snapshot``
        rows erase."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
        out: Dict[str, Dict] = {
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges},
            "histograms": {},
        }
        for h in histograms:
            count, total, buckets = h.snapshot_raw()
            out["histograms"][h.name] = {
                "count": count, "sum": total, "buckets": buckets}
        return out

    def reset(self) -> None:
        """Tests only: drop every instrument (pre-registered names are
        re-created by re-importing callers on demand)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
        _preregister(self)


#: the process-wide registry (the default every instrumentation point
#: and the system_metrics table use)
METRICS = MetricsRegistry()


def _preregister(reg: MetricsRegistry) -> None:
    """The documented catalog (docs/observability.md) — registered at
    import so the system_metrics table is complete on a fresh process."""
    for name in (
        # query lifecycle
        "query.started", "query.finished", "query.failed",
        "query.planning_seconds_total", "query.execution_seconds_total",
        # XLA program registry / compilation
        "xla.programs_compiled", "xla.compile_seconds_total",
        "xla.registry_hits", "xla.registry_misses",
        # device <-> host transfers (the TPU tax EXPLAIN can't see)
        "device.get_calls", "device.get_bytes",
        # spill + exchange volume
        "spill.bytes", "exchange.pages_serialized",
        "exchange.bytes_serialized", "exchange.pages_deserialized",
        "exchange.bytes_deserialized",
        # streaming page exchange (parallel/streams.py): pages/bytes
        # through stage-boundary streams, producer time blocked on the
        # byte cap (backpressure), mid-stream producer-death replays
        # (resume from the consumer's last acked token), and kill-path
        # aborts (pool.kill_query -> streams.abort_query)
        "exchange.stream_pages_total", "exchange.stream_bytes_total",
        "exchange.producer_stall_seconds_total",
        "exchange.stream_replays_total", "exchange.streams_aborted",
        # distributed tiers (VERDICT weak #8: fallbacks countable)
        "dist.stages_total", "dist.fallbacks",
        "multihost.stages_total", "multihost.fallbacks",
        # two-stage window shuffle lost a worker mid-flight and
        # degraded to gather + coordinator window (stage-1 re-scanned)
        "multihost.window_shuffle_degraded",
        # worker task protocol (aborted = client cancellation, not a
        # failure — alerting keys on tasks.failed alone)
        "tasks.started", "tasks.finished", "tasks.failed",
        "tasks.aborted",
        # morsel-driven split scheduler (exec/tasks.py): dispatched
        # split count, consumer stall time waiting on in-flight splits,
        # and prefetch pipeline hit/miss (a hit = the next result was
        # already buffered when the consumer asked)
        "task.splits_dispatched", "task.scheduler_stall_seconds_total",
        "task.prefetch_hits", "task.prefetch_misses",
        # memory plane: cluster low-memory killer victims
        "memory.query_killed",
        # fault-tolerance plane (parallel/failure.py + net.py +
        # testing_faults.py; docs/fault-tolerance.md).  Classified
        # transport errors by reason — one counter per reason keeps the
        # label space fixed (no per-URI series):
        "net.errors_refused", "net.errors_timeout", "net.errors_http",
        "net.errors_protocol", "net.errors_other",
        # per-site poll errors (the classified replacements for the
        # old blind `except: pass` swallows)
        "worker.ping_errors", "cluster.metrics_poll_errors",
        "cluster.memory_poll_errors",
        # retry plane: transient HTTP retries, fragment re-dispatches
        # onto survivors, and splits recovered by coordinator-local
        # execution after every worker failed
        "retry.http_total", "retry.fragments_total",
        "retry.splits_recovered_local",
        # failure-detector state machine: transitions by target state
        "worker.state_transitions", "worker.transitions_to_suspect",
        "worker.transitions_to_dead", "worker.transitions_to_recovered",
        "worker.transitions_to_alive",
        # query deadlines: coordinator kills for EXCEEDED_TIME_LIMIT
        "query.killed_deadline",
        # deterministic fault-injection harness firings
        "fault.injections_total",
        # serving tier: admission plane (serving/admission.py) — queue
        # entries/exits, rejections by reason, and time spent blocked
        # on memory headroom (distinct from concurrency queueing)
        "admission.queued_total", "admission.admitted_total",
        "admission.rejected_queue_full", "admission.rejected_timeout",
        "admission.memory_blocked_total",
        "admission.memory_stall_seconds_total",
        # serving tier: structural result cache (final rows of
        # read-only queries, keyed by plan signature, invalidated by
        # table versions) and the subplan (stage-intermediate) cache
        # at exchange boundaries (serving/cache.py)
        "cache.result_hits", "cache.result_misses",
        "cache.result_stores", "cache.result_evictions",
        "cache.result_invalidations", "cache.result_oversize",
        "cache.subplan_hits", "cache.subplan_misses",
        "cache.subplan_stores", "cache.subplan_evictions",
        "cache.subplan_invalidations", "cache.subplan_oversize",
        # iterative optimizer: successful rule applications and
        # rewrites rejected by the soundness gate
        # (planner/iterative.py + analysis/soundness.py)
        "optimizer.rule_applications", "optimizer.rule_violations",
        # kernel-soundness analyzer: value hazards (overflow +
        # lossy-cast + division) and null-policy violations found per
        # analyzed plan (analysis/kernel_soundness.py)
        "kernel.overflow_hazards", "kernel.null_violations",
        "kernel.sanitizer_escapes",
    ):
        reg.counter(name)
    for name in (
        # HBM pool accounting (memory.wire_pool_gauges attaches the
        # sampling callbacks to the active MemoryPool)
        "memory.pool_reserved_bytes", "memory.pool_peak_bytes",
        "memory.pool_limit_bytes", "memory.pool_queries",
        # live split-scheduler state (exec/tasks.py wires the
        # sampling callbacks at import)
        "task.splits_queued", "task.splits_running",
        # failure-detector worker-state census (parallel/failure.py
        # wires the sampling callbacks when a detector is live)
        "worker.state_alive", "worker.state_suspect",
        "worker.state_dead", "worker.state_recovered",
        # streaming-exchange occupancy (parallel/streams.py wires the
        # sampling callbacks at import): unacked bytes buffered across
        # live streams and streams not yet drained/aborted
        "exchange.buffered_bytes", "exchange.open_streams",
        # concurrency sanitizer (presto_tpu/sync.py, opt-in via
        # PRESTO_TPU_LOCK_SANITIZER): instrumented-lock totals sampled
        # from the process-wide LockWatcher — zero when the sanitizer
        # is off.  lock_inversions > 0 in any run is a release blocker
        # (an observed lock-order cycle arc).
        "sanitizer.lock_acquisitions", "sanitizer.lock_wait_seconds",
        "sanitizer.lock_hold_seconds", "sanitizer.lock_inversions",
        "sanitizer.locks_tracked", "sanitizer.edges_observed",
        # serving tier: live admission queue depth / admitted-and-held
        # tickets (serving/admission.py wires the sampling callbacks)
        # and cache occupancy (serving/cache.py publishes on mutation)
        "admission.queue_depth", "admission.running",
        "cache.result_bytes", "cache.result_entries",
        "cache.subplan_bytes", "cache.subplan_entries",
    ):
        reg.gauge(name)
    for name in ("query.execution_ms", "xla.compile_ms",
                 # admission queue-wait distribution (serving tier)
                 "admission.queue_wait_ms"):
        reg.histogram(name)


_preregister(METRICS)


# ---------------------------------------------------------------------------
# task registry: the system_runtime_tasks table's source
# ---------------------------------------------------------------------------


class TaskEntry:
    __slots__ = ("task_id", "source", "state", "trace_token", "_t0",
                 "elapsed_ms", "rows", "error", "splits", "concurrency",
                 "stall_ms", "prefetch_hits")

    def __init__(self, task_id: str, source: str,
                 trace_token: Optional[str] = None):
        self.task_id = task_id
        self.source = source  # "local" | "worker"
        self.state = "RUNNING"
        self.trace_token = trace_token
        self._t0 = time.perf_counter()
        self.elapsed_ms: Optional[float] = None
        self.rows: Optional[int] = None
        self.error: Optional[str] = None
        # split-scheduler footprint (exec/tasks.py; NULL until the
        # executor reports — e.g. worker shuffle-pull tasks never do)
        self.splits: Optional[int] = None
        self.concurrency: Optional[int] = None
        self.stall_ms: Optional[float] = None
        self.prefetch_hits: Optional[int] = None


class TaskRegistry:
    """Bounded live+finished view of execution tasks on this node —
    coordinator-local query executions (one degenerate task per query)
    and worker task-protocol fragments (SqlTaskManager's task list
    analog, what the reference surfaces as system.runtime.tasks)."""

    def __init__(self, limit: int = 1000):
        self._lock = named_lock("metrics.TaskRegistry._lock")
        self._entries: "Dict[str, TaskEntry]" = {}
        self._order: List[str] = []
        self.limit = limit

    def start(self, task_id: str, source: str,
              trace_token: Optional[str] = None) -> TaskEntry:
        e = TaskEntry(task_id, source, trace_token)
        with self._lock:
            if task_id not in self._entries:
                self._order.append(task_id)
            self._entries[task_id] = e
            while len(self._order) > self.limit:
                self._entries.pop(self._order.pop(0), None)
        METRICS.counter("tasks.started").inc()
        return e

    def finish(self, task_id: str, state: str = "FINISHED",
               rows: Optional[int] = None,
               error: Optional[str] = None) -> None:
        with self._lock:
            e = self._entries.get(task_id)
            if e is None:
                return
            e.state = state
            e.elapsed_ms = round((time.perf_counter() - e._t0) * 1e3, 3)
            e.rows = rows
            e.error = error
        counter = {"FINISHED": "tasks.finished",
                   "ABORTED": "tasks.aborted"}.get(state, "tasks.failed")
        METRICS.counter(counter).inc()

    def update_scheduler(self, task_id: str, splits: int, concurrency: int,
                         stall_ms: float, prefetch_hits: int) -> None:
        """Attach the split-scheduler footprint of a finished (or
        running) execution to its task row — the system_runtime_tasks
        surface of the morsel scheduler."""
        with self._lock:
            e = self._entries.get(task_id)
            if e is None:
                return
            e.splits = int(splits)
            e.concurrency = int(concurrency)
            e.stall_ms = round(float(stall_ms), 3)
            e.prefetch_hits = int(prefetch_hits)

    def entries(self) -> List[TaskEntry]:
        with self._lock:
            return [self._entries[t] for t in self._order]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._order.clear()


#: process-wide task view (system_runtime_tasks reads it)
TASKS = TaskRegistry()
