"""Engine observability: spans + metrics + exports (the telemetry
spine; docs/observability.md).

``obs`` sits below every execution layer and imports none of them —
runner/executor/server/parallel all instrument through this package,
so it must stay dependency-free (events.py only).
"""

from presto_tpu.obs.metrics import METRICS, TASKS, MetricsRegistry, TaskRegistry
from presto_tpu.obs.trace import (
    NULL_SPAN,
    Tracer,
    current_tracer,
    lookup,
    register,
    span,
    tracer_for,
    tracing,
)
from presto_tpu.obs.export import (
    QueryLogListener,
    chrome_trace,
    maybe_enable_trace_dir,
    maybe_write_trace,
    set_trace_dir,
    trace_dir,
    write_trace,
)
from presto_tpu.obs import openmetrics
from presto_tpu.obs.progress import (
    QueryProgress,
    StageProgress,
    current_progress,
    progress_for,
    publishing,
    register_progress,
)
from presto_tpu.obs.timeseries import (
    HISTORY,
    MetricsHistory,
    QueryTimeline,
    current_timeline,
    ensure_timeline,
    record_point,
    recording,
    register_timeline,
    timeline_for,
)
from presto_tpu.obs import doctor
from presto_tpu.obs.history import (
    HistoricalStatsProvider,
    PlanHistoryStore,
    default_history,
    set_default_history,
)

__all__ = [
    "HistoricalStatsProvider", "PlanHistoryStore", "default_history",
    "set_default_history",
    "METRICS", "TASKS", "MetricsRegistry", "TaskRegistry",
    "NULL_SPAN", "Tracer", "current_tracer", "lookup", "register",
    "span", "tracer_for", "tracing",
    "QueryLogListener", "chrome_trace", "maybe_enable_trace_dir",
    "maybe_write_trace", "set_trace_dir", "trace_dir", "write_trace",
    "openmetrics",
    "QueryProgress", "StageProgress", "current_progress", "progress_for",
    "publishing", "register_progress",
    "HISTORY", "MetricsHistory", "QueryTimeline", "current_timeline",
    "ensure_timeline", "record_point", "recording", "register_timeline",
    "timeline_for", "doctor",
]
