"""Query doctor: post-query bottleneck diagnosis from retained
telemetry.

Consumes the trace registry (obs/trace.py), the per-query timeline +
annotations (obs/timeseries.py) and the progress table, and emits
RANKED, evidence-carrying findings from a fixed rulebook — nothing
heuristic is free-floating: every finding names its rule, its score,
and the numbers that fired it, so "why was this query slow" is
answerable from retained telemetry alone (the reference's
QueryStats-driven postmortems, automated).

The rulebook (thresholds are module constants, documented in
docs/observability.md):

========================  ==================================================
rule                      fires when
========================  ==================================================
``compile-bound``         xla_compile span share of wall >= 25%
``queue-bound``           admission wait >= 50% of wall (and >= 10ms)
``memory-blocked``        headroom stall >= 25% of wall (and >= 10ms)
``spill-bound``           spill bytes >= 25% of input bytes (or any spill
                          when input is unknown)
``exchange-backpressure`` producer stall share of wall >= 20%
``skewed-stage``          per-partition rows max/median >= 4x (max >= 64)
``straggler-worker``      per-fragment worker time max/median >= 3x
                          (max >= 50ms, >= 2 workers)
``scan-bound``            ``*:split`` span share of wall >= 50%
``fallback-taken``        the distributed tier fell back to the
                          coordinator (dist_fallback reason present)
``misestimate``           worst estimate-vs-actual node ratio >= 8x
                          (the ``worst_estimate`` timeline annotation)
========================  ==================================================

Scores are comparable severities in [0, 1]; findings sort by score so
the injected dominant cause of a run ranks first (tests pin each rule
that way).  All inputs are read-only registry lookups — diagnosing a
finished query costs microseconds and touches no execution state.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional

from presto_tpu.obs import trace as _trace
from presto_tpu.obs import timeseries as _timeseries
from presto_tpu.obs import progress as _progress

#: rulebook thresholds (docs/observability.md documents each)
COMPILE_SHARE = 0.25
QUEUE_SHARE = 0.50
QUEUE_MIN_MS = 10.0
MEMORY_SHARE = 0.25
MEMORY_MIN_MS = 10.0
SPILL_INPUT_SHARE = 0.25
STALL_SHARE = 0.20
SKEW_RATIO = 4.0
SKEW_MIN_ROWS = 64
STRAGGLER_RATIO = 3.0
STRAGGLER_MIN_MS = 50.0
SCAN_SHARE = 0.50
FALLBACK_SCORE = 0.95
MISESTIMATE_RATIO = 8.0


class Finding:
    """One diagnosis: rule name, severity score, a human summary, and
    the evidence numbers that fired it."""

    __slots__ = ("rule", "score", "summary", "evidence")

    def __init__(self, rule: str, score: float, summary: str,
                 evidence: Dict[str, object]):
        self.rule = rule
        self.score = max(0.0, min(1.0, float(score)))
        self.summary = summary
        self.evidence = evidence

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "score": round(self.score, 3),
            "summary": self.summary,
            "evidence": self.evidence,
        }

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Finding({self.rule!r}, {self.score:.2f})"


def _share(part_ms: float, wall_ms: float) -> float:
    return part_ms / wall_ms if wall_ms > 0 else 0.0


def diagnose(
    query_id: Optional[str] = None,
    *,
    tracer=None,
    timeline=None,
    progress=None,
    wall_ms: Optional[float] = None,
    dist_fallback: Optional[str] = None,
) -> List[Finding]:
    """Run the rulebook over whatever telemetry exists for the query.
    Explicit objects win; otherwise the registries are consulted by
    ``query_id``.  Rules whose evidence source is absent stay silent —
    a traceless query can still be diagnosed from its timeline and
    vice versa."""
    if tracer is None and query_id:
        tracer = _trace.lookup(query_id)
    if timeline is None and query_id:
        timeline = _timeseries.timeline_for(query_id)
    if progress is None and query_id:
        progress = _progress.progress_for(query_id)

    ann: Dict[str, object] = timeline.annotations() if timeline is not None \
        else {}
    if dist_fallback is None:
        dist_fallback = ann.get("dist_fallback")

    span_summary: Dict[str, Dict[str, float]] = (
        tracer.summary() if tracer is not None else {})
    if wall_ms is None:
        w = ann.get("wall_ms")
        if w is not None:
            wall_ms = float(w)
        elif "query" in span_summary:
            wall_ms = span_summary["query"]["total_ms"]
        elif "execute" in span_summary:
            wall_ms = (span_summary["execute"]["total_ms"]
                       + span_summary.get("plan", {}).get("total_ms", 0.0))
    wall_ms = float(wall_ms or 0.0)

    findings: List[Finding] = []

    # -- compile-bound --------------------------------------------------
    compile_ms = span_summary.get("xla_compile", {}).get("total_ms", 0.0)
    share = _share(compile_ms, wall_ms)
    if share >= COMPILE_SHARE:
        findings.append(Finding(
            "compile-bound", share,
            f"XLA compilation took {compile_ms:.0f}ms of {wall_ms:.0f}ms "
            f"wall ({share:.0%}) — warm the program registry or enable "
            "the persistent cache",
            {"compile_ms": round(compile_ms, 3),
             "wall_ms": round(wall_ms, 3), "share": round(share, 3),
             "compiles": span_summary.get("xla_compile", {}).get("count", 0)},
        ))

    # -- queue-bound ----------------------------------------------------
    queued_ms = float(ann.get("queued_ms") or 0.0)
    if queued_ms >= QUEUE_MIN_MS and queued_ms >= QUEUE_SHARE * wall_ms:
        findings.append(Finding(
            "queue-bound", queued_ms / (queued_ms + wall_ms)
            if (queued_ms + wall_ms) > 0 else 0.0,
            f"spent {queued_ms:.0f}ms in the admission queue vs "
            f"{wall_ms:.0f}ms executing — raise admission concurrency or "
            "spread the burst",
            {"queued_ms": round(queued_ms, 3),
             "wall_ms": round(wall_ms, 3)},
        ))

    # -- memory-blocked -------------------------------------------------
    blocked_ms = float(ann.get("memory_blocked_ms") or 0.0)
    if blocked_ms >= MEMORY_MIN_MS and blocked_ms >= MEMORY_SHARE * wall_ms:
        findings.append(Finding(
            "memory-blocked", min(1.0, _share(blocked_ms, wall_ms)),
            f"blocked {blocked_ms:.0f}ms waiting for memory headroom — "
            "lower concurrency or grow the pool",
            {"memory_blocked_ms": round(blocked_ms, 3),
             "wall_ms": round(wall_ms, 3)},
        ))

    # -- spill-bound ----------------------------------------------------
    spill_bytes = float(ann.get("spill_bytes") or 0.0)
    input_bytes = float(ann.get("input_bytes") or 0.0)
    if input_bytes <= 0 and progress is not None:
        input_bytes = float(sum(
            s.get("bytes") or 0 for s in progress.snapshot()["stages"]))
    if spill_bytes > 0 and (
            input_bytes <= 0
            or spill_bytes >= SPILL_INPUT_SHARE * input_bytes):
        ratio = spill_bytes / max(input_bytes, spill_bytes)
        findings.append(Finding(
            "spill-bound", ratio,
            f"spilled {spill_bytes / 1e6:.1f}MB "
            f"({ratio:.0%} of input) to host RAM — the working set "
            "exceeds the pool; grow the limit or reduce concurrency",
            {"spill_bytes": spill_bytes, "input_bytes": input_bytes,
             "ratio": round(ratio, 3)},
        ))

    # -- exchange-backpressure -------------------------------------------
    stall_ms = float(ann.get("exchange_producer_stall_s") or 0.0) * 1e3
    share = _share(stall_ms, wall_ms)
    if share >= STALL_SHARE:
        findings.append(Finding(
            "exchange-backpressure", min(1.0, share),
            f"producers stalled {stall_ms:.0f}ms on the exchange byte cap "
            f"({share:.0%} of wall) — the consumer lags; raise "
            "exchange_buffer_bytes or speed the consuming stage",
            {"producer_stall_ms": round(stall_ms, 3),
             "wall_ms": round(wall_ms, 3), "share": round(share, 3)},
        ))

    # -- skewed-stage ----------------------------------------------------
    partition_rows = ann.get("partition_rows") or {}
    worst = None  # (ratio, stage, mx, med)
    for stage, series in partition_rows.items():
        counts: List[float] = []
        for entry in series:
            counts.extend(float(c) for c in entry)
        live = [c for c in counts if c >= 0]
        if len(live) < 2 or not any(live):
            continue
        mx = max(live)
        med = statistics.median(live)
        ratio = mx / max(med, 1.0)
        if mx >= SKEW_MIN_ROWS and ratio >= SKEW_RATIO:
            if worst is None or ratio > worst[0]:
                worst = (ratio, stage, mx, med)
    if worst is not None:
        ratio, stage, mx, med = worst
        findings.append(Finding(
            "skewed-stage", min(1.0, ratio / (4 * SKEW_RATIO)),
            f"stage {stage} is skewed: busiest partition holds {mx:.0f} "
            f"rows vs median {med:.0f} ({ratio:.1f}x) — a hot key "
            "serializes the stage on one device",
            {"stage": stage, "max_rows": mx, "median_rows": med,
             "ratio": round(ratio, 2)},
        ))

    # -- straggler-worker -------------------------------------------------
    fragment_ms = ann.get("fragment_ms") or {}
    totals = {w: float(sum(v)) for w, v in fragment_ms.items() if v}
    if len(totals) >= 2:
        mx_worker = max(totals, key=totals.get)
        mx = totals[mx_worker]
        med = statistics.median(totals.values())
        ratio = mx / max(med, 1e-9)
        if mx >= STRAGGLER_MIN_MS and ratio >= STRAGGLER_RATIO:
            findings.append(Finding(
                "straggler-worker", min(1.0, ratio / (4 * STRAGGLER_RATIO)),
                f"worker {mx_worker} took {mx:.0f}ms vs median "
                f"{med:.0f}ms ({ratio:.1f}x) — a straggler gates the "
                "stage; see docs/fault-tolerance.md (speculation)",
                {"worker": mx_worker, "max_ms": round(mx, 3),
                 "median_ms": round(med, 3), "ratio": round(ratio, 2),
                 "per_worker_ms": {w: round(v, 3)
                                   for w, v in totals.items()}},
            ))

    # -- scan-bound -------------------------------------------------------
    split_ms = sum(e["total_ms"] for name, e in span_summary.items()
                   if name.endswith(":split"))
    share = _share(split_ms, wall_ms)
    if share >= SCAN_SHARE:
        findings.append(Finding(
            "scan-bound", min(0.9, share),
            f"split execution took {split_ms:.0f}ms of {wall_ms:.0f}ms "
            f"wall ({share:.0%}) — the query is scan-dominated; raise "
            "task concurrency/prefetch or prune with predicates",
            {"split_ms": round(split_ms, 3), "wall_ms": round(wall_ms, 3),
             "share": round(share, 3)},
        ))

    # -- misestimate ------------------------------------------------------
    we = ann.get("worst_estimate") or {}
    ratio = float(we.get("ratio") or 0.0)
    if ratio >= MISESTIMATE_RATIO:
        findings.append(Finding(
            "misestimate", min(1.0, ratio / (4 * MISESTIMATE_RATIO)),
            f"planner misestimated {we.get('node')}: est "
            f"{float(we.get('est') or 0):.0f} rows vs actual "
            f"{int(we.get('actual') or 0)} ({ratio:.1f}x) — consider "
            "SET SESSION feedback_stats = true or fresher table stats",
            {"node": we.get("node"), "est_rows": we.get("est"),
             "actual_rows": we.get("actual"), "ratio": round(ratio, 2)},
        ))

    # -- fallback-taken ---------------------------------------------------
    if dist_fallback:
        findings.append(Finding(
            "fallback-taken", FALLBACK_SCORE,
            "distributed execution fell back to the coordinator: "
            f"{dist_fallback}",
            {"reason": str(dist_fallback)},
        ))

    findings.sort(key=lambda f: f.score, reverse=True)
    return findings


def report(query_id: str) -> Dict[str, object]:
    """The ``/v1/query/<id>/doctor`` body: findings stored at query
    completion when present (the runner annotates them), else a fresh
    diagnosis from whatever the registries still hold."""
    timeline = _timeseries.timeline_for(query_id)
    stored = timeline.annotation("findings") if timeline is not None else None
    if stored is not None:
        return {"queryId": query_id, "findings": stored}
    return {"queryId": query_id,
            "findings": [f.as_dict() for f in diagnose(query_id)]}


def format_findings(findings: List[Dict[str, object]],
                    indent: str = "  ") -> str:
    """The human rendering shared by EXPLAIN ANALYZE VERBOSE's
    ``diagnosis:`` block and the CLI ``--doctor`` flag."""
    if not findings:
        return "diagnosis: no findings (nothing crossed a threshold)"
    lines = ["diagnosis:"]
    for i, f in enumerate(findings, 1):
        d = f.as_dict() if isinstance(f, Finding) else f
        lines.append(f"{indent}{i}. {d['rule']} "
                     f"(score {d['score']:.2f}): {d['summary']}")
    return "\n".join(lines)
