"""OpenMetrics text exposition for the process metrics registry.

Reference analogs: the Prometheus/OpenMetrics pull model the reference
exposes through its JMX exporter sidecars, and the collection design of
Google's Monarch (pull exposition + fixed-bucket distributions so the
collection path never allocates per label).  ``render()`` turns
``obs.METRICS`` into spec-valid OpenMetrics 1.0 text:

- catalog names are dotted (``query.started``); exposition names map
  ``[^a-zA-Z0-9_:]`` to ``_`` (``query_started``),
- counters expose as ``<name>_total``,
- the log2 histograms expose as CUMULATIVE ``_bucket{le="2^k"}`` series
  plus ``_sum``/``_count`` (the last bucket is clamped at 2^31, so the
  final finite ``le`` equals ``_count`` and ``+Inf`` adds nothing new —
  monotonicity holds by construction),
- the body ends with ``# EOF`` as the spec requires.

``json_form()`` is the machine-to-machine twin: the coordinator polls
it from every worker (``GET /v1/metrics?format=json``) to grow
``system_metrics`` a ``node`` column with a cluster-wide rollup.

This module must stay importable from anywhere (obs is the bottom of
the dependency stack): it imports only the sibling registry.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Tuple

from presto_tpu.obs.metrics import METRICS, Histogram, MetricsRegistry

#: the content type OpenMetrics scrapers negotiate
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(name: str) -> str:
    """Catalog name -> OpenMetrics metric name (``query.started`` ->
    ``query_started``); a leading digit gets an underscore prefix."""
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    """Label-value escaping per the spec: backslash, double-quote and
    newline must be escaped inside the quotes."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return format(float(v), ".10g")


def render(registry: MetricsRegistry = None) -> str:
    """The OpenMetrics text body for ``GET /v1/metrics``."""
    reg = registry if registry is not None else METRICS
    ex = reg.export()
    lines: List[str] = []
    for name in sorted(ex["counters"]):
        # family names must not carry the reserved _total suffix; the
        # catalog's *_seconds_total style names keep their sample name
        # (family query_planning_seconds -> sample ..._seconds_total)
        m = metric_name(name)
        if m.endswith("_total"):
            m = m[: -len("_total")]
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}_total {_fmt(ex['counters'][name])}")
    for name in sorted(ex["gauges"]):
        m = metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt(ex['gauges'][name])}")
    for name in sorted(ex["histograms"]):
        h = ex["histograms"][name]
        m = metric_name(name)
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for k, n in enumerate(h["buckets"]):
            cum += n
            lines.append(f'{m}_bucket{{le="{1 << k}"}} {cum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{m}_sum {_fmt(h['sum'])}")
        lines.append(f"{m}_count {h['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def json_form(node: str, registry: MetricsRegistry = None) -> Dict:
    """The poll form the coordinator aggregates: flat (name, value)
    rows in the system_metrics dialect, stamped with this node's id."""
    reg = registry if registry is not None else METRICS
    return {"node": node, "metrics": [[n, float(v)]
                                      for n, v in reg.snapshot()]}


def merge_rows(
    per_node: Dict[str, List[Tuple[str, float]]]
) -> List[Tuple[str, float]]:
    """Cluster rollup: sum each metric over the nodes (counters and
    histogram rows sum exactly; gauge sums read as cluster totals —
    e.g. total reserved HBM)."""
    total: Dict[str, float] = {}
    for rows in per_node.values():
        for name, value in rows:
            v = float(value)
            if math.isnan(v):
                continue  # an unwired gauge must not poison the sum
            total[name] = total.get(name, 0.0) + v
    return sorted(total.items())
