"""Plan-history store: observed per-operator actuals retained ACROSS
queries, keyed by the stable structural node signature (exec/programs.
structural_digest) — the persistence half of the estimate-vs-actual
plane (docs/observability.md "Estimate vs actual").

Reference analog: the historical stats feeding presto-main's
HistoryBasedPlanStatisticsProvider — observed cardinalities beat
textbook selectivity rules whenever a structurally identical node ran
before.

Persistence follows the warehouse metastore idiom
(storage/warehouse.py): one JSON file under the warehouse root,
replaced atomically (tmp + ``os.replace``), carrying a uuid
incarnation that survives coordinator restarts plus a monotonic
version bumped on every save.  A store without a path is purely
in-memory (unit tests, catalogs without a warehouse).

Layering: ``obs`` stays import-time independent of the execution
layers — the structural digest is resolved lazily inside the methods
that need it.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional

_FILE = "_plan_history.json"

#: entries kept per store — LRU by update sequence, like the
#: dictionary-token table in exec/programs.py
DEFAULT_LIMIT = 4096

#: observations of a signature required before the provider trusts it
MIN_OBSERVATIONS = 1


class PlanHistoryStore:
    """Bounded per-warehouse map ``(node type, structural digest) ->
    observed row counts / estimate ratios / peak bytes``."""

    def __init__(self, path: Optional[str] = None,
                 limit: int = DEFAULT_LIMIT):
        self.path = path
        self.limit = limit
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._seq = 0
        self.incarnation = uuid.uuid4().hex[:12]
        self.version = 0
        if path is not None and os.path.exists(path):
            self._load()

    # -- persistence --------------------------------------------------------
    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # a corrupt store starts fresh, never fails a query
        if not isinstance(doc, dict):
            return
        self.incarnation = str(doc.get("incarnation") or self.incarnation)
        self.version = int(doc.get("version") or 0)
        ents = doc.get("entries")
        if isinstance(ents, dict):
            self._entries = {str(k): dict(v) for k, v in ents.items()
                             if isinstance(v, dict)}
            self._seq = max(
                (int(e.get("seq", 0)) for e in self._entries.values()),
                default=0)

    def save(self) -> None:
        if self.path is None:
            return
        with self._lock:
            self.version += 1
            doc = {"incarnation": self.incarnation, "version": self.version,
                   "entries": self._entries}
            tmp = self.path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f, sort_keys=True)
                os.replace(tmp, self.path)  # atomic publish
            except OSError:
                pass  # read-only roots degrade to in-memory behavior

    # -- writes -------------------------------------------------------------
    def observe(self, node_type: str, digest: str, rows: int,
                est_rows: Optional[float] = None,
                peak_bytes: int = 0) -> None:
        """One finished node observation.  Running mean + last value;
        the ratio keeps misestimate attribution queryable later."""
        key = f"{node_type}:{digest}"
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = {"node": node_type, "digest": digest, "n": 0,
                     "rows_mean": 0.0, "rows_last": 0, "est_last": None,
                     "ratio_last": None, "peak_bytes_max": 0, "seq": 0,
                     "updated_ms": 0.0}
                self._entries[key] = e
            n = int(e["n"]) + 1
            e["n"] = n
            e["rows_mean"] = (float(e["rows_mean"]) * (n - 1) + rows) / n
            e["rows_last"] = int(rows)
            if est_rows is not None:
                e["est_last"] = float(est_rows)
                e["ratio_last"] = estimate_ratio(est_rows, rows)
            e["peak_bytes_max"] = max(int(e.get("peak_bytes_max", 0)),
                                      int(peak_bytes))
            self._seq += 1
            e["seq"] = self._seq
            e["updated_ms"] = time.time() * 1e3
            while len(self._entries) > self.limit:
                oldest = min(self._entries,
                             key=lambda k: self._entries[k]["seq"])
                self._entries.pop(oldest)

    def record_query(self, stats, estimates: Optional[dict] = None,
                     save: bool = True) -> None:
        """Fold a finished query's ``QueryStats`` (and its bind-time
        estimate map, when the plan carried one) into the store."""
        estimates = estimates or {}
        for (sig, occ), s in list(stats.by_key.items()):
            if not s.get("invocations"):
                continue
            node_type, digest = sig
            if node_type in ("PrecomputedNode", "ValuesNode"):
                # their stable digests exclude the payload, so every
                # instance would alias one entry — no planning value
                continue
            est = (estimates.get((sig, occ)) or {}).get("rows")
            self.observe(node_type, str(digest), int(s["rows"]),
                         est_rows=est, peak_bytes=int(s.get("bytes", 0)))
        if save:
            self.save()

    # -- reads --------------------------------------------------------------
    def observed_rows(self, node_type: str, digest: str) -> Optional[float]:
        e = self._entries.get(f"{node_type}:{digest}")
        if e is None or int(e.get("n", 0)) < MIN_OBSERVATIONS:
            return None
        return float(e["rows_mean"])

    def rows(self) -> List[dict]:
        """Snapshot for the ``system_plan_history`` table."""
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def __len__(self) -> int:
        return len(self._entries)


class HistoricalStatsProvider:
    """The planner-facing read adapter ``planner/stats.py`` consults
    behind the ``feedback_stats`` session property: observed mean rows
    for a structurally matching node, or None to keep the textbook
    estimate."""

    def __init__(self, store: PlanHistoryStore):
        self.store = store

    def observed_rows(self, node) -> Optional[float]:
        from presto_tpu.exec.programs import structural_digest

        name = type(node).__name__
        if name in ("PrecomputedNode", "ValuesNode", "OutputNode"):
            return None  # exact or payload-blind digests — never override
        return self.store.observed_rows(name, structural_digest(node))


def estimate_ratio(est: Optional[float], actual: int) -> Optional[float]:
    """Misestimate factor ≥1.0, direction-free: max(actual/est,
    est/actual) with both sides floored at one row so an estimated-0 /
    actual-0 node never divides by zero."""
    if est is None:
        return None
    e = max(float(est), 1.0)
    a = max(float(actual), 1.0)
    return max(a / e, e / a)


def operator_rows(stats, estimates: Optional[dict]) -> List[dict]:
    """Per-operator est/actual rows for a finished query — the web
    UI's detail table and the ``/v1/query/<id>/operators`` endpoint
    (annotated onto the timeline as ``operators``)."""
    estimates = estimates or {}
    rows = []
    for (sig, occ), s in sorted(stats.by_key.items(),
                                key=lambda kv: (kv[0][0][0], kv[0][1])):
        if not s.get("invocations"):
            continue
        est = (estimates.get((sig, occ)) or {}).get("rows")
        rows.append({
            "node": sig[0], "occ": int(occ),
            "rows": int(s["rows"]), "pages": int(s["invocations"]),
            "wall_ms": round(float(s["wall_s"]) * 1e3, 3),
            "bytes": int(s.get("bytes", 0)),
            "est_rows": None if est is None else float(est),
            "ratio": estimate_ratio(est, int(s["rows"])),
        })
    return rows


def worst_estimate(stats, estimates: Optional[dict]) -> Optional[dict]:
    """The worst estimate-vs-actual node of a finished query:
    ``{"ratio", "node", "est", "actual"}`` over a QueryStats + the
    plan's bind-time estimate map, or None when nothing is comparable.
    Feeds the timeline annotation the doctor's ``misestimate`` rule
    reads, the query-log completion line, and QueryCompletedEvent."""
    if estimates is None:
        return None
    worst = None
    for (sig, occ), s in list(stats.by_key.items()):
        if not s.get("invocations"):
            continue
        est = (estimates.get((sig, occ)) or {}).get("rows")
        ratio = estimate_ratio(est, int(s["rows"]))
        if ratio is None:
            continue
        if worst is None or ratio > worst["ratio"]:
            worst = {"ratio": float(ratio), "node": sig[0],
                     "est": float(est), "actual": int(s["rows"])}
    return worst


# -- process default (the coordinator's store) ------------------------------
_DEFAULT: Optional[PlanHistoryStore] = None
_DEFAULT_LOCK = threading.Lock()


def default_history() -> PlanHistoryStore:
    """The process-wide store.  A warehouse-backed runner replaces it
    with a persisted one (set_default_history); otherwise an in-memory
    store materializes on first use so ``feedback_stats`` works on any
    catalog."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = PlanHistoryStore()
        return _DEFAULT


def set_default_history(store: Optional[PlanHistoryStore]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = store


def ensure_default_history(path: str) -> PlanHistoryStore:
    """Install a persisted store at ``path`` unless one is already the
    default — re-building a QueryRunner over the same warehouse must
    not discard accumulated in-memory observations."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT.path != path:
            _DEFAULT = PlanHistoryStore(path)
        return _DEFAULT


def history_path(warehouse_root: str) -> str:
    return os.path.join(warehouse_root, _FILE)
