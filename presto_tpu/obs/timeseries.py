"""Telemetry history: a bounded metrics ring + per-query resource
timelines.

Two retention planes, both bounded (a serving process must never grow
telemetry without limit):

1. :class:`MetricsHistory` — a process-wide ring of catalog samples.
   A named daemon thread wakes every ``PRESTO_TPU_METRICS_HISTORY_MS``
   (0 = off; the servers arm a 1s default when unset) and records one
   *tick*: every gauge's value, every counter's per-second rate since
   the previous tick, and every histogram's observation rate plus its
   current p50/p95/p99 (derived from the log2 buckets).  The ring keeps
   the last ``PRESTO_TPU_METRICS_HISTORY_TICKS`` ticks — retention is
   ``ticks x cadence`` (~8.5 min at defaults).  Exposed as the
   ``system_metrics_history`` table and ``GET /v1/metrics/history``.

   Prometheus-vs-history tradeoff: a scraper owns long-term storage;
   the ring exists so a cluster WITHOUT external scraping can still
   answer "what did queue depth / buffered bytes look like over the
   last few minutes" — the autoscale + doctor input — from the process
   itself.  Because names come from the live registry, the engine-lint
   metric-catalog rule covers everything the ring samples by
   construction; derived suffixes (``.rate``, ``.p50``...) are
   computed, never free-hand literals.

2. :class:`QueryTimeline` — one bounded per-query buffer of
   ``(ts_ms, metric, value)`` points appended by the runner/exec/
   parallel hot paths (memory reservation, exchange buffered bytes,
   splits done per stage, device dispatches, admission queue depth),
   plus an ``annotations`` dict of per-query scalars the doctor
   consumes (queued/memory-blocked ms, spill bytes, producer stall,
   per-partition row counts, per-worker fragment durations, findings).
   Registry + thread-local activation mirror obs/progress.py exactly;
   the disabled fast path is ONE thread-local read returning ``None``
   (:func:`record_point` costs a getattr and a branch when no timeline
   is active — the "no measurable overhead when disabled" contract).

Like the rest of ``obs``, this module sits below every execution layer
and imports none of them.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from presto_tpu.envflag import EnvFlag, EnvInt
from presto_tpu.sync import named_lock

#: sampler cadence in ms; 0 disables.  Servers pass ``default_ms=1000``
#: to ``HISTORY.start`` so history is on in serving processes unless
#: the environment explicitly set 0.
metrics_history_ms = EnvInt("PRESTO_TPU_METRICS_HISTORY_MS", 0, floor=0)
#: ring length in ticks (bounds retained memory: ticks x rows/tick)
metrics_history_ticks = EnvInt(
    "PRESTO_TPU_METRICS_HISTORY_TICKS", 512, floor=8)
#: per-query timeline point cap (deque maxlen; oldest points evict)
timeline_points_max = EnvInt("PRESTO_TPU_TIMELINE_POINTS", 2048, floor=64)
#: master switch for per-query timelines — when off, ``ensure_timeline``
#: returns None, nothing registers, and every hot-path hook falls
#: through its single None check
timelines_enabled = EnvFlag("PRESTO_TPU_QUERY_TIMELINES", True)


# ---------------------------------------------------------------------------
# process-wide metrics history ring
# ---------------------------------------------------------------------------


class MetricsHistory:
    """Bounded ring of metrics-catalog samples (see module doc)."""

    def __init__(self, registry=None, max_ticks: Optional[int] = None):
        self._registry = registry
        self._lock = named_lock("timeseries.MetricsHistory._lock")
        self._ticks: "collections.deque" = collections.deque(
            maxlen=max_ticks or metrics_history_ticks())
        # (perf_counter, counter values, histogram counts) of the last
        # tick — rates are deltas against it (perf_counter based:
        # durations never mix with wall-clock)
        self._prev: Optional[Tuple[float, Dict[str, float],
                                   Dict[str, int]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.interval_ms = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from presto_tpu.obs.metrics import METRICS

        return METRICS

    # -- sampling -------------------------------------------------------
    def sample_once(self) -> int:
        """Record one tick; returns the number of rows sampled."""
        from presto_tpu.obs.metrics import bucket_percentiles

        ex = self._reg().export()
        now_pc = time.perf_counter()
        ts_ms = time.time() * 1e3  # epoch stamp (standalone, no deltas)
        rows: List[Tuple[str, float]] = []
        for name, value in ex["gauges"].items():
            v = float(value)
            if v == v:  # an unwired gauge's NaN must not enter the ring
                rows.append((name, v))
        counters = {n: float(v) for n, v in ex["counters"].items()}
        hist_counts = {n: int(h["count"])
                       for n, h in ex["histograms"].items()}
        for name, h in ex["histograms"].items():
            if h["count"]:
                for pname, pv in bucket_percentiles(
                        h["buckets"], h["count"]).items():
                    rows.append((f"{name}.{pname}", pv))
        # prev + ticks under one lock: sample_once may be driven from
        # both the sampler thread and callers (tests, a manual tick)
        with self._lock:
            prev = self._prev
            if prev is not None:
                t_prev, prev_counters, prev_hists = prev
                dt = max(now_pc - t_prev, 1e-9)
                for name, v in counters.items():
                    rows.append(
                        (name + ".rate",
                         max(0.0, v - prev_counters.get(name, 0.0)) / dt))
                for name, c in hist_counts.items():
                    rows.append(
                        (name + ".count.rate",
                         max(0, c - prev_hists.get(name, 0)) / dt))
            self._prev = (now_pc, counters, hist_counts)
            self._ticks.append((ts_ms, rows))
        return len(rows)

    # -- sampler lifecycle ---------------------------------------------
    def start(self, interval_ms: Optional[int] = None,
              default_ms: int = 0) -> bool:
        """Arm the sampler.  Explicit ``interval_ms`` wins; otherwise
        the env knob; otherwise ``default_ms`` (servers pass 1000).
        Returns whether a sampler is running after the call."""
        ms = interval_ms if interval_ms is not None \
            else (metrics_history_ms() or default_ms)
        with self._lock:
            if self._thread is not None:
                return True
            if ms <= 0:
                return False
            self.interval_ms = int(ms)
            self._stop = threading.Event()
            stop = self._stop
            t = threading.Thread(
                target=self._run, args=(stop, ms / 1e3),
                name="obs-history-sampler", daemon=True)
            self._thread = t
        t.start()
        return True

    def _run(self, stop: threading.Event, interval_s: float) -> None:
        while True:
            try:
                self.sample_once()
            except Exception:
                # a mid-shutdown registry hiccup must not kill the
                # sampler; the next tick retries
                pass  # noqa: S110 - sampling is best-effort
            if stop.wait(interval_s):
                return

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
            stop = self._stop
        stop.set()
        if t is not None:
            t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    # -- readers --------------------------------------------------------
    def rows(self) -> List[Tuple[float, str, float]]:
        """Flattened (ts_ms, name, value) rows, oldest tick first —
        the ``system_metrics_history`` table and the history endpoint
        read exactly this shape."""
        with self._lock:
            ticks = list(self._ticks)
        out: List[Tuple[float, str, float]] = []
        for ts_ms, rows in ticks:
            out.extend((ts_ms, name, value) for name, value in rows)
        return out

    def tick_count(self) -> int:
        with self._lock:
            return len(self._ticks)

    def clear(self) -> None:
        with self._lock:
            self._ticks.clear()
            self._prev = None


#: the process-wide history ring (servers arm its sampler; the
#: system_metrics_history table reads it)
HISTORY = MetricsHistory()


# ---------------------------------------------------------------------------
# per-query resource timelines
# ---------------------------------------------------------------------------


class QueryTimeline:
    """One query's bounded (ts_ms, metric, value) buffer + the
    annotation dict shared by admission, exec and the doctor.
    Timestamps are ms since the timeline's creation (perf_counter
    deltas — durations, never wall-clock)."""

    __slots__ = ("query_id", "t0", "dropped", "max_points", "_points",
                 "_ann", "_lock")

    def __init__(self, query_id: str, max_points: Optional[int] = None):
        self.query_id = query_id
        self.t0 = time.perf_counter()
        self.max_points = max_points or timeline_points_max()
        self.dropped = 0
        self._points: "collections.deque" = collections.deque(
            maxlen=self.max_points)
        self._ann: Dict[str, object] = {}
        self._lock = named_lock("timeseries.QueryTimeline._lock")

    # -- writers --------------------------------------------------------
    def record(self, name: str, value: float) -> None:
        ts_ms = (time.perf_counter() - self.t0) * 1e3
        with self._lock:
            if len(self._points) == self.max_points:
                self.dropped += 1  # the deque evicts the oldest point
            self._points.append((ts_ms, name, float(value)))

    def annotate(self, key: str, value) -> None:
        with self._lock:
            self._ann[key] = value

    def bump(self, key: str, delta: float) -> float:
        """Additive annotation (stall seconds, spill bytes...)."""
        with self._lock:
            v = float(self._ann.get(key, 0.0)) + float(delta)
            self._ann[key] = v
            return v

    def extend(self, key: str, subkey: str, value) -> None:
        """Append ``value`` to ``annotations[key][subkey]`` (per-stage
        partition counts, per-worker fragment durations...)."""
        with self._lock:
            series = self._ann.setdefault(key, {})
            series.setdefault(subkey, []).append(value)

    # -- readers --------------------------------------------------------
    def annotation(self, key: str, default=None):
        with self._lock:
            return self._ann.get(key, default)

    def annotations(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._ann)

    def points(self) -> List[Tuple[float, str, float]]:
        with self._lock:
            return list(self._points)

    def snapshot(self) -> Dict:
        with self._lock:
            pts = [[round(ts, 3), name, value]
                   for ts, name, value in self._points]
            ann = dict(self._ann)
            dropped = self.dropped
        return {
            "queryId": self.query_id,
            "points": pts,
            "dropped": dropped,
            "annotations": ann,
        }


# ---------------------------------------------------------------------------
# process registry + thread-local activation (mirrors obs/progress.py)
# ---------------------------------------------------------------------------

_REGISTRY_MAX = 256
_REGISTRY: "collections.OrderedDict[str, QueryTimeline]" = (
    collections.OrderedDict())
_REGISTRY_LOCK = named_lock("timeseries._REGISTRY_LOCK")

_ACTIVE = threading.local()


def register_timeline(timeline: QueryTimeline) -> QueryTimeline:
    with _REGISTRY_LOCK:
        _REGISTRY[timeline.query_id] = timeline
        _REGISTRY.move_to_end(timeline.query_id)
        while len(_REGISTRY) > _REGISTRY_MAX:
            _REGISTRY.popitem(last=False)
    return timeline


def ensure_timeline(query_id: Optional[str]) -> Optional[QueryTimeline]:
    """Get-or-create the timeline for ``query_id`` (admission runs
    before the runner registers one, so both share this entry point).
    Returns ``None`` when timelines are disabled or the id is empty."""
    if not query_id or not timelines_enabled():
        return None
    with _REGISTRY_LOCK:
        tl = _REGISTRY.get(query_id)
        if tl is not None:
            _REGISTRY.move_to_end(query_id)
            return tl
    return register_timeline(QueryTimeline(query_id))


def timeline_for(query_id: str) -> Optional[QueryTimeline]:
    with _REGISTRY_LOCK:
        return _REGISTRY.get(query_id)


def current_timeline() -> Optional[QueryTimeline]:
    return getattr(_ACTIVE, "timeline", None)


def record_point(name: str, value: float) -> None:
    """Hot-path append: one thread-local read; a no-op (no allocation,
    no clock read) when no timeline is active."""
    tl = getattr(_ACTIVE, "timeline", None)
    if tl is not None:
        tl.record(name, value)


class _Activation:
    __slots__ = ("_timeline", "_prev")

    def __init__(self, timeline: Optional[QueryTimeline]):
        self._timeline = timeline

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "timeline", None)
        if self._timeline is not None:
            _ACTIVE.timeline = self._timeline
        return self._timeline

    def __exit__(self, *exc):
        if self._timeline is not None:
            _ACTIVE.timeline = self._prev
        return False


def recording(timeline: Optional[QueryTimeline]) -> _Activation:
    """Bind a timeline to the current thread (``None`` = no-op),
    exactly like ``obs.tracing`` / ``obs.publishing``."""
    return _Activation(timeline)
