"""Trace/metrics exports: Chrome-trace JSON and the JSONL query log.

- ``chrome_trace(tracer)`` renders a tracer's spans in the Chrome
  trace-event format (the ``chrome://tracing`` / Perfetto JSON spec:
  complete "X" events with microsecond ts/dur, pid/tid lanes, plus
  "M" metadata naming the process after the query id) so a TPU query's
  life is inspectable in the standard tooling.
- ``maybe_write_trace`` drops one ``<query_id>.trace.json`` per query
  under the trace directory (``PRESTO_TPU_TRACE_DIR`` env >
  ``query.trace-dir`` config, resolved once at import with a
  ``set_trace_dir`` override hook).
- :class:`QueryLogListener` is an EventListener writing one JSON line
  per completed query — the warehouse query-log sink the reference
  builds on the EventListener SPI.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

from presto_tpu.sync import named_lock

from presto_tpu.events import (
    EventListener, MemoryKillEvent, QueryAdmittedEvent, QueryCompletedEvent,
    QueryKilledEvent, QueryQueuedEvent, WorkerStateChangeEvent,
)
from presto_tpu.obs.trace import Tracer

def _normalize_dir(path: Optional[str]) -> Optional[str]:
    """Shared disable convention with the sibling config keys
    (program_cache_dir, query_log_path): empty / ``0`` / ``false``
    means disabled, not a directory literally named ``0``."""
    if path is None or path.strip() in ("", "0", "false"):
        return None
    return path


# resolved ONCE at import (module scope: the engine-lint env-read rule's
# sanctioned place); set_trace_dir overrides for config wiring and tests
_TRACE_DIR: Optional[str] = _normalize_dir(
    os.environ.get("PRESTO_TPU_TRACE_DIR"))


def trace_dir() -> Optional[str]:
    return _TRACE_DIR


def set_trace_dir(path: Optional[str]) -> None:
    global _TRACE_DIR
    _TRACE_DIR = _normalize_dir(path)


def maybe_enable_trace_dir(config) -> Optional[str]:
    """Wire ``query.trace-dir`` from an EngineConfig; the environment
    (resolved at import) wins over config, matching the persistent
    program cache's precedence."""
    if _TRACE_DIR is not None:
        return _TRACE_DIR
    d = _normalize_dir(config.str("query.trace-dir"))
    if d:
        set_trace_dir(d)
    return d


def chrome_trace(tracer: Tracer) -> Dict[str, Any]:
    """Chrome trace-event JSON for one query's tracer.  Timestamps are
    microseconds relative to the tracer's start (perf_counter deltas —
    monotonic, so spans nest exactly as measured)."""
    pid = os.getpid()
    events = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": f"presto_tpu query {tracer.query_id}"}},
    ]
    with tracer._lock:
        spans = list(tracer.spans)
    # base on the earliest span, not tracer construction: retroactive
    # spans (the parse that ran before tracing was decided) start
    # earlier, and Chrome rejects negative timestamps
    t_base = min([tracer.t_start] + [s.t0 for s in spans])
    tids = set()
    for s in spans:
        ev = {
            "ph": "X",
            "name": s.name,
            "cat": s.cat,
            "ts": round((s.t0 - t_base) * 1e6, 1),
            "dur": round(s.dur * 1e6, 1),
            "pid": pid,
            "tid": s.tid,
        }
        if s.args:
            ev["args"] = {k: v for k, v in s.args.items()}
        events.append(ev)
        tids.add(s.tid)
    for tid in sorted(tids):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": f"thread-{tid}"}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "query_id": tracer.query_id,
            "trace_token": tracer.trace_token,
            "create_time": tracer.create_time,
            # spans past the tracer's retention cap were counted, not
            # kept — a nonzero value means the trace is a prefix
            "dropped_spans": tracer.dropped,
        },
    }


def write_trace(tracer: Tracer, directory: str) -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{tracer.query_id}.trace.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(tracer), f)
        f.write("\n")
    os.replace(tmp, path)
    return path


def maybe_write_trace(tracer: Tracer) -> Optional[str]:
    d = trace_dir()
    if d is None:
        return None
    try:
        return write_trace(tracer, d)
    except OSError:
        return None  # tracing must never fail the query


class QueryLogListener(EventListener):
    """JSONL query log: one line per completed query, carrying the
    lifecycle stage times and (when the query traced) the span-tree
    rollup.  Appends are serialized and flushed per event so the log
    survives a crash with every completed query it saw."""

    def __init__(self, path: str):
        self.path = path
        self._lock = named_lock("export.QueryLogListener._lock")

    def query_completed(self, e: QueryCompletedEvent) -> None:
        from presto_tpu.obs.trace import lookup

        rec: Dict[str, Any] = {
            "query_id": e.query_id,
            "state": e.state,
            "user": e.user,
            "rows": e.rows,
            "create_time": e.create_time,
            "end_time": e.end_time,
            "wall_s": round(e.end_time - e.create_time, 6),
            "sql": e.sql,
        }
        for k in ("error", "trace_token", "dist_stages", "dist_fallback",
                  "planning_ms", "compile_ms", "execution_ms",
                  "cache_hit", "queued_ms", "memory_blocked_ms",
                  "findings", "worst_estimate_ratio"):
            v = getattr(e, k, None)
            if v is not None:
                rec[k] = v
        tracer = lookup(e.query_id)
        if tracer is not None:
            rec["spans"] = tracer.summary()
        self._append(rec)

    def memory_killed(self, e: MemoryKillEvent) -> None:
        """One ``"event": "memory_kill"`` line per low-memory-killer
        victim — the kill DECISION, distinct from (and preceding) the
        victim's completion line."""
        self._append({
            "event": "memory_kill",
            "query_id": e.query_id,
            "freed_bytes": e.freed_bytes,
            "reserved_bytes": e.reserved_bytes,
            "limit_bytes": e.limit_bytes,
            "kill_time": e.kill_time,
        })

    def query_killed(self, e: QueryKilledEvent) -> None:
        """One ``"event": "query_killed"`` line per coordinator kill
        decision (deadline / policy) with its reason code — e.g.
        ``EXCEEDED_TIME_LIMIT`` when ``query.max-execution-time``
        expired (docs/fault-tolerance.md)."""
        self._append({
            "event": "query_killed",
            "query_id": e.query_id,
            "reason": e.reason,
            "message": e.message,
            "limit_s": e.limit_s,
            "elapsed_s": e.elapsed_s,
            "kill_time": e.kill_time,
        })

    def query_queued(self, e: QueryQueuedEvent) -> None:
        """One ``"event": "query_queued"`` line per admission-queue
        entry (serving tier): group + live position at enqueue time."""
        self._append({
            "event": "query_queued",
            "query_id": e.query_id,
            "user": e.user,
            "group": e.group,
            "position": e.position,
            "queue_time": e.queue_time,
        })

    def query_admitted(self, e: QueryAdmittedEvent) -> None:
        """One ``"event": "query_admitted"`` line per dispatch: queue
        wait and the memory projection the admission was made under."""
        self._append({
            "event": "query_admitted",
            "query_id": e.query_id,
            "group": e.group,
            "queued_ms": e.queued_ms,
            "projected_bytes": e.projected_bytes,
            "admit_time": e.admit_time,
        })

    def worker_state_changed(self, e: WorkerStateChangeEvent) -> None:
        """One ``"event": "worker_state_change"`` line per failure-
        detector transition — the audit trail that a mid-query retry
        actually crossed a worker death, not just a slow response."""
        self._append({
            "event": "worker_state_change",
            "uri": e.uri,
            "old_state": e.old_state,
            "new_state": e.new_state,
            "reason": e.reason,
            "change_time": e.change_time,
        })

    def _append(self, rec: Dict[str, Any]) -> None:
        line = json.dumps(rec, default=str)
        try:
            with self._lock:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
        except OSError:
            pass  # a full disk must never fail an already-run query
