"""Logical/physical plan nodes.

Reference analog: ``presto-main/.../sql/planner/plan/`` (46 node types:
TableScanNode.java, FilterNode.java, ProjectNode.java,
AggregationNode.java, JoinNode.java, SortNode.java, TopNNode.java,
LimitNode.java, OutputNode.java, ExchangeNode.java, ValuesNode.java...).
The reference's symbol-based plans (Symbol -> Expression maps) become
positional: every node's output is a flat channel list, expressions are
``expr.ir`` trees over the source's channels.  Positional channels keep
the lowering to device kernels trivial — a channel IS a Block index.

Each node knows its output schema: ``output_names`` / ``output_types``
(+ per-channel dictionary/domain metadata threaded for planner use).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from presto_tpu.catalog import TableHandle
from presto_tpu.expr.ir import AggCall, Expr
from presto_tpu.page import Dictionary
from presto_tpu.types import Type

from presto_tpu.ops.aggregate import output_type as agg_output_type
from presto_tpu.ops.aggregate import state_types as agg_state_types


@dataclasses.dataclass(eq=False)
class Channel:
    """Output column descriptor: name + type + optional dictionary and
    known value domain (for exact key packing)."""

    name: str
    type: Type
    dictionary: Optional[Dictionary] = None
    domain: Optional[Tuple[int, int]] = None


class PlanNode:
    @property
    def sources(self) -> List["PlanNode"]:
        return []

    @property
    def channels(self) -> List[Channel]:
        raise NotImplementedError

    @property
    def output_names(self) -> List[str]:
        return [c.name for c in self.channels]

    @property
    def output_types(self) -> List[Type]:
        return [c.type for c in self.channels]


def _expr_channel(e: Expr, name: str, src: List[Channel]) -> Channel:
    """Derive output channel metadata for a projection expression."""
    from presto_tpu.expr.compile import expr_dictionary
    from presto_tpu.expr.ir import ColumnRef

    if isinstance(e, ColumnRef) and e.index < len(src):
        s = src[e.index]
        return Channel(name, e.type, s.dictionary, s.domain)
    if e.type.is_string or (e.type.is_array and e.type.element is not None
                            and e.type.element.is_string):
        d = expr_dictionary(e, [c.dictionary for c in src])
        if d is not None:
            dom = (0, len(d) - 1) if e.type.is_string else None
            return Channel(name, e.type, d, dom)
    return Channel(name, e.type)


@dataclasses.dataclass(eq=False)
class TableScanNode(PlanNode):
    """Scan selected columns of a table (TableScanNode.java analog).
    ``columns`` are indexes into the connector's full schema;
    ``splits`` optionally restricts to an assigned split subset (the
    worker-side view of a split assignment, metadata/Split.java)."""

    handle: TableHandle
    columns: List[int]
    splits: Optional[List[int]] = None
    # simple pushed-down range constraints (col, op, device-repr value)
    # for stats-based split pruning (TupleDomain pushdown analog)
    constraints: List[Tuple[str, str, int]] = dataclasses.field(default_factory=list)
    # pushed-down row limit: the scan may stop producing splits once
    # this many live rows have been emitted (PushLimitIntoTableScan /
    # ConnectorMetadata applyLimit analog); the LimitNode above stays
    limit: Optional[int] = None
    # TABLESAMPLE (method, pct): "bernoulli" masks rows by a
    # deterministic per-(split, row) hash; "system" keeps whole splits
    # (sql/tree/SampledRelation + SampleNode analog)
    sample: Optional[Tuple[str, float]] = None

    @property
    def channels(self) -> List[Channel]:
        return [
            Channel(c.name, c.type, c.dictionary, c.domain)
            for i in self.columns
            for c in [self.handle.columns[i]]
        ]


@dataclasses.dataclass(eq=False)
class FilterNode(PlanNode):
    source: PlanNode
    predicate: Expr

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        return self.source.channels


@dataclasses.dataclass(eq=False)
class ProjectNode(PlanNode):
    source: PlanNode
    projections: List[Expr]
    names: List[str]

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        src = self.source.channels
        return [_expr_channel(e, n, src) for e, n in zip(self.projections, self.names)]


@dataclasses.dataclass(eq=False)
class AggregationNode(PlanNode):
    """Grouped/global aggregation (AggregationNode.java analog).

    step: 'single' | 'partial' | 'final' — the PARTIAL/FINAL split of
    iterative/rule/PushPartialAggregationThroughExchange.java.
    For step='final' the source emits partial-state pages (keys then
    state columns).
    """

    source: PlanNode
    group_exprs: List[Expr]
    group_names: List[str]
    aggs: List[AggCall]
    agg_names: List[str]
    step: str = "single"
    max_groups: int = 1 << 16
    # equal group keys are contiguous in the input (scan sort order
    # covers the keys): the streaming-aggregation path skips the sort
    # (StreamingAggregationOperator.java:38)
    presorted: bool = False

    @property
    def sources(self):
        return [self.source]

    @property
    def key_domains(self) -> List[Optional[Tuple[int, int]]]:
        from presto_tpu.expr.ir import ColumnRef

        src = self.source.channels
        out = []
        for e in self.group_exprs:
            if isinstance(e, ColumnRef) and src[e.index].domain is not None:
                out.append(src[e.index].domain)
            else:
                out.append(None)
        return out

    def _agg_dict(self, agg, src: List[Channel]):
        """Dictionary of value-preserving aggregates — the single
        source of truth lives in ops/aggregate.py (_agg_dict)."""
        from presto_tpu.ops.aggregate import _agg_dict as agg_dictionary

        return agg_dictionary(agg, [c.dictionary for c in src])

    @property
    def channels(self) -> List[Channel]:
        src = self.source.channels
        keys = [_expr_channel(e, n, src) for e, n in zip(self.group_exprs, self.group_names)]
        if self.step == "partial":
            states = []
            for agg, name in zip(self.aggs, self.agg_names):
                d = self._agg_dict(agg, src)
                for j, t in enumerate(agg_state_types(agg)):
                    states.append(Channel(f"{name}${j}", t, d if j == 0 else None))
            return keys + states
        return keys + [
            Channel(n, agg_output_type(a), self._agg_dict(a, src))
            for a, n in zip(self.aggs, self.agg_names)
        ]


@dataclasses.dataclass(eq=False)
class GroupIdNode(PlanNode):
    """Grouping-set row replication (operator/GroupIdOperator.java
    analog). Each input page is emitted once per grouping set with the
    set's inactive key channels masked to NULL plus a constant $group_id
    channel; a single downstream aggregation grouped by
    (keys..., $group_id) then computes every set in one pass — the
    TPU-friendly form of GROUPING SETS / ROLLUP / CUBE (no per-set
    re-scan, all replicas are device-resident concatenations).

    Output channel layout: source channels, then one channel per key
    expression, then $group_id.
    """

    source: PlanNode
    key_exprs: List[Expr]
    key_names: List[str]
    # per grouping set: which key positions are live (unmasked)
    set_masks: List[List[bool]]

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        src = self.source.channels
        keys = [_expr_channel(e, n, src) for e, n in zip(self.key_exprs, self.key_names)]
        from presto_tpu.types import BIGINT as _BIGINT

        gid = Channel("$group_id", _BIGINT, None, (0, max(len(self.set_masks) - 1, 0)))
        return src + keys + [gid]


@dataclasses.dataclass(eq=False)
class JoinNode(PlanNode):
    """Hash join (JoinNode.java analog). ``left`` is the probe side,
    ``right`` the build side (the reference also builds on the right).
    kind: inner | left | semi | anti.  ``unique_build``: planner's
    guarantee that build keys are unique (primary-key joins) enabling
    the probe-aligned kernel instead of the expanding one."""

    left: PlanNode
    right: PlanNode
    left_keys: List[Expr]
    right_keys: List[Expr]
    kind: str = "inner"
    unique_build: bool = False
    # build side fetched per probe batch via the connector's point-
    # lookup SPI instead of a full scan (operator/index/IndexLoader +
    # planner IndexJoinOptimizer.java)
    use_index: bool = False
    # NULL keys match each other (IS NOT DISTINCT FROM): the
    # INTERSECT/EXCEPT lowering's comparison semantics
    null_safe_keys: bool = False
    # ANSI three-valued IN/NOT IN (HashSemiJoinOperator.java:32): an
    # unmatched probe is NULL (not FALSE) when its key is NULL or the
    # build side holds a NULL key.  Set for IN-subquery lowerings;
    # EXISTS keeps plain semi/anti semantics.
    null_aware: bool = False

    @property
    def sources(self):
        return [self.left, self.right]

    @property
    def key_domains(self) -> List[Optional[Tuple[int, int]]]:
        """Join-key packing domains: union of probe/build side domains
        per key position (both sides must pack identically)."""
        from presto_tpu.expr.ir import ColumnRef

        lch, rch = self.left.channels, self.right.channels
        out = []
        for le, re_ in zip(self.left_keys, self.right_keys):
            ld = lch[le.index].domain if isinstance(le, ColumnRef) else None
            rd = rch[re_.index].domain if isinstance(re_, ColumnRef) else None
            if ld is not None and rd is not None:
                out.append((min(ld[0], rd[0]), max(ld[1], rd[1])))
            else:
                out.append(None)
        return out

    @property
    def channels(self) -> List[Channel]:
        if self.kind in ("semi", "anti"):
            return self.left.channels
        if self.kind == "mark":
            from presto_tpu.types import BOOLEAN as _BOOLEAN

            return self.left.channels + [Channel("$mark", _BOOLEAN)]
        return self.left.channels + self.right.channels


@dataclasses.dataclass(eq=False)
class RemoteSourceNode(PlanNode):
    """Leaf consuming another stage's task output buffers over DCN —
    the worker-to-worker shuffle read (operator/ExchangeOperator.java:36
    consuming execution/buffer/PartitionedOutputBuffer.java partitions
    via HttpPageBufferClient).  ``producer`` is the upstream fragment's
    plan, held ONLY for its output channel layout (types/dictionaries
    must match what the upstream serialized); it is never executed by
    the consuming worker."""

    producer: PlanNode
    tasks: List  # [(worker_uri, task_id)] upstream stage tasks
    buffer_id: int = 0

    @property
    def sources(self):
        return []

    @property
    def channels(self) -> List[Channel]:
        return self.producer.channels


@dataclasses.dataclass(eq=False)
class CrossSingleNode(PlanNode):
    """Cross join against a guaranteed single-row relation — the
    planner's lowering of uncorrelated scalar subqueries (reference:
    EnforceSingleRowNode.java + cross join in
    TransformUncorrelatedSubqueryToJoin); executed as a broadcast of
    the single row's values into the probe stream."""

    left: PlanNode
    right: PlanNode

    @property
    def sources(self):
        return [self.left, self.right]

    @property
    def channels(self) -> List[Channel]:
        return self.left.channels + self.right.channels


@dataclasses.dataclass(eq=False)
class UnnestNode(PlanNode):
    """Expand array/map-valued expressions to one output row per
    element, replicating the source row's columns (reference:
    operator/UnnestOperator.java:35, plan/UnnestNode.java).  Output
    channels = source channels + per-arg element column(s) (maps emit a
    key column then a value column) + optional ordinality column.

    TPU shape: output capacity = source capacity * max_elems — a
    static cross of (row, slot) with liveness row_mask[r] & (j <
    len[r]), so the expansion is one reshape/gather kernel."""

    source: PlanNode
    unnest_exprs: List[Expr]
    elem_names: List[str]
    ordinality: bool = False

    @property
    def sources(self):
        return [self.source]

    @property
    def max_elems(self) -> int:
        return max(e.type.max_elems for e in self.unnest_exprs)

    @property
    def channels(self) -> List[Channel]:
        from presto_tpu.types import BIGINT

        out = list(self.source.channels)
        i = 0
        srcs = self.source.channels
        for e in self.unnest_exprs:
            if e.type.is_map:
                out.append(_expr_channel_elem(e, self.elem_names[i], srcs, key=True))
                out.append(_expr_channel_elem(e, self.elem_names[i + 1], srcs))
                i += 2
            else:
                out.append(_expr_channel_elem(e, self.elem_names[i], srcs))
                i += 1
        if self.ordinality:
            out.append(Channel(self.elem_names[i] if i < len(self.elem_names)
                               else "ordinality", BIGINT))
        return out


def _expr_channel_elem(e: Expr, name: str, src: List[Channel], key: bool = False) -> Channel:
    """Channel for an unnested element column: element type, with the
    container column's dictionary if the elements are dict-coded."""
    from presto_tpu.expr.ir import Call as _Call

    t = e.type.key_element if key else e.type.element
    from presto_tpu.expr.compile import expr_dictionary

    # MAP(keys_array, values_array): each side's dictionary provenance
    # comes from its own constructor argument
    if isinstance(e, _Call) and e.fn in ("map", "map_construct"):
        e = e.args[0] if key else e.args[1]
    d = expr_dictionary(e, [c.dictionary for c in src]) if t.is_string else None
    return Channel(name, t, d)


@dataclasses.dataclass(eq=False)
class SortNode(PlanNode):
    source: PlanNode
    sort_exprs: List[Expr]
    ascending: List[bool]
    nulls_first: Optional[List[bool]] = None

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        return self.source.channels


@dataclasses.dataclass(eq=False)
class TopNNode(PlanNode):
    source: PlanNode
    sort_exprs: List[Expr]
    ascending: List[bool]
    count: int = 0
    nulls_first: Optional[List[bool]] = None

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        return self.source.channels


@dataclasses.dataclass(eq=False)
class LimitNode(PlanNode):
    source: PlanNode
    count: int

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        return self.source.channels


@dataclasses.dataclass(eq=False)
class ValuesNode(PlanNode):
    """Literal rows (ValuesNode.java analog).  String columns store
    dictionary codes with the Dictionary in ``dictionaries``."""

    names: List[str]
    types: List[Type]
    rows: List[tuple]
    dictionaries: Optional[List[Optional[Dictionary]]] = None

    @property
    def channels(self) -> List[Channel]:
        dicts = self.dictionaries or [None] * len(self.names)
        return [
            Channel(n, t, d, (0, len(d) - 1) if d is not None else None)
            for n, t, d in zip(self.names, self.types, dicts)
        ]


@dataclasses.dataclass(eq=False)
class UnionNode(PlanNode):
    """UNION ALL concatenation (UnionNode.java analog).  Sources must
    be type-aligned by the planner; VARCHAR columns whose arms carry
    different dictionaries get a merged dictionary with per-source code
    offsets (applied by the executor)."""

    inputs: List[PlanNode]

    def __post_init__(self):
        self._channels: Optional[List[Channel]] = None
        self._offsets: Optional[List[List[int]]] = None

    def _compute(self):
        if self._channels is not None:
            return
        chans: List[Channel] = []
        offsets = [[0] * len(self.inputs[0].channels) for _ in self.inputs]
        for i, base in enumerate(self.inputs[0].channels):
            dicts = [src.channels[i].dictionary for src in self.inputs]
            if base.type.is_string and len({id(d) for d in dicts}) > 1:
                values: List[str] = []
                for k, d in enumerate(dicts):
                    offsets[k][i] = len(values)
                    values.extend(list(d.values))
                merged = Dictionary(values)
                chans.append(Channel(base.name, base.type, merged, (0, len(values) - 1)))
            else:
                domain = base.domain
                for src in self.inputs[1:]:
                    d2 = src.channels[i].domain
                    domain = (
                        (min(domain[0], d2[0]), max(domain[1], d2[1]))
                        if domain is not None and d2 is not None
                        else None
                    )
                chans.append(Channel(base.name, base.type, base.dictionary, domain))
        self._channels = chans
        self._offsets = offsets

    @property
    def sources(self):
        return list(self.inputs)

    @property
    def channels(self) -> List[Channel]:
        self._compute()
        return self._channels

    @property
    def code_offsets(self) -> List[List[int]]:
        self._compute()
        return self._offsets


@dataclasses.dataclass(eq=False)
class WindowNode(PlanNode):
    """Window functions over one (partition, order) spec
    (WindowNode.java / WindowOperator analog); appends one channel per
    function."""

    source: PlanNode
    partition_exprs: List[Expr]
    order_exprs: List[Expr]
    ascending: List[bool]
    funcs: List[object]  # ops.window.WindowFunc
    func_names: List[str]

    @property
    def sources(self):
        return [self.source]

    @property
    def partition_domains(self):
        from presto_tpu.expr.ir import ColumnRef

        src = self.source.channels
        out = []
        for e in self.partition_exprs:
            if isinstance(e, ColumnRef) and src[e.index].domain is not None:
                out.append(src[e.index].domain)
            else:
                out.append(None)
        return out

    @property
    def channels(self) -> List[Channel]:
        return self.source.channels + [
            Channel(n, f.type) for f, n in zip(self.funcs, self.func_names)
        ]


@dataclasses.dataclass(eq=False)
class PrecomputedNode(PlanNode):
    """A materialized Page injected into a plan — how distributed stage
    results re-enter local post-processing (the role RemoteSourceNode /
    ExchangeNode plays between fragments in
    planner/plan/RemoteSourceNode.java)."""

    page: object  # Page
    channel_list: List[Channel]

    @property
    def channels(self) -> List[Channel]:
        return self.channel_list


@dataclasses.dataclass(eq=False)
class OutputNode(PlanNode):
    """Root: names the final result columns (OutputNode.java analog)."""

    source: PlanNode
    names: List[str]

    @property
    def sources(self):
        return [self.source]

    @property
    def channels(self) -> List[Channel]:
        src = self.source.channels
        return [Channel(n, c.type, c.dictionary, c.domain) for n, c in zip(self.names, src)]


def plan_tree_str(node: PlanNode, indent: int = 0, stats=None, estimator=None,
                  exclusive=None, mem=None, estimates=None,
                  misestimate_factor: float = 8.0, _keys=None) -> str:
    """EXPLAIN-style rendering (planPrinter/PlanPrinter.java analog);
    pass the executor's QueryStats for EXPLAIN ANALYZE annotations and a
    planner StatsCalculator for cost estimates ({rows: N} like the
    reference's estimate lines).  ``exclusive`` maps chain-member nodes
    to per-operator EXCLUSIVE seconds (EXPLAIN ANALYZE VERBOSE — fused
    chains re-run prefix-by-prefix; OperatorStats.java:38 analog).
    ``mem`` maps ``id(node)`` to peak reserved bytes from the tagged
    memory reservations (EXPLAIN ANALYZE per-operator memory).

    ``estimates`` is the binder's bind-time estimate map
    (``plan._estimates``, keyed by the structural stats keys); with
    ``stats`` it turns every operator line into an estimate-vs-actual
    line — ``est: X rows · actual: Y rows (×Z)`` — flagging nodes whose
    ratio exceeds ``misestimate_factor`` in either direction."""
    if estimator is None and stats is None and indent == 0:
        from presto_tpu.planner.stats import StatsCalculator

        estimator = StatsCalculator()
    if indent == 0 and estimates is None and stats is not None:
        estimates = getattr(node, "_estimates", None)
    if estimates is not None and _keys is None:
        # one shared key walk for the whole render (the same walk that
        # registered the stats entries), so twins resolve by occurrence
        from presto_tpu.exec.local import plan_node_keys

        _keys = {}
        for n, key in plan_node_keys(node):
            _keys.setdefault(id(n), key)
    pad = "  " * indent
    name = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, TableScanNode):
        detail = f" {node.handle.table}{[c.name for c in node.channels]}"
    elif isinstance(node, FilterNode):
        detail = f" {node.predicate!r}"
    elif isinstance(node, ProjectNode):
        detail = f" {node.names}"
    elif isinstance(node, AggregationNode):
        detail = f" [{node.step}] keys={node.group_names} aggs={node.aggs!r}"
    elif isinstance(node, JoinNode):
        detail = f" [{node.kind}] {node.left_keys!r} = {node.right_keys!r}"
    elif isinstance(node, WindowNode):
        detail = f" partition={node.partition_exprs!r} funcs={[f.kind for f in node.funcs]}"
    elif isinstance(node, (LimitNode, TopNNode)):
        detail = f" {node.count}"
    ann = stats.annotation(node) if stats is not None else ""
    if stats is not None and estimates is not None:
        from presto_tpu.obs.history import estimate_ratio

        key = _keys.get(id(node)) if _keys is not None else None
        est = (estimates.get(key) or {}).get("rows") if key else None
        actual = stats.actual_rows(node)
        if est is not None:
            line = f"  est: {int(est)} rows"
            if actual is not None:
                ratio = estimate_ratio(est, actual)
                line += f" · actual: {actual} rows (×{ratio:.1f})"
                if ratio >= misestimate_factor:
                    line += " ** MISESTIMATE **"
            else:
                # fused chain interior: its pages never stream
                # individually, so there is no per-node actual
                line += " · actual: n/a"
            ann += line
    if exclusive is not None and node in exclusive:
        ann += f"  [excl={exclusive[node] * 1e3:.1f}ms]"
    if mem is not None and id(node) in mem:
        nbytes = mem[id(node)]
        human = (f"{nbytes / 1e6:.1f}MB" if nbytes >= 1e6
                 else f"{nbytes / 1e3:.1f}kB")
        ann += f"  [peak_mem={human}]"
    if estimator is not None:
        try:
            ann += "  {rows: %d}" % int(estimator.rows(node))
        except Exception:
            pass
    out = f"{pad}- {name}{detail}{ann}\n"
    for s in node.sources:
        out += plan_tree_str(s, indent + 1, stats, estimator, exclusive, mem,
                             estimates=estimates,
                             misestimate_factor=misestimate_factor,
                             _keys=_keys)
    return out
