from presto_tpu.planner.plan import (  # noqa: F401
    AggregationNode,
    FilterNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    ValuesNode,
)
