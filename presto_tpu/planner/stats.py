"""Plan statistics calculator (CBO v1).

Reference analog: ``presto-main/.../cost/`` — ``StatsCalculator`` rule
set (``FilterStatsCalculator``, ``JoinStatsRule``,
``AggregationStatsRule``) producing ``PlanNodeStatsEstimate`` /
``SymbolStatsEstimate``.  Collapsed to the two quantities this planner
acts on: output row count and per-channel (domain, NDV) ranges derived
from connector metadata, propagated bottom-up with the textbook
selectivity rules:

  eq literal        1 / ndv, domain pins to the value
  range literal     overlap fraction of the domain
  IN (k literals)   k / ndv
  join (inner)      |L| * |R| / max(ndv_L, ndv_R) per key
  group by          min(prod key ndvs, rows)

Used by the binder for join ordering / build-side choice / aggregation
capacity sizing, and by the fragmenter for broadcast-vs-partitioned
distribution (DetermineJoinDistributionType.java:33 AUTOMATIC mode).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from presto_tpu.expr.ir import Call, ColumnRef, Expr, Literal
from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    GroupIdNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)

UNKNOWN_FILTER_SELECTIVITY = 0.25  # FilterStatsCalculator's default-ish


@dataclasses.dataclass
class ColumnEstimate:
    """SymbolStatsEstimate analog: value range + distinct count."""

    domain: Optional[Tuple[float, float]] = None
    ndv: Optional[float] = None


@dataclasses.dataclass
class PlanEstimate:
    """PlanNodeStatsEstimate analog."""

    rows: float
    columns: List[ColumnEstimate]

    def col(self, i: int) -> ColumnEstimate:
        if 0 <= i < len(self.columns):
            return self.columns[i]
        return ColumnEstimate()


class StatsCalculator:
    """Memoized bottom-up estimator. The memo holds the node reference
    alongside its estimate — id() keys alone would go stale when CPython
    recycles a collected node's address for a new one (a calculator may
    outlive individual plans, e.g. the binder's)."""

    _MEMO_CAP = 1 << 17

    def __init__(self):
        self._memo: Dict[int, Tuple[PlanNode, PlanEstimate]] = {}
        # feedback loop (obs/history.py HistoricalStatsProvider): when
        # set, observed row counts from prior executions override the
        # textbook rules on structural-signature match — the binder
        # installs it per plan when the `feedback_stats` session
        # property is on
        self.history = None

    def rows(self, node: PlanNode) -> float:
        return self.estimate(node).rows

    def estimate(self, node: PlanNode) -> PlanEstimate:
        got = self._memo.get(id(node))
        if got is not None and got[0] is node:
            return got[1]
        est = self._compute(node)
        est.rows = max(est.rows, 0.0)
        if self.history is not None:
            try:
                observed = self.history.observed_rows(node)
            except Exception:
                observed = None  # a corrupt store must not fail planning
            if observed is not None:
                # observed actuals beat textbook selectivities; column
                # estimates stay — only the cardinality is fed back
                est = dataclasses.replace(est, rows=float(observed))
        from presto_tpu.planner.plan import PrecomputedNode

        if not isinstance(node, PrecomputedNode):  # don't pin device pages
            if len(self._memo) > self._MEMO_CAP:
                self._memo.clear()
            self._memo[id(node)] = (node, est)
        return est

    def reset(self) -> None:
        self._memo.clear()

    # ------------------------------------------------------------------
    def _compute(self, node: PlanNode) -> PlanEstimate:
        if isinstance(node, TableScanNode):
            rows = float(node.handle.row_count)
            pk = set(getattr(node.handle, "primary_key", None) or [])
            cols = []
            for i in node.columns:
                ch = node.handle.columns[i]
                ndv = None
                if getattr(ch, "ndv", None) is not None:
                    ndv = float(ch.ndv)
                elif ch.name in pk and len(pk) == 1:
                    # composite-key members are NOT unique individually
                    ndv = rows
                elif ch.domain is not None:
                    lo, hi = ch.domain
                    ndv = min(float(hi - lo + 1), rows)
                cols.append(ColumnEstimate(
                    domain=(float(ch.domain[0]), float(ch.domain[1])) if ch.domain else None,
                    ndv=ndv,
                ))
            return PlanEstimate(rows, cols)

        if isinstance(node, FilterNode):
            src = self.estimate(node.source)
            sel, cols = self._filter(node.predicate, src)
            rows = src.rows * sel
            out_cols = [ColumnEstimate(c.domain,
                                       None if c.ndv is None else min(c.ndv, max(rows, 1.0)))
                        for c in cols]
            return PlanEstimate(rows, out_cols)

        if isinstance(node, ProjectNode):
            src = self.estimate(node.source)
            cols = []
            for e in node.projections:
                if isinstance(e, ColumnRef):
                    cols.append(src.col(e.index))
                elif isinstance(e, Literal):
                    cols.append(ColumnEstimate(None, 1.0))
                else:
                    cols.append(ColumnEstimate())
            return PlanEstimate(src.rows, cols)

        if isinstance(node, JoinNode):
            return self._join(node)

        if isinstance(node, CrossSingleNode):
            src = self.estimate(node.left)
            right = self.estimate(node.right)
            return PlanEstimate(src.rows, src.columns + right.columns)

        if isinstance(node, AggregationNode):
            src = self.estimate(node.source)
            groups = 1.0
            key_cols = []
            for e in node.group_exprs:
                ndv = None
                if isinstance(e, ColumnRef):
                    ndv = src.col(e.index).ndv
                    key_cols.append(src.col(e.index))
                else:
                    key_cols.append(ColumnEstimate())
                groups *= ndv if ndv is not None else max(src.rows ** 0.5, 1.0)
            rows = min(groups, src.rows) if node.group_exprs else 1.0
            agg_cols = [ColumnEstimate() for _ in node.channels[len(node.group_exprs):]]
            return PlanEstimate(rows, key_cols + agg_cols)

        if isinstance(node, GroupIdNode):
            src = self.estimate(node.source)
            nsets = max(len(node.set_masks), 1)
            key_cols = []
            for e in node.key_exprs:
                key_cols.append(src.col(e.index) if isinstance(e, ColumnRef)
                                else ColumnEstimate())
            gid = ColumnEstimate((0.0, float(nsets - 1)), float(nsets))
            return PlanEstimate(src.rows * nsets, src.columns + key_cols + [gid])

        if isinstance(node, (LimitNode, TopNNode)):
            src = self.estimate(node.source)
            return PlanEstimate(min(float(node.count), src.rows), src.columns)

        if isinstance(node, UnionNode):
            rows = sum(self.estimate(s).rows for s in node.inputs)
            return PlanEstimate(rows, [ColumnEstimate() for _ in node.channels])

        if isinstance(node, ValuesNode):
            return PlanEstimate(float(len(node.rows)),
                                [ColumnEstimate() for _ in node.types])

        from presto_tpu.planner.plan import PrecomputedNode

        if isinstance(node, PrecomputedNode):
            # materialized page: exact row count available.  The EXPLAIN
            # simulation fabricates page=None nodes carrying the
            # planner's estimate instead (fragment.py tag()).
            if node.page is None:
                est = getattr(node, "_est_rows", None)
                rows = float(est) if est is not None else 1.0
                return PlanEstimate(
                    rows, [ColumnEstimate() for _ in node.channels])
            import numpy as _np

            rows = float(_np.asarray(node.page.row_mask).sum())
            return PlanEstimate(rows, [ColumnEstimate() for _ in node.channels])

        if isinstance(node, (SortNode, OutputNode, WindowNode)):
            src = self.estimate(node.source)
            ncols = len(node.channels)
            cols = list(src.columns) + [ColumnEstimate()] * (ncols - len(src.columns))
            return PlanEstimate(src.rows, cols[:ncols])

        srcs = node.sources
        if srcs:
            src = self.estimate(srcs[0])
            return PlanEstimate(src.rows, [ColumnEstimate() for _ in node.channels])
        return PlanEstimate(1.0, [ColumnEstimate() for _ in node.channels])

    # ------------------------------------------------------------------
    def _join(self, node: JoinNode) -> PlanEstimate:
        left = self.estimate(node.left)
        right = self.estimate(node.right)
        # per-key selectivity: 1 / max(ndv_l, ndv_r)
        sel = 1.0
        any_stats = False
        for lk, rk in zip(node.left_keys, node.right_keys):
            ndv_l = left.col(lk.index).ndv if isinstance(lk, ColumnRef) else None
            ndv_r = right.col(rk.index).ndv if isinstance(rk, ColumnRef) else None
            m = max(ndv_l or 0.0, ndv_r or 0.0)
            if m > 0:
                sel /= m
                any_stats = True
        if node.kind == "semi":
            # fraction of probe rows with a match
            frac = 0.5
            if any_stats and left.rows > 0:
                inner = left.rows * right.rows * sel
                frac = min(inner / left.rows, 1.0)
            return PlanEstimate(left.rows * frac, left.columns)
        if node.kind == "anti":
            frac = 0.5
            if any_stats and left.rows > 0:
                inner = left.rows * right.rows * sel
                frac = min(inner / left.rows, 1.0)
            return PlanEstimate(left.rows * (1.0 - frac), left.columns)
        if any_stats:
            rows = left.rows * right.rows * sel
        else:
            rows = max(left.rows, right.rows)
        if node.unique_build and node.kind in ("inner", "left"):
            # each probe row matches at most once (FK->PK): probe-bound
            rows = min(rows, left.rows)
        if node.kind in ("left", "full"):
            rows = max(rows, left.rows)
        if node.kind == "full":
            rows = max(rows, right.rows)
        return PlanEstimate(rows, left.columns + right.columns)

    # ------------------------------------------------------------------
    def _filter(self, e: Expr, src: PlanEstimate) -> Tuple[float, List[ColumnEstimate]]:
        """(selectivity, narrowed column estimates)."""
        cols = [dataclasses.replace(c) for c in src.columns]
        sel = self._conjunct(e, cols)
        return sel, cols

    def _conjunct(self, e: Expr, cols: List[ColumnEstimate]) -> float:
        if not isinstance(e, Call):
            return UNKNOWN_FILTER_SELECTIVITY
        fn = e.fn
        if fn == "and":
            return self._conjunct(e.args[0], cols) * self._conjunct(e.args[1], cols)
        if fn == "or":
            a = self._conjunct(e.args[0], list(cols))
            b = self._conjunct(e.args[1], list(cols))
            return min(a + b, 1.0)
        if fn == "not":
            return max(1.0 - self._conjunct(e.args[0], list(cols)), 0.05)
        col, lit, op = self._col_lit(e)
        if col is None:
            if fn == "is_null":
                return 0.05
            if fn == "not_null":
                return 0.95
            if fn == "in" and isinstance(e.args[0], ColumnRef):
                c = cols[e.args[0].index] if e.args[0].index < len(cols) else ColumnEstimate()
                k = float(len(e.args) - 1)
                if c.ndv:
                    return min(k / c.ndv, 1.0)
                return UNKNOWN_FILTER_SELECTIVITY
            if fn == "between" and isinstance(e.args[0], ColumnRef):
                sel = 1.0
                if isinstance(e.args[1], Literal):
                    sel *= self._range_sel(cols, e.args[0], e.args[1], "ge")
                if isinstance(e.args[2], Literal):
                    sel *= self._range_sel(cols, e.args[0], e.args[2], "le")
                return sel
            return UNKNOWN_FILTER_SELECTIVITY
        if op == "eq":
            c = cols[col.index] if col.index < len(cols) else ColumnEstimate()
            if lit.value is not None and not col.type.is_string:
                v = float(lit.value)
                cols[col.index] = ColumnEstimate((v, v), 1.0)
            if c.ndv:
                return 1.0 / c.ndv
            return 0.1
        if op == "ne":
            c = cols[col.index] if col.index < len(cols) else ColumnEstimate()
            return 1.0 - (1.0 / c.ndv) if c.ndv else 0.9
        return self._range_sel(cols, col, lit, op)

    def _col_lit(self, e: Call):
        """Normalize (col cmp literal) conjuncts; returns (col, lit, op)."""
        if e.fn not in ("eq", "ne", "lt", "le", "gt", "ge") or len(e.args) != 2:
            return None, None, None
        a, b = e.args
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
        if isinstance(a, ColumnRef) and isinstance(b, Literal):
            return a, b, e.fn
        if isinstance(b, ColumnRef) and isinstance(a, Literal):
            return b, a, flip.get(e.fn, e.fn)
        return None, None, None

    def _range_sel(self, cols, col: ColumnRef, lit: Literal, op: str) -> float:
        if col.index >= len(cols) or lit is None or lit.value is None \
                or col.type.is_string:
            return UNKNOWN_FILTER_SELECTIVITY
        c = cols[col.index]
        if c.domain is None:
            return UNKNOWN_FILTER_SELECTIVITY
        lo, hi = c.domain
        try:
            v = float(lit.value)
            # align scaled-int decimal spaces (domains are raw values)
            col_scale = (col.type.scale or 0) if col.type.is_decimal else 0
            lit_scale = (lit.type.scale or 0) if lit.type.is_decimal else 0
            if col_scale != lit_scale:
                v = v * (10.0 ** (col_scale - lit_scale))
        except (TypeError, ValueError):
            return UNKNOWN_FILTER_SELECTIVITY
        width = max(hi - lo, 1e-9)
        if op in ("lt", "le"):
            frac = (min(v, hi) - lo) / width
            new_dom = (lo, min(v, hi))
        else:  # gt, ge
            frac = (hi - max(v, lo)) / width
            new_dom = (max(v, lo), hi)
        frac = min(max(frac, 0.0), 1.0)
        new_ndv = None if c.ndv is None else max(c.ndv * frac, 1.0)
        cols[col.index] = ColumnEstimate(new_dom, new_ndv)
        return max(frac, 1e-4)


def capture_estimates(plan: PlanNode, calc: Optional[StatsCalculator] = None
                      ) -> Dict[tuple, dict]:
    """Stamp the whole plan with its bind-time estimates, keyed by the
    SAME ``((type name, structural digest), occurrence)`` ids
    ``QueryStats.register_plan`` assigns — so estimates and actuals
    share one key space by construction.  The binder attaches the
    result as ``plan._estimates``; EXPLAIN ANALYZE and the history
    feed read it back per node."""
    from presto_tpu.exec.local import plan_node_keys

    if calc is None:
        calc = StatsCalculator()
    out: Dict[tuple, dict] = {}
    for node, key in plan_node_keys(plan):
        if key in out:
            continue  # structural twins: the first occurrence-keyed hit wins
        try:
            est = calc.estimate(node)
        except Exception:
            continue  # an unestimable node renders without an estimate
        out[key] = {"rows": float(est.rows)}
    return out
