"""Iterative rule-based plan optimizer.

Reference analog: ``sql/planner/iterative/IterativeOptimizer.java``
with ``Memo.java`` and the ``Rule`` interface (79 rules in
``iterative/rule/``).  The memo here is an explored-set keyed by node
identity (plan nodes are identity-hashed DAG nodes, so a rewritten
node re-enters the queue and already-stable subtrees are skipped);
rules fire bottom-up to a fixpoint with an iteration budget.

Rules shipped (the subset with teeth for this engine's plan shapes —
each names its reference rule):
  MergeAdjacentFilters        iterative/rule/MergeFilters.java
  MergeAdjacentProjects       iterative/rule/MergeAdjacentProjects (via
                              InlineProjections.java)
  PushFilterThroughProject    iterative/rule/PushdownFilterIntoRow... /
                              PredicatePushDown's project case
  RemoveIdentityProjection    iterative/rule/RemoveRedundantIdentityProjections.java
  EvaluateConstantFilter      iterative/rule/RemoveTrivialFilters.java
  PushLimitThroughProject     iterative/rule/PushLimitThroughProject.java
  MergeLimits                 iterative/rule/MergeLimitWithSort / MergeLimits
"""

from __future__ import annotations

import dataclasses

from typing import Dict, List, Optional

from presto_tpu.expr.ir import Call, ColumnRef, Expr, Literal
from presto_tpu.matching import Pattern
from presto_tpu.obs.metrics import METRICS
from presto_tpu.planner.plan import (
    AggregationNode,
    CrossSingleNode,
    FilterNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)


class Rule:
    pattern: Pattern

    def apply(self, node: PlanNode) -> Optional[PlanNode]:  # pragma: no cover
        raise NotImplementedError


def _subst(e: Expr, inputs: List[Expr]) -> Expr:
    """Replace ColumnRefs with the corresponding input expressions
    (projection inlining)."""
    if isinstance(e, ColumnRef):
        return inputs[e.index]
    if isinstance(e, Call):
        return Call(type=e.type, fn=e.fn,
                    args=tuple(_subst(a, inputs) for a in e.args))
    from presto_tpu.expr.ir import LambdaExpr

    if isinstance(e, LambdaExpr):
        return LambdaExpr(type=e.type, params=e.params,
                          body=_subst(e.body, inputs))
    return e


class MergeAdjacentFilters(Rule):
    pattern = Pattern.type_of(FilterNode).with_sources(Pattern.type_of(FilterNode))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        inner: FilterNode = node.source
        from presto_tpu.types import BOOLEAN

        combined = Call(type=BOOLEAN, fn="and",
                        args=(inner.predicate, node.predicate))
        return FilterNode(inner.source, combined)


class MergeAdjacentProjects(Rule):
    pattern = Pattern.type_of(ProjectNode).with_sources(Pattern.type_of(ProjectNode))

    def apply(self, node: ProjectNode) -> Optional[PlanNode]:
        inner: ProjectNode = node.source
        # inline only when no inner expression is referenced twice by a
        # non-trivial outer use (avoids duplicating compute; XLA CSE
        # would fuse anyway, but keep plans readable)
        refs: dict = {}
        for p in node.projections:
            for r in _expr_refs(p):
                refs[r] = refs.get(r, 0) + 1
        for i, ip in enumerate(inner.projections):
            if refs.get(i, 0) > 1 and not isinstance(ip, (ColumnRef, Literal)):
                return None
        new_projs = [_subst(p, list(inner.projections)) for p in node.projections]
        return ProjectNode(inner.source, new_projs, list(node.names))


class PushFilterThroughProject(Rule):
    pattern = Pattern.type_of(FilterNode).with_sources(Pattern.type_of(ProjectNode))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        proj: ProjectNode = node.source
        # a nondeterministic projection the predicate reads must stay
        # upstream of the filter decision: substituting would evaluate
        # e.g. random() once for the filter and again for the output
        # (PredicatePushDown pushes deterministic conjuncts only)
        if any(not _deterministic(proj.projections[i])
               for i in set(_expr_refs(node.predicate))):
            return None
        pred = _subst(node.predicate, list(proj.projections))
        return ProjectNode(FilterNode(proj.source, pred),
                           list(proj.projections), list(proj.names))


class RemoveIdentityProjection(Rule):
    pattern = Pattern.type_of(ProjectNode).where(
        lambda n: len(n.projections) == len(n.source.channels)
        and all(
            isinstance(p, ColumnRef) and p.index == i
            for i, p in enumerate(n.projections)
        )
        and [c.name for c in n.source.channels] == list(n.names)
    )

    def apply(self, node: ProjectNode) -> Optional[PlanNode]:
        return node.source


class EvaluateConstantFilter(Rule):
    pattern = Pattern.type_of(FilterNode).where(
        lambda n: isinstance(n.predicate, Literal))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        pred: Literal = node.predicate
        if pred.value:
            return node.source
        # provably-false filter -> empty values relation
        return ValuesNode(
            names=list(node.output_names), types=list(node.output_types),
            rows=[],
        )


class RecordScanConstraints(Rule):
    """Filter directly over a scan: record simple (col cmp literal)
    conjuncts on the scan for stats-based split pruning — rewrites that
    move filters below projections re-expose this opportunity after
    binding (PickTableLayout / TupleDomain pushdown analog)."""

    pattern = Pattern.type_of(FilterNode).with_sources(Pattern.type_of(TableScanNode))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        scan: TableScanNode = node.source
        names = [scan.handle.columns[i].name for i in scan.columns]
        flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
        found = []

        def emit(op: str, col: ColumnRef, lit: Literal):
            if lit.value is not None and not col.type.is_string \
                    and col.index < len(names):
                found.append((names[col.index], op, lit.value))

        def walk(e: Expr):
            if not isinstance(e, Call):
                return
            if e.fn == "and":
                walk(e.args[0])
                walk(e.args[1])
                return
            if e.fn in ("eq", "lt", "le", "gt", "ge") and len(e.args) == 2:
                a, b = e.args
                if isinstance(a, ColumnRef) and isinstance(b, Literal):
                    emit(e.fn, a, b)
                elif isinstance(b, ColumnRef) and isinstance(a, Literal):
                    emit(flip[e.fn], b, a)

        walk(node.predicate)
        new = [c for c in found if c not in scan.constraints]
        if not new:
            return None  # fixpoint: nothing to record
        scan.constraints.extend(new)
        return node  # same node, enriched scan (counts as progress once)


class PushLimitThroughProject(Rule):
    pattern = Pattern.type_of(LimitNode).with_sources(Pattern.type_of(ProjectNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        proj: ProjectNode = node.source
        return ProjectNode(LimitNode(proj.source, node.count),
                           list(proj.projections), list(proj.names))


class MergeLimits(Rule):
    pattern = Pattern.type_of(LimitNode).with_sources(Pattern.type_of(LimitNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        inner: LimitNode = node.source
        return LimitNode(inner.source, min(node.count, inner.count))


class MergeLimitWithSort(Rule):
    """Limit over Sort -> bounded TopN (MergeLimitWithSort.java) — the
    subquery-ORDER-BY + outer-LIMIT shape the binder can't fuse."""

    pattern = Pattern.type_of(LimitNode).with_sources(Pattern.type_of(SortNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        srt: SortNode = node.source
        return TopNNode(srt.source, list(srt.sort_exprs), list(srt.ascending),
                        node.count, srt.nulls_first)


class PushLimitThroughUnion(Rule):
    """Limit over UNION ALL: bound each arm too (no arm needs to
    produce more than the limit) while keeping the outer limit
    (PushLimitThroughUnion.java)."""

    pattern = Pattern.type_of(LimitNode).with_sources(Pattern.type_of(UnionNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        union: UnionNode = node.source
        if all(isinstance(i, LimitNode) and i.count <= node.count
               for i in union.inputs):
            return None  # already bounded
        bounded = [
            i if isinstance(i, LimitNode) and i.count <= node.count
            else LimitNode(i, node.count)
            for i in union.inputs
        ]
        return LimitNode(UnionNode(bounded), node.count)


class FlattenUnions(Rule):
    """Union arms that are themselves unions splice inline
    (MergeUnion-style flattening keeps one concat instead of a chain)."""

    pattern = Pattern.type_of(UnionNode).where(
        lambda n: any(isinstance(i, UnionNode) for i in n.inputs))

    def apply(self, node: UnionNode) -> Optional[PlanNode]:
        flat: List[PlanNode] = []
        for i in node.inputs:
            if isinstance(i, UnionNode):
                flat.extend(i.inputs)
            else:
                flat.append(i)
        return UnionNode(flat)


def _expr_refs(e: Expr) -> List[int]:
    if isinstance(e, ColumnRef):
        return [e.index]
    if isinstance(e, Call):
        return [r for a in e.args for r in _expr_refs(a)]
    from presto_tpu.expr.ir import LambdaExpr

    if isinstance(e, LambdaExpr):
        return _expr_refs(e.body)
    return []


class PushLimitIntoTableScan(Rule):
    """LIMIT over a count-preserving chain (projections only) down to
    the scan: the scan stops producing splits once the limit's worth of
    live rows has been emitted, so later splits never generate/load
    (iterative/rule/PushLimitIntoTableScan.java / the SPI's applyLimit).
    The LimitNode stays above for the exact cut."""

    pattern = Pattern(LimitNode)

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        import dataclasses as _dc

        projs: List[ProjectNode] = []
        src = node.source
        while isinstance(src, ProjectNode):
            projs.append(src)
            src = src.source
        if not isinstance(src, TableScanNode):
            return None
        if src.limit is not None and src.limit <= node.count:
            return None
        rebuilt: PlanNode = _dc.replace(src, limit=node.count)
        for p in reversed(projs):
            rebuilt = ProjectNode(rebuilt, p.projections, p.names)
        return LimitNode(rebuilt, node.count)


def _provably_distinct(src: PlanNode) -> bool:
    """Rows of ``src`` are provably unique as full tuples: a grouped
    aggregation's output (unique per key tuple), a projection that
    keeps every key of such an aggregation, or a scan whose selected
    columns include the table's primary key."""
    if isinstance(src, AggregationNode) and src.step == "single" \
            and src.group_exprs:
        return True
    if isinstance(src, TableScanNode):
        pk = src.handle.primary_key
        if pk:
            names = [src.handle.columns[i].name for i in src.columns]
            return all(k in names for k in pk)
        return False
    if isinstance(src, ProjectNode):
        if not all(isinstance(p, ColumnRef) for p in src.projections):
            return False
        kept = {p.index for p in src.projections}
        inner = src.source
        if isinstance(inner, AggregationNode) and inner.step == "single" \
                and inner.group_exprs:
            return set(range(len(inner.group_exprs))) <= kept
        if isinstance(inner, TableScanNode):
            pk = inner.handle.primary_key
            if pk:
                names = [inner.handle.columns[i].name for i in inner.columns]
                return all(k in names and names.index(k) in kept for k in pk)
    return False


class RemoveRedundantDistinct(Rule):
    """DISTINCT (an aggregation with no aggregates) over input that is
    already distinct on every output column is the identity
    (iterative/rule/RemoveRedundantDistinct /
    MultipleDistinctAggregationToMarkDistinct's pruning role)."""

    pattern = Pattern(AggregationNode)

    def apply(self, node: AggregationNode) -> Optional[PlanNode]:
        if node.aggs or node.step != "single" or not node.group_exprs:
            return None
        src = node.source
        n_src = len(src.channels)
        identity = (
            len(node.group_exprs) == n_src
            and all(isinstance(e, ColumnRef) and e.index == i
                    for i, e in enumerate(node.group_exprs))
        )
        if not identity:
            return None
        if not _provably_distinct(src):
            return None
        return src


def _empty_like(node: PlanNode) -> ValuesNode:
    """Zero-row Values with the node's exact output channels (the
    RemoveEmpty* rules' replacement relation)."""
    chans = node.channels
    return ValuesNode(
        names=[c.name for c in chans], types=[c.type for c in chans],
        rows=[], dictionaries=[c.dictionary for c in chans])


def _is_empty(node: PlanNode) -> bool:
    return isinstance(node, ValuesNode) and not node.rows


class EvaluateZeroLimit(Rule):
    """LIMIT 0 / TopN 0 produce nothing (EvaluateZeroLimit.java /
    EvaluateZeroTopN variant)."""

    pattern = Pattern.type_of((LimitNode, TopNNode)).where(
        lambda n: n.count == 0)

    def apply(self, node) -> Optional[PlanNode]:
        return _empty_like(node)


class PropagateEmptyValues(Rule):
    """Collapse operators over provably-empty inputs (the
    RemoveEmpty… rule family: empty scans, 1=0 filters and LIMIT 0
    propagate upward instead of compiling device programs):

    - Filter/Project/Sort/TopN/Limit/Window over empty -> empty
    - grouped aggregation over empty -> empty (global aggregation
      keeps its one-row result and is left alone)
    - inner join with either side empty, left/semi/anti joins with an
      empty probe, and semi joins with an empty build -> empty
    - union arms that are empty drop out
    """

    pattern = Pattern.type_of(PlanNode).where(
        lambda n: any(_is_empty(s) for s in n.sources))

    def apply(self, node: PlanNode) -> Optional[PlanNode]:
        from presto_tpu.planner.plan import JoinNode, WindowNode

        if isinstance(node, (FilterNode, ProjectNode, SortNode, TopNNode,
                             LimitNode, WindowNode)):
            return _empty_like(node)
        if isinstance(node, AggregationNode):
            if node.group_exprs and node.step in ("single", "partial"):
                return _empty_like(node)
            return None
        if isinstance(node, JoinNode):
            left_empty = _is_empty(node.left)
            right_empty = _is_empty(node.right)
            if node.kind == "inner" and (left_empty or right_empty):
                return _empty_like(node)
            if node.kind in ("left", "semi", "anti", "mark") and left_empty:
                return _empty_like(node)
            if node.kind == "semi" and right_empty:
                return _empty_like(node)
            return None
        if isinstance(node, UnionNode):
            live = [i for i in node.inputs if not _is_empty(i)]
            if not live:
                return _empty_like(node)
            if len(live) == len(node.inputs):
                return None
            if len(live) == 1:
                arm = live[0]
                return ProjectNode(
                    arm,
                    [ColumnRef(type=c.type, index=i, name=c.name)
                     for i, c in enumerate(arm.channels)],
                    list(node.output_names))
            return UnionNode(live)
        return None


_NONDETERMINISTIC = {"random", "rand", "uuid", "now", "current_timestamp"}


def _deterministic(e: Expr) -> bool:
    if isinstance(e, Call):
        return e.fn not in _NONDETERMINISTIC and all(
            _deterministic(a) for a in e.args)
    from presto_tpu.expr.ir import LambdaExpr

    if isinstance(e, LambdaExpr):
        return _deterministic(e.body)
    return True


def _simplify_expr(e: Expr) -> Expr:
    """Algebraic identity folding (SimplifyExpressions.java's
    ExpressionInterpreter subset): boolean short-circuits, double
    negation, +0 / *1 arithmetic units."""
    if not isinstance(e, Call):
        return e
    args = tuple(_simplify_expr(a) for a in e.args)
    e = Call(type=e.type, fn=e.fn, args=args)

    def lit(a, v):
        return isinstance(a, Literal) and a.value == v and not a.type.is_string

    if e.fn in ("eq", "ne", "lt", "le", "gt", "ge") and len(args) == 2 \
            and all(isinstance(a, Literal) and a.value is not None
                    and not a.type.is_string for a in args) \
            and not ((args[0].type.is_decimal or args[1].type.is_decimal)
                     and (args[0].type.scale != args[1].type.scale)):
        # decimals store SCALED ints: only same-scale pairs compare
        # directly (the binder coerces comparisons to a common scale)
        import operator

        op = {"eq": operator.eq, "ne": operator.ne, "lt": operator.lt,
              "le": operator.le, "gt": operator.gt, "ge": operator.ge}[e.fn]
        return Literal(type=e.type, value=bool(op(args[0].value,
                                                  args[1].value)))
    if e.fn == "and":
        if any(lit(a, False) for a in args):
            return Literal(type=e.type, value=False)
        live = [a for a in args if not lit(a, True)]
        if not live:
            return Literal(type=e.type, value=True)
        if len(live) == 1:
            return live[0]
        return Call(type=e.type, fn="and", args=tuple(live))
    if e.fn == "or":
        if any(lit(a, True) for a in args):
            return Literal(type=e.type, value=True)
        live = [a for a in args if not lit(a, False)]
        if not live:
            return Literal(type=e.type, value=False)
        if len(live) == 1:
            return live[0]
        return Call(type=e.type, fn="or", args=tuple(live))
    if e.fn == "not":
        a = args[0]
        if isinstance(a, Literal) and isinstance(a.value, bool):
            return Literal(type=e.type, value=not a.value)
        if isinstance(a, Call) and a.fn == "not":
            return a.args[0]
        return e
    if e.fn in ("add", "sub") and len(args) == 2:
        a, b = args
        if lit(b, 0) and a.type == e.type:
            return a
        if e.fn == "add" and lit(a, 0) and b.type == e.type:
            return b
        return e
    if e.fn == "mul" and len(args) == 2:
        a, b = args
        if lit(b, 1) and a.type == e.type:
            return a
        if lit(a, 1) and b.type == e.type:
            return b
        return e
    return e


class SimplifyExpressions(Rule):
    """Fold identities inside filter predicates and projections
    (SimplifyExpressions.java)."""

    pattern = Pattern.type_of((FilterNode, ProjectNode))

    def apply(self, node) -> Optional[PlanNode]:
        if isinstance(node, FilterNode):
            s = _simplify_expr(node.predicate)
            if s == node.predicate:
                return None
            if isinstance(s, Literal) and s.value is True:
                return node.source
            return FilterNode(node.source, s)
        outs = [_simplify_expr(p) for p in node.projections]
        if all(a == b for a, b in zip(outs, node.projections)):
            return None
        return ProjectNode(node.source, outs, list(node.names))


#: aggregates whose result can depend on input order (kept behind sorts)
_ORDER_SENSITIVE_AGGS = {"array_agg", "map_agg", "multimap_agg",
                         "map_union", "min_by", "max_by", "arbitrary",
                         "min_by_n", "max_by_n"}


class PruneOrderByInAggregation(Rule):
    """A sort feeding a (non-streaming) aggregation is meaningless —
    hash aggregation is order-insensitive
    (PruneOrderByInAggregation.java).  Left alone when the planner
    chose the presorted streaming path, where order IS load-bearing,
    and when any aggregate is order-sensitive (array_agg and friends)."""

    pattern = Pattern.type_of(AggregationNode).where(
        lambda n: isinstance(n.source, SortNode) and not n.presorted
        and not any(a.fn in _ORDER_SENSITIVE_AGGS for a in n.aggs))

    def apply(self, node: AggregationNode) -> Optional[PlanNode]:
        import dataclasses

        return dataclasses.replace(node, source=node.source.source)


class PushTopNThroughProject(Rule):
    """TopN over Project -> Project over TopN, inlining the sort keys
    (PushTopNThroughProject.java) so the bound applies before
    projection work."""

    pattern = Pattern.type_of(TopNNode).with_sources(
        Pattern.type_of(ProjectNode))

    def apply(self, node: TopNNode) -> Optional[PlanNode]:
        proj: ProjectNode = node.source
        if not all(_deterministic(p) for p in proj.projections):
            return None
        keys = [_subst(k, proj.projections) for k in node.sort_exprs]
        return ProjectNode(
            TopNNode(proj.source, keys, list(node.ascending), node.count,
                     node.nulls_first),
            list(proj.projections), list(proj.names))


class PushFilterThroughSort(Rule):
    """Filter commutes below Sort so fewer rows sort
    (PredicatePushDown's sort case)."""

    pattern = Pattern.type_of(FilterNode).with_sources(
        Pattern.type_of(SortNode))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        srt: SortNode = node.source
        return SortNode(FilterNode(srt.source, node.predicate),
                        list(srt.sort_exprs), list(srt.ascending),
                        srt.nulls_first)


class PushFilterThroughUnion(Rule):
    """Filter distributes into UNION ALL arms (PredicatePushDown's
    union case).  Guarded off when the predicate touches a dictionary
    VARCHAR channel: arm-local codes differ from the union's merged
    dictionary, so the compiled comparison would be wrong."""

    pattern = Pattern.type_of(FilterNode).with_sources(
        Pattern.type_of(UnionNode))

    def apply(self, node: FilterNode) -> Optional[PlanNode]:
        union: UnionNode = node.source
        # one predicate instance becomes one per arm — replicating a
        # nondeterministic predicate multiplies its call sites
        # (PredicatePushDown pushes deterministic conjuncts only)
        if not _deterministic(node.predicate):
            return None
        refs = set(_expr_refs(node.predicate))
        chans = union.channels
        for i in refs:
            if chans[i].dictionary is not None:
                return None
            for arm in union.inputs:
                if arm.channels[i].dictionary is not None:
                    return None
        return UnionNode([FilterNode(arm, node.predicate)
                          for arm in union.inputs])


class SimplifyCountOverConstant(Rule):
    """count(<non-null literal>) == count(*)
    (SimplifyCountOverConstant.java)."""

    pattern = Pattern.type_of(AggregationNode).where(
        lambda n: any(a.fn == "count" and isinstance(a.arg, Literal)
                      and a.arg.value is not None and not a.distinct
                      for a in n.aggs))

    def apply(self, node: AggregationNode) -> Optional[PlanNode]:
        import dataclasses

        from presto_tpu.expr.ir import AggCall

        aggs = [
            AggCall(fn="count_star", arg=None, type=a.type, distinct=False,
                    filter=a.filter)
            if (a.fn == "count" and isinstance(a.arg, Literal)
                and a.arg.value is not None and not a.distinct)
            else a
            for a in node.aggs
        ]
        return dataclasses.replace(node, aggs=aggs)


class MergeLimitWithTopN(Rule):
    """Limit over TopN: the smaller count wins — TopN output is sorted,
    so its prefix IS the tighter TopN (MergeLimitWithTopN.java)."""

    pattern = Pattern.type_of(LimitNode).with_sources(Pattern.type_of(TopNNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        t: TopNNode = node.source
        return TopNNode(t.source, list(t.sort_exprs), list(t.ascending),
                        min(t.count, node.count), t.nulls_first)


class PushTopNThroughUnion(Rule):
    """TopN over UNION ALL: each arm only needs its own top N — bound
    the arms, keep the outer TopN for the global pick
    (PushTopNThroughUnion.java)."""

    pattern = Pattern.type_of(TopNNode).with_sources(Pattern.type_of(UnionNode))

    def apply(self, node: TopNNode) -> Optional[PlanNode]:
        union: UnionNode = node.source
        # the sort keys get replicated into every arm — see
        # PushFilterThroughUnion's determinism guard
        if not all(_deterministic(k) for k in node.sort_exprs):
            return None

        def bounded(arm: PlanNode) -> bool:
            # the planted TopN may have been relocated below the arm's
            # projection by PushTopNThroughProject — look through
            # row-preserving projections only (an inner limit deep in
            # e.g. a join subtree does NOT bound the arm)
            while isinstance(arm, ProjectNode):
                arm = arm.source
            return (isinstance(arm, (TopNNode, LimitNode))
                    and arm.count <= node.count)

        if all(bounded(i) for i in union.inputs):
            return None
        arms = [
            i if bounded(i) else TopNNode(
                i, list(node.sort_exprs), list(node.ascending), node.count,
                node.nulls_first)
            for i in union.inputs
        ]
        return TopNNode(UnionNode(arms), list(node.sort_exprs),
                        list(node.ascending), node.count, node.nulls_first)


class PushLimitThroughRowPreserving(Rule):
    """Limit commutes exactly with 1:1 row-preserving nodes: mark
    joins (one output per probe row), left joins with a unique build
    side, and scalar-subquery cross products — limiting the probe
    first shrinks the join's work (PushLimitThroughSemiJoin.java /
    PushLimitThroughMarkDistinct.java; their SemiJoinNode is this
    engine's mark join)."""

    @staticmethod
    def _row_preserving(n: PlanNode) -> bool:
        if isinstance(n, CrossSingleNode):
            return True
        return (isinstance(n, JoinNode) and not n.use_index
                and (n.kind == "mark"
                     or (n.kind == "left" and n.unique_build)))

    pattern = Pattern.type_of(LimitNode).where(
        lambda n: PushLimitThroughRowPreserving._row_preserving(n.source)
        and not isinstance(n.source.sources[0], LimitNode))

    def apply(self, node: LimitNode) -> Optional[PlanNode]:
        j = node.source
        limited = LimitNode(j.left, node.count)
        if isinstance(j, CrossSingleNode):
            return CrossSingleNode(limited, j.right)
        return dataclasses.replace(j, left=limited)


class PruneCountAggregationOverScalar(Rule):
    """count(*) over a relation that produces exactly one row is the
    literal 1 — no need to execute the source
    (PruneCountAggregationOverScalar.java)."""

    @staticmethod
    def _scalar(n: PlanNode) -> bool:
        while isinstance(n, ProjectNode):  # projections preserve rows
            n = n.source
        if isinstance(n, ValuesNode) and len(n.rows) == 1:
            return True
        return (isinstance(n, AggregationNode) and not n.group_exprs
                and n.step in ("single", "final"))

    pattern = Pattern.type_of(AggregationNode).where(
        lambda n: n.step == "single" and not n.group_exprs and n.aggs
        and all(a.fn == "count_star" and a.filter is None for a in n.aggs)
        and PruneCountAggregationOverScalar._scalar(n.source))

    def apply(self, node: AggregationNode) -> Optional[PlanNode]:
        from presto_tpu.types import BIGINT

        return ValuesNode(names=list(node.agg_names),
                          types=[BIGINT] * len(node.aggs),
                          rows=[tuple(1 for _ in node.aggs)])


class GatherAndMergeWindows(Rule):
    """Adjacent window nodes over the same (partition, order) spec
    merge into one — one partition sort instead of two
    (GatherAndMergeWindows.java).  Fires only when the outer node's
    expressions read the shared source, not the inner's outputs."""

    pattern = Pattern.type_of(WindowNode).with_sources(
        Pattern.type_of(WindowNode))

    def apply(self, node: WindowNode) -> Optional[PlanNode]:
        inner: WindowNode = node.source
        if (node.partition_exprs != inner.partition_exprs
                or node.order_exprs != inner.order_exprs
                or node.ascending != inner.ascending):
            return None
        base = len(inner.source.channels)
        refs: List[int] = []
        for e in list(node.partition_exprs) + list(node.order_exprs):
            refs.extend(_expr_refs(e))
        for f in node.funcs:
            if f.arg is not None:
                refs.extend(_expr_refs(f.arg))
        if any(r >= base for r in refs):
            return None  # outer consumes the inner's function outputs
        return WindowNode(
            inner.source, list(inner.partition_exprs),
            list(inner.order_exprs), list(inner.ascending),
            list(inner.funcs) + list(node.funcs),
            list(inner.func_names) + list(node.func_names))


class PruneUnionColumns(Rule):
    """A pure column-selection projection over UNION ALL moves into
    the arms, so each arm scans only what the query needs
    (PushProjectionThroughUnion.java, restricted to the ColumnRef-only
    pruning case — per-arm dictionaries re-merge in the new union)."""

    pattern = Pattern.type_of(ProjectNode).where(
        lambda n: isinstance(n.source, UnionNode)
        and all(isinstance(p, ColumnRef) for p in n.projections)
        and [p.index for p in n.projections]
        != list(range(len(n.source.channels))))

    def apply(self, node: ProjectNode) -> Optional[PlanNode]:
        union: UnionNode = node.source
        arms = []
        for arm in union.inputs:
            if isinstance(arm, ProjectNode):
                # compose: select the surviving expressions directly
                projs = [arm.projections[p.index] for p in node.projections]
                arms.append(ProjectNode(arm.source, projs, list(node.names)))
            else:
                src = arm.channels
                projs = [ColumnRef(type=src[p.index].type, index=p.index)
                         for p in node.projections]
                arms.append(ProjectNode(arm, projs, list(node.names)))
        return UnionNode(arms)


class EvaluateZeroSample(Rule):
    """TABLESAMPLE at 0 percent is the empty relation — no scan
    (EvaluateZeroSample.java)."""

    pattern = Pattern.type_of(TableScanNode).where(
        lambda n: n.sample is not None and n.sample[1] <= 0)

    def apply(self, node: TableScanNode) -> Optional[PlanNode]:
        return _empty_like(node)  # keeps channel dictionaries


class RemoveFullSample(Rule):
    """TABLESAMPLE at >= 100 percent samples nothing away — drop the
    clause so scans fuse normally (RemoveFullSample.java)."""

    pattern = Pattern.type_of(TableScanNode).where(
        lambda n: n.sample is not None and n.sample[1] >= 100)

    def apply(self, node: TableScanNode) -> Optional[PlanNode]:
        import dataclasses as _dc

        return _dc.replace(node, sample=None)


class RemoveUnreferencedScalarApply(Rule):
    """A scalar-subquery cross product whose single-row side is never
    read by the consuming projection evaluates for nothing — drop it
    (RemoveUnreferencedScalarApplyNodes.java / the lateral twin)."""

    @staticmethod
    def _fires(n: ProjectNode) -> bool:
        if not isinstance(n.source, CrossSingleNode):
            return False
        base = len(n.source.left.channels)
        return all(r < base for p in n.projections for r in _expr_refs(p))

    pattern = Pattern.type_of(ProjectNode).where(
        lambda n: RemoveUnreferencedScalarApply._fires(n))

    def apply(self, node: ProjectNode) -> Optional[PlanNode]:
        return ProjectNode(node.source.left, list(node.projections),
                           list(node.names))


DEFAULT_RULES: List[Rule] = [
    MergeAdjacentFilters(),
    PushFilterThroughProject(),
    MergeAdjacentProjects(),
    RemoveIdentityProjection(),
    EvaluateConstantFilter(),
    RecordScanConstraints(),
    PushLimitThroughProject(),
    MergeLimits(),
    MergeLimitWithSort(),
    PushLimitThroughUnion(),
    FlattenUnions(),
    PushLimitIntoTableScan(),
    RemoveRedundantDistinct(),
    EvaluateZeroLimit(),
    PropagateEmptyValues(),
    SimplifyExpressions(),
    PruneOrderByInAggregation(),
    PushTopNThroughProject(),
    PushFilterThroughSort(),
    PushFilterThroughUnion(),
    SimplifyCountOverConstant(),
    MergeLimitWithTopN(),
    PushTopNThroughUnion(),
    PushLimitThroughRowPreserving(),
    PruneCountAggregationOverScalar(),
    GatherAndMergeWindows(),
    PruneUnionColumns(),
    EvaluateZeroSample(),
    RemoveFullSample(),
    RemoveUnreferencedScalarApply(),
]


class OptimizerStats:
    """Per-optimize() rule-application bookkeeping, surfaced by
    EXPLAIN (TYPE VALIDATE) / EXPLAIN ANALYZE VERBOSE so plan-diff
    investigations can see which rules moved a plan without a
    debugger."""

    def __init__(self):
        self.iterations = 0
        self.rule_hits: Dict[str, int] = {}

    def record(self, rule_name: str) -> None:
        self.iterations += 1
        self.rule_hits[rule_name] = self.rule_hits.get(rule_name, 0) + 1

    def summary(self) -> str:
        if not self.iterations:
            return "optimizer: 0 iterations"
        hits = ", ".join(
            f"{name}={count}"
            for name, count in sorted(self.rule_hits.items(),
                                      key=lambda kv: (-kv[1], kv[0])))
        return f"optimizer: {self.iterations} iterations, rule hits: {hits}"


class IterativeOptimizer:
    """Bottom-up fixpoint driver (IterativeOptimizer.java's exploration
    loop over a Memo, with node identity as the group key).

    With ``validate=True`` every successful ``Rule.apply`` is gated by
    ``analysis.soundness.check_rewrite`` — an unsound rewrite raises
    ``RewriteSoundnessError`` naming the rule (the per-rewrite analog
    of the reference's PlanSanityChecker between-optimizer runs)."""

    def __init__(self, rules: Optional[List[Rule]] = None,
                 max_iterations: int = 1000, validate: bool = False):
        self.rules = rules if rules is not None else DEFAULT_RULES
        self.max_iterations = max_iterations
        self.validate = validate
        self.stats = OptimizerStats()

    def optimize(self, root: PlanNode) -> PlanNode:
        self._budget = self.max_iterations
        self.stats = OptimizerStats()
        return self._explore(root)

    def _explore(self, node: PlanNode) -> PlanNode:
        # children first so parents see stable sources
        node = self._rewrite_sources(node)
        progress = True
        while progress and self._budget > 0:
            progress = False
            for rule in self.rules:
                if rule.pattern.match(node) is None:
                    continue
                out = rule.apply(node)
                if out is None or out is node:
                    continue
                self._budget -= 1
                rname = type(rule).__name__
                self.stats.record(rname)
                METRICS.counter("optimizer.rule_applications").inc()
                if self.validate:
                    self._check(rname, node, out)
                node = self._rewrite_sources(out)
                progress = True
                break
        return node

    def _check(self, rule_name: str, before: PlanNode,
               after: PlanNode) -> None:
        from presto_tpu.analysis.soundness import (RewriteSoundnessError,
                                                   check_rewrite)

        violations = check_rewrite(rule_name, before, after)
        if violations:
            METRICS.counter("optimizer.rule_violations").inc()
            raise RewriteSoundnessError(rule_name, violations, before, after)

    def _rewrite_sources(self, node: PlanNode) -> PlanNode:
        srcs = node.sources
        if not srcs:
            return node
        new = [self._explore(s) for s in srcs]
        if all(a is b for a, b in zip(new, srcs)):
            return node
        _replace_sources(node, new)
        if self.validate and any(
                a is not b and a not in node.sources
                for a, b in zip(new, srcs) if a is not b):
            from presto_tpu.analysis.soundness import (RewriteSoundnessError,
                                                       RewriteViolation)

            METRICS.counter("optimizer.rule_violations").inc()
            raise RewriteSoundnessError(
                "_replace_sources",
                [RewriteViolation(
                    "sources-replaced", "_replace_sources",
                    f"{type(node).__name__} still references a stale "
                    "source after replacement — in-place source "
                    "mutation did not take effect")],
                node)
        return node


def _replace_sources(node: PlanNode, new_sources: List[PlanNode]) -> None:
    """In-place source replacement: plan nodes are plain dataclasses
    whose source fields are named 'source' / 'left' / 'right' /
    'inputs'."""
    if hasattr(node, "source"):
        node.source = new_sources[0]
        return
    if hasattr(node, "left"):
        node.left, node.right = new_sources
        return
    if hasattr(node, "inputs"):
        node.inputs = list(new_sources)
        return
    raise AssertionError(f"cannot replace sources of {type(node).__name__}")
