"""REST protocol client.

Reference analog: ``presto-client``'s ``StatementClientV1.java`` — POST
the statement, then follow ``nextUri`` pages until exhausted.  Uses
stdlib urllib (no external HTTP dependency).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Iterator, List, Optional, Tuple


class StatementClient:
    def __init__(self, server_uri: str, timeout: float = 650.0):
        self.server_uri = server_uri.rstrip("/")
        # per-request bound: a wedged coordinator must fail the client
        # call, not hang it (the naked-urlopen lint contract).  Sized
        # past the server's 600s blocking-POST long-poll bound so the
        # client always receives the server's page (terminal state or
        # nextUri), never a client-side timeout first
        self.timeout = timeout
        # id of the last executed statement (the CLI's --doctor key)
        self.last_query_id: Optional[str] = None

    def execute(self, sql: str,
                on_progress=None) -> Tuple[List[dict], List[tuple]]:
        """Run a statement; returns (columns, rows).

        ``on_progress``: optional callback receiving each page's
        ``stats`` dict.  When set, the statement POSTs with
        ``X-Presto-Async`` and the server returns immediately — pages
        while the query runs carry ``progressPercentage`` / ``stages``
        and no data; the loop below polls ``nextUri`` until the state
        is terminal (the reference StatementClient's real shape)."""
        headers = {"Content-Type": "text/plain"}
        if on_progress is not None:
            headers["X-Presto-Async"] = "1"
        req = urllib.request.Request(
            f"{self.server_uri}/v1/statement",
            data=sql.encode(),
            method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            page = json.load(resp)
        self.last_query_id = page.get("id")
        if on_progress is not None and page.get("stats"):
            on_progress(page["stats"])
        if page.get("error"):
            raise RuntimeError(self._error_text(page))
        columns = page.get("columns") or []
        rows = [tuple(r) for r in page.get("data", [])]
        while page.get("nextUri"):
            with urllib.request.urlopen(page["nextUri"],
                                        timeout=self.timeout) as resp:
                page = json.load(resp)
            if on_progress is not None and page.get("stats"):
                on_progress(page["stats"])
            if page.get("error"):
                raise RuntimeError(self._error_text(page))
            if not columns and page.get("columns"):
                columns = page["columns"]  # set once the query finishes
            rows.extend(tuple(r) for r in page.get("data", []))
        return columns, rows

    @staticmethod
    def _error_text(page: dict) -> str:
        """Statement error with its policy code when one is present
        (QUERY_QUEUE_FULL / EXCEEDED_QUEUE_TIME / EXCEEDED_TIME_LIMIT)."""
        code = page.get("errorCode")
        return f"[{code}] {page['error']}" if code else str(page["error"])

    def server_info(self) -> dict:
        with urllib.request.urlopen(f"{self.server_uri}/v1/info",
                                    timeout=10.0) as resp:
            return json.load(resp)

    def queries(self) -> list:
        with urllib.request.urlopen(f"{self.server_uri}/v1/query",
                                    timeout=10.0) as resp:
            return json.load(resp)

    def doctor(self, query_id: str) -> dict:
        """``GET /v1/query/<id>/doctor``: the ranked post-query
        diagnosis (obs/doctor.py findings)."""
        with urllib.request.urlopen(
                f"{self.server_uri}/v1/query/{query_id}/doctor",
                timeout=10.0) as resp:
            return json.load(resp)
