"""REST protocol client.

Reference analog: ``presto-client``'s ``StatementClientV1.java`` — POST
the statement, then follow ``nextUri`` pages until exhausted.  Uses
stdlib urllib (no external HTTP dependency).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Iterator, List, Optional, Tuple


class StatementClient:
    def __init__(self, server_uri: str):
        self.server_uri = server_uri.rstrip("/")

    def execute(self, sql: str) -> Tuple[List[dict], List[tuple]]:
        """Run a statement; returns (columns, rows)."""
        req = urllib.request.Request(
            f"{self.server_uri}/v1/statement",
            data=sql.encode(),
            method="POST",
            headers={"Content-Type": "text/plain"},
        )
        with urllib.request.urlopen(req) as resp:
            page = json.load(resp)
        if page.get("error"):
            raise RuntimeError(page["error"])
        columns = page.get("columns", [])
        rows = [tuple(r) for r in page.get("data", [])]
        while page.get("nextUri"):
            with urllib.request.urlopen(page["nextUri"]) as resp:
                page = json.load(resp)
            if page.get("error"):
                raise RuntimeError(page["error"])
            rows.extend(tuple(r) for r in page.get("data", []))
        return columns, rows

    def server_info(self) -> dict:
        with urllib.request.urlopen(f"{self.server_uri}/v1/info") as resp:
            return json.load(resp)

    def queries(self) -> list:
        with urllib.request.urlopen(f"{self.server_uri}/v1/query") as resp:
            return json.load(resp)
