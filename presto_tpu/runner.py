"""Top-level query runner: SQL in, rows out.

Reference analog: ``testing/LocalQueryRunner.java:207`` — the
full-pipeline in-process harness (parse -> analyze -> plan -> execute)
used by the reference's tests and benchmarks, and the model for the
coordinator's query lifecycle (execution/SqlQueryExecution.java).
Statement dispatch mirrors the coordinator's non-query statement
handlers (EXPLAIN via QueryExplainer, SET SESSION, SHOW metadata).
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner, MaterializedResult, QueryStats
from presto_tpu.session import Session
from presto_tpu.sql import ast
from presto_tpu.sql.binder import Binder
from presto_tpu.sql.parser import parse_statement
from presto_tpu.types import BIGINT, VARCHAR, Type


class QueryRunner:
    def __init__(self, catalog: Catalog, session: Optional[Session] = None, jit: bool = True,
                 memory_pool=None):
        from presto_tpu.events import EventListenerManager

        self.catalog = catalog
        self.session = session or Session()
        self.binder = Binder(catalog)
        self._jit_default = jit
        self.memory_pool = memory_pool
        self.events = EventListenerManager()
        self.executor = self._make_executor()
        # plan cache: repeated executions of the same SQL reuse the same
        # plan-node identities, so the executor's compiled-chain caches
        # hit and nothing retraces (ExpressionCompiler's cache role,
        # sql/gen/ExpressionCompiler.java:53 cache field)
        self._plans = {}

    def _make_executor(self) -> LocalRunner:
        cap = self.session.get("split_capacity") or None
        return LocalRunner(
            self.catalog,
            jit=self._jit_default and self.session.get("jit"),
            split_capacity=cap,
            memory_pool=self.memory_pool,
        )

    # ------------------------------------------------------------------
    def plan(self, sql: str):
        plan = self._plans.get(sql)
        if plan is None:
            plan = self.binder.plan(sql)
            self._plans[sql] = plan
        return plan

    def execute(self, sql: str) -> MaterializedResult:
        import time

        from presto_tpu.events import (
            QueryCompletedEvent, QueryCreatedEvent, new_query_id,
        )

        stmt = parse_statement(sql)

        if isinstance(stmt, (ast.Query, ast.Union)):
            qid = new_query_id()
            t0 = time.time()
            self.events.query_created(
                QueryCreatedEvent(qid, sql, self.session.user, t0)
            )
            try:
                res = self.executor.run(self._plan_cached(sql, stmt))
            except Exception as e:
                self.events.query_completed(QueryCompletedEvent(
                    qid, sql, self.session.user, "FAILED", t0, time.time(),
                    error=f"{type(e).__name__}: {e}",
                ))
                raise
            self.events.query_completed(QueryCompletedEvent(
                qid, sql, self.session.user, "FINISHED", t0, time.time(),
                rows=len(res.rows),
            ))
            return res

        if isinstance(stmt, ast.Explain):
            plan = self.binder.plan_ast(stmt.query)
            if stmt.analyze:
                stats = QueryStats()
                self.executor.stats = stats
                try:
                    self.executor.run(plan)
                finally:
                    self.executor.stats = None
                text = self.executor.explain_with_stats(plan, stats)
            else:
                text = self.executor.explain(plan)
            return MaterializedResult(["Query Plan"], [VARCHAR], [(text,)])

        if isinstance(stmt, ast.SetSession):
            self.session.set(stmt.name, stmt.value)
            # executor knobs may have changed; rebuild (plans survive)
            self.executor = self._make_executor()
            return MaterializedResult(["result"], [VARCHAR], [("SET SESSION",)])

        if isinstance(stmt, ast.ShowSession):
            rows = [
                (name, str(value), str(default), desc)
                for name, value, default, desc in self.session.describe()
            ]
            return MaterializedResult(
                ["name", "value", "default", "description"], [VARCHAR] * 4, rows
            )

        if isinstance(stmt, ast.ShowTables):
            names = sorted(
                t
                for cname in self.catalog._connectors
                for t in self.catalog.connector(cname).table_names()
            )
            return MaterializedResult(["table"], [VARCHAR], [(n,) for n in names])

        if isinstance(stmt, ast.ShowColumns):
            handle = self.catalog.resolve(stmt.table)
            rows = [(c.name, repr(c.type)) for c in handle.columns]
            return MaterializedResult(["column", "type"], [VARCHAR, VARCHAR], rows)

        raise ValueError(f"unsupported statement {stmt!r}")

    def _plan_cached(self, sql: str, q: ast.Query):
        plan = self._plans.get(sql)
        if plan is None:
            plan = self.binder.plan_ast(q)
            self._plans[sql] = plan
        return plan

    def explain(self, sql: str) -> str:
        return self.executor.explain(self.plan(sql))
