"""Top-level query runner: SQL in, rows out.

Reference analog: ``testing/LocalQueryRunner.java:207`` — the
full-pipeline in-process harness (parse -> analyze -> plan -> execute)
used by the reference's tests and benchmarks, and the model for the
coordinator's query lifecycle (execution/SqlQueryExecution.java).
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner, MaterializedResult
from presto_tpu.sql.binder import Binder


class QueryRunner:
    def __init__(self, catalog: Catalog, jit: bool = True):
        self.catalog = catalog
        self.binder = Binder(catalog)
        self.executor = LocalRunner(catalog, jit=jit)
        # plan cache: repeated executions of the same SQL reuse the same
        # plan-node identities, so the executor's compiled-chain caches
        # hit and nothing retraces (ExpressionCompiler's cache role,
        # sql/gen/ExpressionCompiler.java:53 cache field)
        self._plans = {}

    def plan(self, sql: str):
        plan = self._plans.get(sql)
        if plan is None:
            plan = self.binder.plan(sql)
            self._plans[sql] = plan
        return plan

    def execute(self, sql: str) -> MaterializedResult:
        return self.executor.run(self.plan(sql))

    def explain(self, sql: str) -> str:
        return self.executor.explain(self.plan(sql))
