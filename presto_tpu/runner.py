"""Top-level query runner: SQL in, rows out.

Reference analog: ``testing/LocalQueryRunner.java:207`` — the
full-pipeline in-process harness (parse -> analyze -> plan -> execute)
used by the reference's tests and benchmarks, and the model for the
coordinator's query lifecycle (execution/SqlQueryExecution.java).
Statement dispatch mirrors the coordinator's non-query statement
handlers (EXPLAIN via QueryExplainer, SET SESSION, SHOW metadata).
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner, MaterializedResult, QueryStats
from presto_tpu.session import Session
from presto_tpu.sql import ast
from presto_tpu.sql.binder import Binder
from presto_tpu.sql.parser import parse_statement
from presto_tpu.types import BIGINT, VARCHAR, Type


def _substitute_params(node, params):
    """Replace ? Parameter nodes with the EXECUTE ... USING expressions
    (sql/tree/Parameter.java rewriting in the reference's
    ParameterRewriter)."""
    import dataclasses as _dc

    if isinstance(node, ast.Parameter):
        if node.index >= len(params):
            raise ValueError(
                f"parameter ?{node.index + 1} has no USING value")
        return params[node.index]
    if isinstance(node, tuple):
        # nested tuples (With.ctes pairs, Case.whens) recurse
        return tuple(_substitute_params(x, params) for x in node)
    if not isinstance(node, ast.Node):
        return node
    changes = {}
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, (tuple, ast.Node)):
            nv = _substitute_params(v, params)
            if nv is not v and nv != v:
                changes[f.name] = nv
            elif isinstance(nv, tuple) and any(
                a is not b for a, b in zip(nv, v)
            ):
                changes[f.name] = nv
    return _dc.replace(node, **changes) if changes else node


def _count_parameters(node) -> int:
    """Number of ? placeholders in a statement tree."""
    import dataclasses as _dc

    if isinstance(node, ast.Parameter):
        return 1
    if isinstance(node, tuple):
        return sum(_count_parameters(x) for x in node)
    if not isinstance(node, ast.Node):
        return 0
    return sum(_count_parameters(getattr(node, f.name))
               for f in _dc.fields(node))


class QueryRunner:
    def __init__(self, catalog: Catalog, session: Optional[Session] = None, jit: bool = True,
                 memory_pool=None, access_control=None, programs=None):
        from presto_tpu.events import EventListenerManager
        from presto_tpu.security import AccessControl

        self.catalog = catalog
        self.session = session or Session()
        # program registry shared by every executor this runner builds
        # (SET SESSION rebuilds the executor; compiled programs survive)
        self.programs = programs
        self.binder = Binder(catalog, session=self.session)
        self._jit_default = jit
        # Accounting is always-on (memory/MemoryPool.java:43 tracks
        # every operator unconditionally): None selects the process
        # pool sized to detected HBM/RAM; False disables (tests only).
        if memory_pool is None:
            from presto_tpu.memory import default_memory_pool

            memory_pool = default_memory_pool()
        self.memory_pool = memory_pool or None
        self.access_control = access_control or AccessControl()
        self.events = EventListenerManager()
        # per-session explicit transaction (transaction/TransactionManager.java)
        from presto_tpu.transaction import TransactionManager

        self.transactions = TransactionManager()
        self._open_tx = None
        # PREPARE name FROM <query> registry (StatementResource's
        # prepared-statement session map analog)
        self._prepared = {}
        # CALL registry (ProcedureRegistry.java); kill_query ships
        # built-in like the reference's KillQueryProcedure
        self.procedures = {
            "system.runtime.kill_query": self._kill_query_procedure,
        }
        self.executor = self._make_executor()
        # estimate-vs-actual: a warehouse-backed catalog persists its
        # plan history next to the metastore (obs/history.py); catalogs
        # without a warehouse share the process in-memory store
        try:
            from presto_tpu.obs.history import (
                ensure_default_history, history_path,
            )
            from presto_tpu.storage.warehouse import WarehouseConnector

            for _c in catalog._connectors.values():
                if isinstance(_c, WarehouseConnector):
                    ensure_default_history(history_path(_c.root))
                    break
        except Exception:
            pass  # history must never block runner construction
        # plan cache: repeated executions of the same SQL reuse the same
        # plan-node identities, so the executor's compiled-chain caches
        # hit and nothing retraces (ExpressionCompiler's cache role,
        # sql/gen/ExpressionCompiler.java:53 cache field)
        self._plans = {}

    def _make_executor(self) -> LocalRunner:
        cap = self.session.get("split_capacity") or None
        ex = LocalRunner(
            self.catalog,
            jit=self._jit_default and self.session.get("jit"),
            split_capacity=cap,
            memory_pool=self.memory_pool,
            programs=self.programs,
            # 0 / -1 = process default (config/env resolved once in
            # exec/tasks.py); any positive session value wins per query
            task_concurrency=int(self.session.get("task_concurrency")) or None,
            task_prefetch=int(self.session.get("task_prefetch")),
        )
        ex.merge_sort = bool(self.session.get("distributed_sort"))
        return ex

    # ------------------------------------------------------------------
    def plan(self, sql: str):
        plan = self._plans.get(sql)
        if plan is None:
            plan = self._validated(self.binder.plan(sql))
            self._plans[sql] = plan
        return plan

    def _validated(self, plan):
        """Run the static plan/IR validator when always-on checking is
        enabled (``validate_plans`` session property or the process-wide
        ``PRESTO_TPU_VALIDATE_PLANS`` switch the test harness sets);
        cached plans validate once at bind time.  The kernel-soundness
        tier (``validate_kernels`` / ``PRESTO_TPU_VALIDATE_KERNELS``)
        gates the same way: the abstract interpreter proves overflow,
        lossy-cast, division, accumulator, and null-policy soundness of
        every compiled expression before the plan can execute."""
        from presto_tpu.analysis import (kernel_validation_enabled,
                                         validation_enabled)

        if validation_enabled() or self.session.get("validate_plans"):
            from presto_tpu.analysis import assert_valid

            assert_valid(plan)
        if kernel_validation_enabled() or self.session.get("validate_kernels"):
            from presto_tpu.analysis import assert_kernel_sound

            assert_kernel_sound(plan)
        return plan

    def _tracing_enabled(self) -> bool:
        """Span tracing is on when the ``trace`` session property asks
        for it or a trace directory is configured (query.trace-dir /
        PRESTO_TPU_TRACE_DIR) — otherwise every span call is the no-op
        fast path (obs/trace.py)."""
        from presto_tpu import obs

        try:
            if self.session.get("trace"):
                return True
        except KeyError:
            pass
        return obs.trace_dir() is not None

    def execute(self, sql: str, query_id=None,
                trace_token: Optional[str] = None) -> MaterializedResult:
        import time

        from presto_tpu.events import (
            QueryCompletedEvent, QueryCreatedEvent, new_query_id,
        )

        t_q0 = time.perf_counter()
        stmt = parse_statement(sql)
        parse_s = time.perf_counter() - t_q0

        if isinstance(stmt, (ast.Query, ast.Union, ast.With, ast.SetOp)):
            from presto_tpu import obs
            from presto_tpu.events import new_trace_token

            qid = query_id or new_query_id()
            trace = (trace_token or self.session.trace_token
                     or new_trace_token())
            tracer = None
            if self._tracing_enabled():
                tracer = obs.register(obs.Tracer(qid, trace))
                tracer.add_complete("parse", "lifecycle", t_q0, parse_s)
            t0 = time.time()
            obs.METRICS.counter("query.started").inc()
            obs.TASKS.start(qid, "local", trace_token=trace)
            # live progress: always registered (the statement protocol,
            # CLI and UI read it) — publication is one thread-local
            # read per split when nothing else is active
            progress = obs.register_progress(obs.QueryProgress(qid))
            # resource timeline: admission may have created it already
            # (queue-depth points + queued/blocked annotations land
            # before execution starts); None when timelines are off
            timeline = obs.ensure_timeline(qid)
            self.events.query_created(
                QueryCreatedEvent(qid, sql, self.session.user, t0, trace_token=trace)
            )
            planning_s: Optional[float] = None
            cache_hit: Optional[bool] = None
            with obs.tracing(tracer), obs.publishing(progress), \
                    obs.recording(timeline):
                try:
                    t1 = time.perf_counter()
                    with obs.span("plan", cat="lifecycle"):
                        plan = self._plan_cached(sql, stmt)
                        self._check_access(plan)
                        # serving tier: (key, versions) captured AT PLAN
                        # TIME so a write racing this execution leaves
                        # the stored entry stale-by-version, never
                        # silently current (serving/cache.py)
                        prepared = self._result_cache_prepared(plan)
                    planning_s = time.perf_counter() - t1
                    t1 = time.perf_counter()
                    # estimate-vs-actual: per-operator actuals sink,
                    # opt-in (one device sync per page)
                    qstats = (QueryStats()
                              if self.session.get("collect_stats") else None)
                    with obs.span("execute", cat="lifecycle"):
                        res = None
                        if prepared is not None:
                            res = self._result_cache_hit(plan, prepared)
                            cache_hit = res is not None
                        if res is None:
                            res = self._run_plan(plan, qid, stats=qstats)
                    execution_s = time.perf_counter() - t1
                except Exception as e:
                    obs.METRICS.counter("query.failed").inc()
                    progress.mark_done()
                    err = f"{type(e).__name__}: {e}"
                    obs.TASKS.finish(qid, "FAILED", error=err)
                    self._finalize_trace(tracer, t_q0)
                    self.events.query_completed(QueryCompletedEvent(
                        qid, sql, self.session.user, "FAILED", t0, time.time(),
                        error=err, trace_token=trace,
                        planning_ms=self._ms(planning_s),
                    ))
                    raise
            # populate the result cache AFTER the query succeeded (and
            # outside the failure path: a cache anomaly must never fail
            # an already-executed query).  The entry carries the
            # versions captured at plan time, so a write that raced the
            # execution leaves it stale-by-version.
            if prepared is not None and not cache_hit:
                from presto_tpu.serving.cache import default_result_cache

                default_result_cache().store(
                    prepared, res.names, res.types, res.rows)
            progress.mark_done()
            compile_ms = (round(tracer.total_s("xla_compile") * 1e3, 3)
                          if tracer is not None else None)
            obs.METRICS.counter("query.finished").inc()
            obs.METRICS.counter("query.planning_seconds_total").inc(planning_s)
            obs.METRICS.counter("query.execution_seconds_total").inc(execution_s)
            obs.METRICS.histogram("query.execution_ms").observe(execution_s * 1e3)
            obs.TASKS.finish(qid, "FINISHED", rows=len(res.rows))
            # split-scheduler footprint onto the task row (local tier
            # only: a mesh run's executor stats would be stale).  The
            # thread-local accumulator is read, not last_task_stats —
            # concurrent queries on one runner must not swap footprints
            ts = self.executor._task_stats.as_dict()
            if not cache_hit and not self.session.get("distributed") \
                    and ts.get("splits"):
                obs.TASKS.update_scheduler(
                    qid, ts["splits"], ts["concurrency"],
                    ts["stall_s"] * 1e3, ts["prefetch_hits"])
            # per-run outcome off the result object (not the shared
            # runner fields — concurrent queries would swap stats)
            dist_stages = getattr(res, "dist_stages", None)
            dist_fallback = getattr(res, "dist_fallback", None)
            # stage times ride the result for the statement protocol
            res.planning_ms = self._ms(planning_s)
            res.compile_ms = compile_ms
            res.execution_ms = self._ms(execution_s)
            # serving-tier surfaces: whether this result came from the
            # structural cache, and the executor's observed peak bytes
            # (the admission controller's projection source for the
            # next run of this statement)
            res.cache_hit = cache_hit
            res.query_id = qid  # embedded callers (CLI --doctor) key
            # the timeline/doctor registries off the result itself
            res.peak_bytes = (0 if cache_hit
                              else getattr(self.executor,
                                           "last_peak_bytes", 0))
            self._finalize_trace(tracer, t_q0)
            # post-query diagnosis (obs/doctor.py): ranked findings from
            # the rulebook over trace + timeline + progress; they ride
            # the result (statement protocol), the timeline (the
            # /v1/query/<id>/doctor endpoint) and the completion event
            # (query-log `findings` field)
            wall_ms = ((res.planning_ms or 0.0) + (res.execution_ms or 0.0))
            queued_ms = memory_blocked_ms = None
            # estimate-vs-actual attribution: the worst-node ratio is
            # annotated BEFORE the doctor runs (its `misestimate` rule
            # reads it), feeds the plan-history store, and rides the
            # result + completion event + query-log line
            worst = None
            if qstats is not None and not cache_hit:
                from presto_tpu.obs.history import (
                    default_history, operator_rows, worst_estimate,
                )

                est_map = getattr(plan, "_estimates", None)
                worst = worst_estimate(qstats, est_map)
                if timeline is not None:
                    if worst is not None:
                        timeline.annotate("worst_estimate", worst)
                    # per-operator detail rows for the web UI /
                    # /v1/query/<id>/operators endpoint
                    timeline.annotate(
                        "operators", operator_rows(qstats, est_map))
                default_history().record_query(qstats, est_map)
            res.worst_estimate = worst
            res.worst_estimate_ratio = worst["ratio"] if worst else None
            if timeline is not None:
                timeline.annotate("wall_ms", wall_ms)
                if dist_fallback:
                    timeline.annotate("dist_fallback", dist_fallback)
                queued_ms = timeline.annotation("queued_ms")
                memory_blocked_ms = timeline.annotation("memory_blocked_ms")
            findings = [f.as_dict() for f in obs.doctor.diagnose(
                qid, tracer=tracer, timeline=timeline, progress=progress,
                wall_ms=wall_ms, dist_fallback=dist_fallback)]
            if timeline is not None:
                timeline.annotate("findings", findings)
            res.findings = findings
            res.queued_ms = queued_ms
            res.memory_blocked_ms = memory_blocked_ms
            self.events.query_completed(QueryCompletedEvent(
                qid, sql, self.session.user, "FINISHED", t0, time.time(),
                rows=len(res.rows), trace_token=trace,
                dist_stages=dist_stages, dist_fallback=dist_fallback,
                planning_ms=res.planning_ms, compile_ms=compile_ms,
                execution_ms=res.execution_ms, cache_hit=cache_hit,
                queued_ms=queued_ms, memory_blocked_ms=memory_blocked_ms,
                findings=findings,
                worst_estimate_ratio=res.worst_estimate_ratio,
            ))
            return res

        if isinstance(stmt, ast.Explain):
            validate = getattr(stmt, "validate", False)
            # EXPLAIN (TYPE VALIDATE) always gates every rewrite, like
            # it always runs the plan validator
            plan = self.binder.plan_ast(
                stmt.query, validate_rewrites=True if validate else None)
            if validate:
                # parse + bind succeeded; now the static tier: the
                # plan/IR validator (analysis/) checks type soundness,
                # null-mask policy, ladder conformance and signature
                # determinism — PlanValidationError propagates with
                # node-specific diagnostics (EXPLAIN (TYPE VALIDATE));
                # every rewrite already passed the soundness gate above
                from presto_tpu.analysis import (assert_kernel_sound,
                                                 assert_valid)
                from presto_tpu.types import BOOLEAN

                assert_valid(plan)
                # kernel-soundness tier: interval/overflow/null-policy
                # proof over every compiled expression (KernelSoundness-
                # Error carries node-attributed diagnostics)
                assert_kernel_sound(plan)
                report = getattr(plan, "_optimizer_report", None)
                summary = report.summary() if report else "optimizer: n/a"
                return MaterializedResult(
                    ["Valid", "Optimizer"], [BOOLEAN, VARCHAR],
                    [(True, summary)])
            if getattr(stmt, "distributed", False):
                from presto_tpu.parallel.fragment import explain_distributed

                text = explain_distributed(
                    plan, catalog=self.catalog,
                    min_stage_rows=int(
                        self.session.get("distributed_min_stage_rows")))
                return MaterializedResult(["Query Plan"], [VARCHAR], [(text,)])
            if stmt.analyze and getattr(stmt, "verbose", False):
                # the verbose re-execution runs under its own tracer +
                # timeline so the doctor can append a `diagnosis:` block
                # (EXPLAIN has no client query id; a synthetic one keys
                # the registries like any other query)
                from presto_tpu import obs
                from presto_tpu.events import new_query_id

                qid = query_id or new_query_id()
                tracer = obs.register(obs.Tracer(qid))
                timeline = obs.ensure_timeline(qid)
                progress = obs.register_progress(obs.QueryProgress(qid))
                t1 = time.perf_counter()
                with obs.tracing(tracer), obs.publishing(progress), \
                        obs.recording(timeline):
                    text = self.executor.explain_analyze_verbose(plan)
                wall_ms = (time.perf_counter() - t1) * 1e3
                progress.mark_done()
                findings = [f.as_dict() for f in obs.doctor.diagnose(
                    qid, tracer=tracer, timeline=timeline,
                    progress=progress, wall_ms=wall_ms)]
                if timeline is not None:
                    timeline.annotate("findings", findings)
                text = obs.doctor.format_findings(findings) + "\n" + text
            elif stmt.analyze:
                stats = QueryStats()
                stats.register_plan(plan)
                if self.session.get("distributed"):
                    # a distributed session's ANALYZE must execute on
                    # the tier the query would actually use — running
                    # local-only silently dropped every worker-fragment
                    # operator from the output
                    self._distributed().run(plan, stats=stats)
                else:
                    self.executor.stats = stats
                    try:
                        self.executor.run(plan)
                    finally:
                        self.executor.stats = None
                text = self.executor.explain_with_stats(
                    plan, stats, misestimate_factor=float(
                        self.session.get("misestimate_factor")))
                # analyze runs feed the plan-history store like any
                # stats-collecting execution
                from presto_tpu.obs.history import default_history

                default_history().record_query(
                    stats, getattr(plan, "_estimates", None))
            else:
                text = self.executor.explain(plan)
            return MaterializedResult(["Query Plan"], [VARCHAR], [(text,)])

        if isinstance(stmt, (ast.Grant, ast.Revoke)):
            ac = self.access_control
            chk = getattr(ac, "check_can_grant", None)
            if chk is not None:
                chk(self.session.user)  # no self-escalation
            fn = getattr(ac, "grant" if isinstance(stmt, ast.Grant)
                         else "revoke", None)
            if fn is None:
                raise ValueError(
                    "the active access control does not support GRANT/REVOKE"
                    " (use GrantingAccessControl)")
            fn(stmt.grantee, stmt.table, stmt.privileges)
            word = "GRANT" if isinstance(stmt, ast.Grant) else "REVOKE"
            return MaterializedResult(["result"], [VARCHAR], [(word,)])

        if isinstance(stmt, ast.AlterTableRename):
            handle = self.catalog.resolve(stmt.name)
            conn = self.catalog.connector(handle.connector_name)
            self._check_tx_writable(handle.connector_name, conn)
            self.access_control.check_can_write(self.session.user,
                                                 handle.table)
            if not hasattr(conn, "rename_table"):
                raise ValueError(
                    f"connector {handle.connector_name} does not support "
                    "ALTER TABLE RENAME")
            new_bare = stmt.new_name.split(".")[-1]
            conn.rename_table(handle.table, new_bare)
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("RENAME",)])

        if isinstance(stmt, ast.SetSession):
            self.session.set(stmt.name, stmt.value)
            # executor knobs may have changed; rebuild (plans survive)
            self.executor = self._make_executor()
            self._dist = None  # mesh/session knobs re-resolve lazily
            return MaterializedResult(["result"], [VARCHAR], [("SET SESSION",)])

        if isinstance(stmt, ast.ShowSession):
            rows = [
                (name, str(value), str(default), desc)
                for name, value, default, desc in self.session.describe()
            ]
            return MaterializedResult(
                ["name", "value", "default", "description"], [VARCHAR] * 4, rows
            )

        if isinstance(stmt, ast.StartTransaction):
            from presto_tpu.transaction import TransactionError

            if self._open_tx is not None:
                raise TransactionError("a transaction is already open")
            self._open_tx = self.transactions.begin(read_only=stmt.read_only)
            return MaterializedResult(["result"], [VARCHAR], [("START TRANSACTION",)])

        if isinstance(stmt, ast.Commit):
            from presto_tpu.transaction import TransactionError

            if self._open_tx is None:
                raise TransactionError("no transaction is open")
            tx, self._open_tx = self._open_tx, None
            self.transactions.commit(tx.tx_id)
            self._invalidate_plans()  # published writes change table state
            return MaterializedResult(["result"], [VARCHAR], [("COMMIT",)])

        if isinstance(stmt, ast.Rollback):
            from presto_tpu.transaction import TransactionError

            if self._open_tx is None:
                raise TransactionError("no transaction is open")
            tx, self._open_tx = self._open_tx, None
            self.transactions.rollback(tx.tx_id)
            return MaterializedResult(["result"], [VARCHAR], [("ROLLBACK",)])

        if isinstance(stmt, (ast.CreateTableAs, ast.InsertInto)):
            return self._write(stmt, query_id=query_id)

        if isinstance(stmt, ast.DropTable):
            # drops route through access control exactly like writes
            # (AccessControlManager.checkCanDropTable analog)
            handle = self.catalog.resolve(stmt.name)
            # access rules key on bare table names
            self.access_control.check_can_write(self.session.user, handle.table)
            conn = self.catalog.connector(handle.connector_name)
            if not hasattr(conn, "drop_table"):
                raise ValueError(f"connector {handle.connector_name} is read-only")
            self._check_tx_writable(handle.connector_name, conn)
            if self._stage_write(handle.connector_name, conn, "drop_table", handle.table):
                return MaterializedResult(["result"], [VARCHAR], [("DROP TABLE (staged)",)])
            conn.drop_table(handle.table)
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("DROP TABLE",)])

        if isinstance(stmt, ast.Prepare):
            self._prepared[stmt.name] = stmt.query
            return MaterializedResult(["result"], [VARCHAR], [("PREPARE",)])

        if isinstance(stmt, ast.Execute):
            q = self._prepared.get(stmt.name)
            if q is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            bound = _substitute_params(q, list(stmt.params))
            # parameters make each execution a distinct plan; don't
            # pollute the text-keyed plan cache
            plan = self._validated(self.binder.plan_ast(bound))
            self._check_access(plan)
            return self.executor.run(plan, query_id=query_id)

        if isinstance(stmt, ast.Deallocate):
            if self._prepared.pop(stmt.name, None) is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            return MaterializedResult(["result"], [VARCHAR], [("DEALLOCATE",)])

        if isinstance(stmt, ast.ShowCatalogs):
            names = sorted(self.catalog._connectors)
            return MaterializedResult(["catalog"], [VARCHAR], [(n,) for n in names])

        if isinstance(stmt, ast.ShowFunctions):
            from presto_tpu.sql.binder import AGG_FUNCTIONS, SCALAR_FUNCTIONS

            window = ["rank", "dense_rank", "row_number", "ntile",
                      "percent_rank", "cume_dist", "lead", "lag",
                      "first_value", "last_value", "nth_value"]
            rows = sorted(
                [(f, "scalar") for f in SCALAR_FUNCTIONS]
                + [(f, "aggregate") for f in AGG_FUNCTIONS]
                + [(f, "window") for f in window]
            )
            return MaterializedResult(["function", "kind"], [VARCHAR, VARCHAR], rows)

        if isinstance(stmt, (ast.DescribeOutput, ast.DescribeInput)):
            q = self._prepared.get(stmt.name)
            if q is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            if isinstance(stmt, ast.DescribeInput):
                # parameter positions; deviation (PARITY.md): every
                # type reports 'unknown' — the reference's
                # DescribeInputRewrite infers types from the parameter
                # context, which this binder does not track
                n = _count_parameters(q)
                rows = [(i, "unknown") for i in range(n)]
                return MaterializedResult(
                    ["Position", "Type"], [BIGINT, VARCHAR], rows)
            # DESCRIBE OUTPUT: bind with NULL parameters to recover the
            # projected column names/types (DescribeOutputRewrite)
            n = _count_parameters(q)
            filled = _substitute_params(q, tuple(ast.NullLit()
                                                 for _ in range(n)))
            plan = self.binder.plan_ast(filled)
            self._check_access(plan)  # no schema leaks on denied tables
            rows = [(nm, repr(t)) for nm, t in
                    zip(plan.output_names, plan.output_types)]
            return MaterializedResult(
                ["Column Name", "Type"], [VARCHAR, VARCHAR], rows)

        if isinstance(stmt, ast.ResetSession):
            self.session.reset(stmt.name)
            # executor knobs may have changed; rebuild (plans survive)
            self.executor = self._make_executor()
            self._dist = None  # mesh/session knobs re-resolve lazily
            return MaterializedResult(["result"], [VARCHAR],
                                      [("RESET SESSION",)])

        if isinstance(stmt, ast.ShowCreateTable):
            handle = self.catalog.resolve(stmt.table)
            cols = ",\n".join(f"   {c.name} {c.type!r}"
                              for c in handle.columns)
            ddl = (f"CREATE TABLE {stmt.table} (\n{cols}\n)")
            return MaterializedResult(["Create Table"], [VARCHAR], [(ddl,)])

        if isinstance(stmt, ast.ShowStats):
            # ShowStatsRewrite.java's table shape: one row per column +
            # the summary row carrying row_count.  Domains live in
            # DEVICE representation (dictionary codes, epoch days,
            # scaled decimal ints) — convert to logical values here.
            import datetime as _dt

            from presto_tpu.types import DOUBLE

            def logical(c, v):
                if v is None:
                    return None
                t = c.type
                if t.is_string:
                    return None  # codes say nothing about value order
                if t.name == "date":
                    return str(_dt.date(1970, 1, 1)
                               + _dt.timedelta(days=int(v)))
                if t.is_decimal:
                    return str(v / 10 ** (t.scale or 0))
                return str(v)

            handle = self.catalog.resolve(stmt.table)
            rows = []
            for c in handle.columns:
                ndv = c.ndv
                if ndv is None and c.dictionary is not None:
                    ndv = len(c.dictionary)
                if ndv is None and c.domain is not None \
                        and c.type.is_integerlike:
                    # width == ndv only for unscaled integer domains
                    ndv = c.domain[1] - c.domain[0] + 1
                lo, hi = (c.domain if c.domain is not None else (None, None))
                if c.type.is_string and c.dictionary is not None:
                    vals = c.dictionary.values
                    lo_s, hi_s = (min(vals), max(vals)) if vals else (None, None)
                else:
                    lo_s, hi_s = logical(c, lo), logical(c, hi)
                rows.append((c.name, float(ndv) if ndv is not None else None,
                             lo_s, hi_s, None))
            rows.append((None, None, None, None, float(handle.row_count)))
            return MaterializedResult(
                ["column_name", "distinct_values_count", "low_value",
                 "high_value", "row_count"],
                [VARCHAR, DOUBLE, VARCHAR, VARCHAR, DOUBLE], rows)

        if isinstance(stmt, ast.Describe):
            rows = self._columns_of(stmt.table)
            return MaterializedResult(["column", "type"], [VARCHAR, VARCHAR], rows)

        if isinstance(stmt, ast.Delete):
            return self._delete(stmt, query_id=query_id)

        if isinstance(stmt, ast.ShowTables):
            names = sorted(
                set(
                    t
                    for cname in self.catalog._connectors
                    for t in self.catalog.connector(cname).table_names()
                )
                | {k[2] for k in self.catalog._views}  # views list too
            )
            return MaterializedResult(["table"], [VARCHAR], [(n,) for n in names])

        if isinstance(stmt, ast.ShowColumns):
            rows = self._columns_of(stmt.table)
            return MaterializedResult(["column", "type"], [VARCHAR, VARCHAR], rows)

        if isinstance(stmt, ast.Use):
            cat = stmt.catalog or self.session.catalog
            if cat is None:
                raise ValueError("USE schema requires a current catalog "
                                 "(USE catalog.schema)")
            if cat not in self.catalog._connectors:
                raise ValueError(f"catalog not found: {cat}")
            if not self.catalog.has_schema(cat, stmt.schema):
                raise ValueError(f"schema not found: {cat}.{stmt.schema}")
            self.session.catalog = cat
            self.session.schema = stmt.schema
            self._invalidate_plans()  # name resolution changed
            return MaterializedResult(["result"], [VARCHAR], [("USE",)])

        if isinstance(stmt, ast.CreateView):
            # bind now so a broken view fails at CREATE, store the text
            # (CreateViewTask.java:44 analyzes the view statement first)
            self.binder.plan(stmt.sql)
            try:
                self.catalog.resolve(stmt.name, session=self.session)
                raise ValueError(
                    f"a table with that name already exists: {stmt.name}")
            except KeyError:
                pass
            self.access_control.check_can_write(
                self.session.user, stmt.name.split(".")[-1])
            self.catalog.create_view(stmt.name, stmt.sql,
                                     session=self.session,
                                     replace=stmt.replace)
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("CREATE VIEW",)])

        if isinstance(stmt, ast.DropView):
            self.access_control.check_can_write(
                self.session.user, stmt.name.split(".")[-1])
            self.catalog.drop_view(stmt.name, session=self.session,
                                   if_exists=stmt.if_exists)
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("DROP VIEW",)])

        if isinstance(stmt, ast.CreateSchema):
            cat = stmt.catalog or self.session.catalog
            if cat is None:
                raise ValueError("CREATE SCHEMA requires a catalog")
            self.catalog.create_schema(cat, stmt.name,
                                       if_not_exists=stmt.if_not_exists)
            return MaterializedResult(["result"], [VARCHAR],
                                      [("CREATE SCHEMA",)])

        if isinstance(stmt, ast.DropSchema):
            cat = stmt.catalog or self.session.catalog
            if cat is None:
                raise ValueError("DROP SCHEMA requires a catalog")
            self.catalog.drop_schema(cat, stmt.name,
                                     if_exists=stmt.if_exists,
                                     cascade=stmt.cascade)
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("DROP SCHEMA",)])

        if isinstance(stmt, ast.RenameSchema):
            cat = stmt.catalog or self.session.catalog
            if cat is None:
                raise ValueError("ALTER SCHEMA requires a catalog")
            self.catalog.rename_schema(cat, stmt.name, stmt.new_name)
            if self.session.catalog == cat and self.session.schema == stmt.name:
                self.session.schema = stmt.new_name
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [("ALTER SCHEMA",)])

        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            if cat is not None:
                rows = [(s,) for s in self.catalog.schemas(cat)]
            else:  # no catalog context: union over catalogs
                seen = sorted({s for c in self.catalog._connectors
                               for s in self.catalog.schemas(c)})
                rows = [(s,) for s in seen]
            return MaterializedResult(["Schema"], [VARCHAR], rows)

        if isinstance(stmt, (ast.AddColumn, ast.DropColumn)):
            handle = self.catalog.resolve(stmt.table, session=self.session)
            self.access_control.check_can_write(self.session.user,
                                                handle.table.split(".")[-1])
            conn = self.catalog.connector(handle.connector_name)
            self._check_tx_writable(handle.connector_name, conn)
            if isinstance(stmt, ast.AddColumn):
                if not hasattr(conn, "add_column"):
                    raise ValueError(
                        f"connector {handle.connector_name} does not "
                        "support ADD COLUMN")
                from presto_tpu.types import parse_type

                conn.add_column(handle.table, stmt.column,
                                parse_type(stmt.type_name))
                msg = "ADD COLUMN"
            else:
                if not hasattr(conn, "drop_column"):
                    raise ValueError(
                        f"connector {handle.connector_name} does not "
                        "support DROP COLUMN")
                conn.drop_column(handle.table, stmt.column)
                msg = "DROP COLUMN"
            self._invalidate_plans()
            return MaterializedResult(["result"], [VARCHAR], [(msg,)])

        if isinstance(stmt, ast.ShowPartitions):
            handle = self.catalog.resolve(stmt.table, session=self.session)
            conn = self.catalog.connector(handle.connector_name)
            pcols = (conn.partition_columns(handle.table)
                     if hasattr(conn, "partition_columns") else [])
            if not pcols or not hasattr(conn, "partitions"):
                raise ValueError(f"table is not partitioned: {stmt.table}")
            rows = [tuple(p.get(c) for c in pcols)
                    for p in conn.partitions(handle.table)]
            types = {c.name: c.type for c in handle.columns}
            return MaterializedResult(
                list(pcols), [types.get(c, VARCHAR) for c in pcols], rows)

        if isinstance(stmt, ast.SetPath):
            self.session.path = stmt.path
            return MaterializedResult(["result"], [VARCHAR], [("SET PATH",)])

        if isinstance(stmt, ast.Call):
            return self._call_procedure(stmt)

        raise ValueError(f"unsupported statement {stmt!r}")

    def _columns_of(self, name: str):
        """(column, type) rows for a table OR a view (views bind their
        stored SQL to recover the projected shape — ShowColumnsRewrite
        consults metadata.getView the same way)."""
        view = self.catalog.lookup_view(name, self.session)
        if view is not None:
            # bind under the view's creation-time namespace, exactly
            # like the binder's reference-time expansion
            vdef = view[1]
            saved = (self.session.catalog, self.session.schema)
            self.session.catalog = vdef.catalog
            self.session.schema = vdef.schema
            try:
                plan = self.binder.plan(vdef.sql)
            finally:
                self.session.catalog, self.session.schema = saved
            return [(n, repr(t))
                    for n, t in zip(plan.output_names, plan.output_types)]
        handle = self.catalog.resolve(name, session=self.session)
        return [(c.name, repr(c.type)) for c in handle.columns]

    def _call_procedure(self, stmt: ast.Call) -> MaterializedResult:
        """CALL proc(literal args) via the procedure registry
        (spi/procedure/Procedure.java + execution/CallTask.java:60 —
        kill_query ships as a procedure there too)."""
        proc = self.procedures.get(stmt.name.lower())
        if proc is None:
            raise ValueError(f"procedure not registered: {stmt.name}")

        def lit(node):
            if isinstance(node, ast.StringLit):
                return node.value
            if isinstance(node, ast.NumberLit):
                v = node.text
                return float(v) if ("." in v or "e" in v.lower()) else int(v)
            if isinstance(node, ast.NullLit):
                return None
            if isinstance(node, ast.Unary) and node.op == "-":
                return -lit(node.operand)
            raise ValueError("CALL arguments must be literals")

        out = proc(self.session, *[lit(a) for a in stmt.args])
        return MaterializedResult(["result"], [VARCHAR],
                                  [(out if out is not None else "CALL",)])

    def register_procedure(self, name: str, fn) -> None:
        """Connector/plugin procedure registration
        (spi/procedure/Procedure.java)."""
        self.procedures[name.lower()] = fn

    def _kill_query_procedure(self, session, query_id, message=None):
        """system.runtime.kill_query(query_id[, message]): fail the
        query's future memory reservations (the in-process analog of
        KillQueryProcedure.java — the coordinator overrides this with
        its query-manager kill)."""
        if self.memory_pool is None:
            raise ValueError("no memory pool; kill_query unavailable")
        freed = self.memory_pool.kill_query(str(query_id))
        return f"killed {query_id} (freed {freed} bytes)"

    def _write(self, stmt, query_id=None) -> MaterializedResult:
        """CTAS / INSERT (TableWriterOperator + TableFinishOperator
        analog: the query result lands in the writable connector and
        the row count is returned)."""
        import numpy as np

        plan = self._validated(self.binder.plan_ast(stmt.query))
        self._check_access(plan)
        if isinstance(stmt, ast.InsertInto):
            self.access_control.check_can_insert(
                self.session.user, stmt.name.split(".")[-1])
        else:
            self.access_control.check_can_write(
                self.session.user, stmt.name.split(".")[-1])

        # resolve the write target BEFORE running the source query so a
        # READ ONLY transaction / non-transactional connector rejects
        # without burning device time on the doomed SELECT
        if isinstance(stmt, ast.CreateTableAs):
            if self.catalog.lookup_view(stmt.name, self.session) is not None:
                raise ValueError(
                    f"a view with that name already exists: {stmt.name}")
            cname, table = self._write_target(stmt.name)
            conn = self.catalog.connector(cname)
        else:
            handle = self.catalog.resolve(stmt.name)
            cname, table = handle.connector_name, handle.table
            conn = self.catalog.connector(cname)
            if not hasattr(conn, "append_pages"):
                raise ValueError(f"connector {cname} is read-only")
        self._check_tx_writable(cname, conn)

        # scaled writers: per-page transfer+compaction runs on a pool
        # that grows while the producer outpaces it; results publish
        # atomically after the whole query succeeds
        # (scheduler/ScaledWriterScheduler.java + TableFinishOperator)
        from presto_tpu.exec.local import GroupCapacityExceeded
        from presto_tpu.writer import ScaledWriter

        while True:
            writer = ScaledWriter(lambda p: p.compact_host())
            done = False
            try:
                for p in self.executor.stream_pages(plan, query_id=query_id):
                    writer.submit(p)
                pages = writer.finish()
                done = True
                break
            except GroupCapacityExceeded:
                pass  # restart with the executor's larger caps
            finally:
                if not done:
                    writer.abort()  # never leak blocked writer threads
        live = [p for p in pages
                if int(np.asarray(p.row_mask).sum()) > 0]
        pages = live or pages[:1]
        rows = sum(int(np.asarray(p.row_mask).sum()) for p in pages)

        if isinstance(stmt, ast.CreateTableAs):
            schema = list(zip(plan.output_names, plan.output_types))
            props = dict(getattr(stmt, "properties", ()) or ())
            if props and not getattr(conn, "supports_table_properties", False):
                raise ValueError(
                    f"connector {cname} does not support CREATE TABLE "
                    f"properties {sorted(props)}")
            if props:
                op_args = (table, schema, pages)
                if not self._stage_write(cname, conn, "create_table",
                                         *op_args, properties=props):
                    conn.create_table(table, schema, pages, properties=props)
            elif not self._stage_write(cname, conn, "create_table", table, schema, pages):
                conn.create_table(table, schema, pages)
        else:
            want = [c.type for c in handle.columns]
            got = plan.output_types
            # name+scale equality: decimal scale decides the scaled-int
            # representation (a name-only check would let decimal(x,3)
            # data land in a decimal(x,2) column 10x off), but precision
            # is metadata — expressions widen to precision 18 and their
            # values are still valid for any column of the same scale.
            if [(t.name, t.scale) for t in want] != [(t.name, t.scale) for t in got]:
                raise ValueError(f"INSERT schema mismatch: {want} vs {got}")
            pages = [self._recode_strings(p, handle) for p in pages]
            if not self._stage_write(cname, conn, "append_pages", table, pages):
                conn.append_pages(table, pages)
        self._invalidate_plans()
        return MaterializedResult(["rows"], [BIGINT], [(rows,)])

    def _delete(self, stmt, query_id=None) -> MaterializedResult:
        """DELETE FROM t [WHERE pred] (DeleteOperator /
        MetadataDeleteOperator analog): the surviving rows re-select
        through the engine (NOT pred) and overwrite the table pages
        atomically — connector-side delete-by-rewrite, the model the
        memory connector supports."""
        import numpy as np

        handle = self.catalog.resolve(stmt.table)
        self.access_control.check_can_delete(self.session.user, handle.table)
        conn = self.catalog.connector(handle.connector_name)
        if not hasattr(conn, "create_table"):
            raise ValueError(f"connector {handle.connector_name} is read-only")
        self._check_tx_writable(handle.connector_name, conn)
        before = conn.row_count(handle.table)
        if stmt.where is None:
            keep_sql_pred = None
            survivors = []
        else:
            # survivors: NOT pred OR pred IS NULL (NULL predicates keep
            # the row, matching DELETE's true-only semantics)
            keep = ast.Query(
                select=(ast.SelectItem(ast.Star()),),
                from_=(ast.TableRef(handle.table),),
                where=ast.Binary("or", ast.Unary("not", stmt.where),
                                 ast.IsNull(stmt.where, False)),
            )
            plan = self.binder.plan_ast(keep)
            page = self.executor.run_to_page(plan, query_id=query_id).compact_host()
            survivors = [page]
        schema = conn.schema(handle.table)
        op_args = (handle.table, schema, survivors,
                   {c.name: c.domain for c in handle.columns})
        if self._stage_write(handle.connector_name, conn, "create_table", *op_args):
            return MaterializedResult(["rows"], [BIGINT], [(-1,)])
        conn.create_table(*op_args)
        self._invalidate_plans()
        after = conn.row_count(handle.table)
        return MaterializedResult(["rows"], [BIGINT], [(before - after,)])

    def _write_target(self, name: str):
        """(connector, physical table) for a CTAS target: a
        'catalog.table' prefix routes to that connector, else the USE
        defaults apply (non-default schema prefixes the physical name),
        else the default writable one."""
        if "." in name:
            cname, bare = name.split(".", 1)
            if cname in self.catalog._connectors:
                return cname, bare
        s_cat, s_sch = self.session.catalog, self.session.schema
        if ("." not in name and s_cat in self.catalog._connectors
                and hasattr(self.catalog.connector(s_cat), "create_table")):
            return s_cat, (name if s_sch in (None, "default")
                           else f"{s_sch}.{name}")
        if self.catalog.write_connector is None:
            raise ValueError("no writable connector registered")
        return self.catalog.write_connector, name

    def _check_tx_writable(self, connector_name: str, conn) -> None:
        """Early rejection for writes that cannot proceed in the open
        transaction (read-only / connector without tx hooks)."""
        if self._open_tx is None:
            return
        from presto_tpu.transaction import TransactionError

        if self._open_tx.read_only:
            raise TransactionError("transaction is READ ONLY")
        if not hasattr(conn, "begin_transaction") or not hasattr(conn, "stage"):
            raise TransactionError(
                f"connector {connector_name} does not support transactions")

    def _invalidate_plans(self) -> None:
        """Writes change split counts / stats snapshotted into cached
        plans (TableHandle.num_splits, row_count); drop them so the next
        query re-resolves metadata (the reference re-resolves per query
        — its plans are never cached across queries)."""
        self._plans.clear()

    def _stage_write(self, connector_name: str, conn, op: str, *args,
                     **kwargs) -> bool:
        """Inside an open transaction, stage the write on the connector's
        tx handle instead of applying it; returns True when staged."""
        if self._open_tx is None:
            return False
        self._check_tx_writable(connector_name, conn)
        handle = self._open_tx.handle_for(connector_name, conn)
        conn.stage(handle, op, *args, **kwargs)
        return True

    def _recode_strings(self, page, handle):
        """Recode inserted VARCHAR blocks onto the table's dictionary so
        appended pages and existing pages agree on code meaning; values
        absent from the table dictionary are rejected."""
        import numpy as np

        from presto_tpu.page import Block, Page

        blocks = list(page.blocks)
        changed = False
        conn = self.catalog.connector(handle.connector_name)
        open_cols = (conn.open_dictionary_columns(handle.table)
                     if hasattr(conn, "open_dictionary_columns") else set())
        for i, col in enumerate(handle.columns):
            if not col.type.is_string:
                continue
            if col.name in open_cols:
                # dynamic partitioning: new values extend the
                # metastore's value list instead of being rejected
                continue
            b = blocks[i]
            dst = getattr(col, "dictionary", None)
            if dst is None or b.dictionary is dst:
                continue
            src = b.dictionary
            codes = np.asarray(b.data)
            valid = np.asarray(b.valid) & np.asarray(page.row_mask)
            # O(|dictionary|) remap table + vectorized gather
            remap = np.asarray([dst.code_of(v) for v in src.values], np.int64)
            in_range = (codes >= 0) & (codes < len(remap))
            new_codes = np.where(in_range, remap[np.clip(codes, 0, len(remap) - 1)], -1)
            bad = valid & (new_codes < 0)
            if bad.any():
                j = int(np.nonzero(bad)[0][0])
                val = src.values[codes[j]] if in_range[j] else codes[j]
                raise ValueError(
                    f"INSERT value {val!r} not in dictionary of column {col.name}"
                )
            blocks[i] = Block(new_codes.astype(codes.dtype), b.valid, b.type, dst)
            changed = True
        return Page(tuple(blocks), page.row_mask) if changed else page

    def _result_cache_prepared(self, plan):
        """(key, versions) when the result cache applies to this query
        (``result_cache_enabled`` session property, deterministic plan,
        every scanned table versioned) — None otherwise."""
        try:
            if not self.session.get("result_cache_enabled"):
                return None
        except KeyError:
            return None
        from presto_tpu.serving.cache import default_result_cache

        return default_result_cache().prepare(plan, self.catalog)

    def _result_cache_hit(self, plan, prepared):
        """A MaterializedResult served from the structural result cache,
        or None on miss.  The cached row list is copied — callers (the
        coordinator's pager, verifiers) may hold results across later
        invalidations."""
        from presto_tpu.serving.cache import default_result_cache

        got = default_result_cache().lookup(prepared)
        if got is None:
            return None
        names, types, rows = got
        return MaterializedResult(list(names), list(types), list(rows))

    def _run_plan(self, plan, query_id=None, stats=None):
        """Route through the device-mesh tier when ``SET SESSION
        distributed = true`` and the plan shape distributes; otherwise
        (or on DistributedUnsupported) the local executor.  The query
        scope tags streaming-exchange buffers with the query id so a
        deadline/memory kill (pool.kill_query) aborts them and unblocks
        backpressured producer threads.

        ``stats``: per-operator actuals sink (``collect_stats`` /
        EXPLAIN ANALYZE) — threaded into whichever tier executes so
        estimate-vs-actual attribution works on every path."""
        from presto_tpu.parallel.streams import query_scope

        with query_scope(query_id):
            if self.session.get("distributed"):
                return self._distributed().run(plan, stats=stats)
            if stats is not None:
                stats.register_plan(plan)
                self.executor.stats = stats
                try:
                    return self.executor.run(plan, query_id=query_id)
                finally:
                    self.executor.stats = None
            return self.executor.run(plan, query_id=query_id)

    def _distributed(self):
        if getattr(self, "_dist", None) is None:
            from presto_tpu.parallel.dist import DistributedRunner, make_mesh

            n = self.session.get("hash_partition_count") or None
            self._dist = DistributedRunner(
                self.catalog, mesh=make_mesh(n), session=self.session)
        return self._dist

    def _plan_cached(self, sql: str, q: ast.Query):
        plan = self._plans.get(sql)
        if plan is None:
            from presto_tpu.sql.binder import BindError, annotate_position

            try:
                plan = self._validated(self.binder.plan_ast(q))
            except BindError as e:
                # statement text is known here: render the failing AST
                # node's offset as line:col in the user-facing error
                raise annotate_position(e, sql) from e.__cause__
            self._plans[sql] = plan
        return plan

    @staticmethod
    def _ms(seconds: Optional[float]) -> Optional[float]:
        return None if seconds is None else round(seconds * 1e3, 3)

    @staticmethod
    def _finalize_trace(tracer, t_q0: float) -> None:
        """Close the root ``query`` span (parse start -> now) and write
        the per-query Chrome-trace file when a trace dir is set."""
        if tracer is None:
            return
        import time

        from presto_tpu import obs

        tracer.add_complete("query", "lifecycle", t_q0,
                            time.perf_counter() - t_q0)
        obs.maybe_write_trace(tracer)

    def _check_access(self, plan) -> None:
        from presto_tpu.security import scan_tables

        for table in scan_tables(plan):
            self.access_control.check_can_select(self.session.user, table)

    def explain(self, sql: str) -> str:
        return self.executor.explain(self.plan(sql))

    def explain_distributed(self, sql: str) -> str:
        """Fragment-tree rendering (EXPLAIN (TYPE DISTRIBUTED) analog:
        sql/planner/PlanFragmenter SubPlans printed by PlanPrinter)."""
        from presto_tpu.parallel.fragment import explain_distributed

        return explain_distributed(
            self.plan(sql), catalog=self.catalog,
            min_stage_rows=int(self.session.get("distributed_min_stage_rows")))
