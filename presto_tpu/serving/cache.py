"""Structural result + subplan caches for the serving tier.

Reference analog: the coordinator-side caching plane of the reference's
serving deployments (materialized query results keyed by canonical plan
shape, invalidated by table data versions — the role fragment-result
caching plays in warehouse serving tiers; presto-main itself re-executes
everything, which is exactly the gap ROADMAP item 2 names for the
"millions of users" half of the north star).

Two caches share one byte-capped LRU implementation:

- :class:`ResultCache` stores the final rows of read-only queries,
  keyed by the STRUCTURAL plan signature (``exec/programs.ir_signature``
  — the same canonical-IR identity that keys the ProgramRegistry and
  QueryStats), so two dashboard clients issuing textually different but
  structurally identical queries share one entry.

- :class:`SubplanCache` applies the same scheme at exchange boundaries:
  a distributed stage (scan -> filter -> partial agg -> exchange)
  shared as a prefix across dashboard variants hits warm intermediate
  pages instead of re-executing the stage (``parallel/dist.py`` wires
  it around its stage callbacks).

Correctness model — entries are invalidated by WAREHOUSE TABLE
VERSIONS: every versioned connector exposes ``table_version(table)``, a
monotonically increasing integer bumped on INSERT/CTAS/DELETE/DDL.  The
versions of every scanned table are captured into the key at plan time
(before execution starts); a lookup whose captured versions disagree
with the live ones drops the entry and misses.  A plan that scans ANY
table whose connector does not expose versions (system tables, streams,
remote) is uncacheable, as is a plan containing a nondeterministic
function call — stale results are never served (docs/serving.md states
the full consistency contract).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from presto_tpu.envflag import EnvInt
from presto_tpu.sync import named_lock

#: process default for the result-cache byte budget
#: (``query.result-cache-bytes`` config / PRESTO_TPU_RESULT_CACHE_BYTES)
_RESULT_CACHE_BYTES = EnvInt("PRESTO_TPU_RESULT_CACHE_BYTES", 64 << 20)
#: and for the subplan (stage intermediate) cache
_SUBPLAN_CACHE_BYTES = EnvInt("PRESTO_TPU_SUBPLAN_CACHE_BYTES", 128 << 20)

#: no single entry may take more than this fraction of the cache — one
#: giant result must not evict the whole working set to store itself
_MAX_ENTRY_FRACTION = 0.5

# function calls whose value is not a pure function of the inputs; a
# plan containing one must never serve from (or populate) a cache.
# now()/current_timestamp bind to a per-plan literal (binder._query_now)
# but are listed anyway: a cached LITERAL timestamp served forever is
# exactly the staleness the cache must not introduce.
NONDETERMINISTIC_FNS = frozenset(
    {"random", "rand", "uuid", "now", "current_timestamp", "current_time",
     "current_date", "localtimestamp", "shuffle"})


# ---------------------------------------------------------------------------
# cacheability + keys
# ---------------------------------------------------------------------------


def _walk_exprs(obj, seen: set):
    """Yield every expr Call in a plan/IR tree (generic dataclass walk;
    descent stops at leaf value objects — Types, Dictionaries, Pages)."""
    from presto_tpu.expr.ir import Call
    from presto_tpu.page import Dictionary, Page
    from presto_tpu.types import Type

    if obj is None or isinstance(obj, (bool, int, float, str, bytes,
                                       Type, Dictionary, Page)):
        return
    oid = id(obj)
    if oid in seen:
        return
    seen.add(oid)
    if isinstance(obj, Call):
        yield obj
        for a in obj.args:
            yield from _walk_exprs(a, seen)
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for x in obj:
            yield from _walk_exprs(x, seen)
        return
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            yield from _walk_exprs(getattr(obj, f.name), seen)


def plan_deterministic(plan) -> bool:
    """False when any expression in the plan calls a nondeterministic
    function — such a plan must never populate or serve from a cache."""
    return all(c.fn not in NONDETERMINISTIC_FNS
               for c in _walk_exprs(plan, set()))


def _scan_nodes(plan) -> List:
    from presto_tpu.planner.plan import TableScanNode

    out: List = []

    def walk(node):
        if isinstance(node, TableScanNode):
            out.append(node)
        for s in node.sources:
            walk(s)

    walk(plan)
    return out


def plan_table_versions(plan, catalog) -> Optional[Tuple]:
    """Sorted ``(connector, table, version)`` triples for every table
    the plan scans, or None when any scanned table's connector does not
    expose ``table_version`` (-> the plan is uncacheable).  A plan with
    no scans at all (pure VALUES) versions to the empty tuple."""
    versions = set()
    for scan in _scan_nodes(plan):
        handle = scan.handle
        try:
            conn = catalog.connector(handle.connector_name)
        except KeyError:
            return None
        fn = getattr(conn, "table_version", None)
        if fn is None:
            return None
        try:
            # versions are opaque hashable tokens: ints for the memory
            # connector, (incarnation, counter) pairs for the warehouse
            versions.add((handle.connector_name, handle.table,
                          fn(handle.table)))
        except Exception:
            return None  # a connector that errors on versioning opts out
    return tuple(sorted(versions, key=repr))


def plan_cache_key(plan) -> Optional[Tuple]:
    """Hashable structural signature of a bound plan (the
    ProgramRegistry's ``ir_signature`` applied to the whole tree), or
    None when the plan is not cacheable (nondeterministic functions).
    Textually different queries with identical structure — the repeated
    dashboard case — produce the SAME key; anything ``ir_signature``
    keys by object identity (unknown leaf objects) merely forgoes
    sharing, never produces a wrong hit."""
    if not plan_deterministic(plan):
        return None
    from presto_tpu.exec.programs import ir_signature

    try:
        return ("plan", ir_signature(plan))
    except Exception:
        return None  # unsignable plans are simply uncacheable


def signature_has_identity_keys(sig) -> bool:
    """True when an ``ir_signature`` tree contains an identity-keyed
    leaf (the ``("I", type, token)`` form): such a key is stable only
    for the lifetime of one specific object and can never match across
    queries — a cache entry stored under it is pure budget pollution.
    (Dictionary tokens ``("D", n)`` are fine: table dictionaries are
    long-lived connector state.)"""
    if isinstance(sig, tuple):
        if len(sig) == 3 and sig[0] == "I" and isinstance(sig[2], int):
            return True
        return any(signature_has_identity_keys(x) for x in sig)
    return False


def result_nbytes(rows: List[tuple]) -> int:
    """Approximate host footprint of a materialized row set (byte-cap
    accounting; exactness is not required, monotonicity in data size
    is)."""
    import sys

    total = 0
    for r in rows:
        total += 64  # tuple + list-slot overhead
        for v in r:
            if isinstance(v, (str, bytes)):
                total += 48 + len(v)
            else:
                total += sys.getsizeof(v) if v is not None else 16
    return total


# ---------------------------------------------------------------------------
# the byte-capped LRU both caches share
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("value", "versions", "nbytes")

    def __init__(self, value, versions, nbytes: int):
        self.value = value
        self.versions = versions
        self.nbytes = int(nbytes)


class StructuralCache:
    """Byte-capped LRU keyed by structural signatures, validated by
    table versions on every read.  ``metric_prefix`` selects the
    pre-registered ``cache.<prefix>_*`` instrument family
    (obs/metrics.py catalog)."""

    def __init__(self, max_bytes: int, metric_prefix: str):
        self.max_bytes = int(max_bytes)
        self.metric_prefix = metric_prefix
        self._lock = named_lock("cache.StructuralCache._lock")
        self._entries: "collections.OrderedDict[Any, _Entry]" = \
            collections.OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def _counter(self, what: str):
        from presto_tpu.obs import METRICS

        return METRICS.counter(f"cache.{self.metric_prefix}_{what}")

    def _publish_gauges(self) -> None:
        from presto_tpu.obs import METRICS

        METRICS.gauge(f"cache.{self.metric_prefix}_bytes").set(self.bytes)
        METRICS.gauge(f"cache.{self.metric_prefix}_entries").set(
            len(self._entries))

    def get(self, key, versions) -> Optional[Any]:
        """The cached value when present AND its captured table versions
        equal ``versions`` — a version mismatch drops the entry (write
        invalidation is lazy: the bump happens in the connector, the
        entry dies at its next lookup)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                self._counter("misses").inc()
                return None
            if e.versions != versions:
                self._entries.pop(key)
                self.bytes -= e.nbytes
                self.invalidations += 1
                self.misses += 1
                self._counter("invalidations").inc()
                self._counter("misses").inc()
                self._publish_gauges()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._counter("hits").inc()
            return e.value

    def put(self, key, versions, value, nbytes: int) -> bool:
        """Insert (replacing any same-key entry); False when the value
        is too large to cache (> half the budget)."""
        nbytes = int(nbytes)
        if nbytes > self.max_bytes * _MAX_ENTRY_FRACTION:
            self._counter("oversize").inc()
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old.nbytes
            self._entries[key] = _Entry(value, versions, nbytes)
            self.bytes += nbytes
            self._counter("stores").inc()
            while self.bytes > self.max_bytes and self._entries:
                _, ev = self._entries.popitem(last=False)
                self.bytes -= ev.nbytes
                self.evictions += 1
                self._counter("evictions").inc()
            self._publish_gauges()
        return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0
            self._publish_gauges()

    def resize(self, max_bytes: int) -> None:
        """Change the byte budget (config wiring), evicting LRU-first
        down to the new cap."""
        with self._lock:
            self.max_bytes = int(max_bytes)
            while self.bytes > self.max_bytes and self._entries:
                _, ev = self._entries.popitem(last=False)
                self.bytes -= ev.nbytes
                self.evictions += 1
                self._counter("evictions").inc()
            self._publish_gauges()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }


# ---------------------------------------------------------------------------
# result cache (final rows of read-only queries)
# ---------------------------------------------------------------------------


class ResultCache:
    """Final-result cache over :class:`StructuralCache`: entry = the
    (names, types, rows) triple of a finished read-only query."""

    def __init__(self, max_bytes: Optional[int] = None):
        self.cache = StructuralCache(
            max_bytes if max_bytes is not None else _RESULT_CACHE_BYTES(),
            "result")

    def prepare(self, plan, catalog) -> Optional[Tuple]:
        """(key, versions) when the plan is cacheable — computed ONCE
        at plan time so the versions a stored entry carries are the
        pre-execution ones (a write racing the execution makes the
        entry stale-by-version, never silently current)."""
        key = plan_cache_key(plan)
        if key is None:
            return None
        versions = plan_table_versions(plan, catalog)
        if versions is None:
            return None
        return (key, versions)

    def lookup(self, prepared):
        """Cached (names, types, rows) or None."""
        if prepared is None:
            return None
        return self.cache.get(prepared[0], prepared[1])

    def store(self, prepared, names, types, rows) -> bool:
        if prepared is None:
            return False
        return self.cache.put(prepared[0], prepared[1],
                              (list(names), list(types), list(rows)),
                              result_nbytes(rows))

    def stats(self) -> Dict[str, Any]:
        return self.cache.stats()

    def clear(self) -> None:
        self.cache.clear()


# ---------------------------------------------------------------------------
# subplan (stage-intermediate) cache
# ---------------------------------------------------------------------------


class SubplanCache:
    """Stage-output cache at exchange boundaries: the distributed
    runner consults it before executing a stage whose subtree reads
    only versioned base tables, and stores the stage's materialized
    page after.  Pages are immutable device arrays, so sharing one
    across queries is safe; the byte cap bounds the HBM the cache may
    pin (``memory.page_bytes`` accounting)."""

    def __init__(self, max_bytes: Optional[int] = None):
        self.cache = StructuralCache(
            max_bytes if max_bytes is not None else _SUBPLAN_CACHE_BYTES(),
            "subplan")

    def prepare(self, stage_root, catalog, extra=()) -> Optional[Tuple]:
        """(key, versions) when the stage is cacheable: deterministic,
        every leaf a versioned base-table scan (a stage over another
        stage's intermediate keys that intermediate by object identity,
        which never repeats across queries — prepare still succeeds but
        such keys simply never hit).  ``extra`` folds stage-level
        execution parameters (shard bounds, mesh width) into the key."""
        key = plan_cache_key(stage_root)
        if key is None:
            return None
        # a stage over another stage's intermediate (PrecomputedNode
        # page) keys by object identity — that entry can never be
        # looked up by a later query, so storing it would only evict
        # the genuinely shareable base-table-prefix entries
        if signature_has_identity_keys(key):
            return None
        versions = plan_table_versions(stage_root, catalog)
        if versions is None:
            return None
        return (("stage",) + tuple(extra) + (key,), versions)

    def lookup(self, prepared):
        if prepared is None:
            return None
        return self.cache.get(prepared[0], prepared[1])

    def store(self, prepared, page) -> bool:
        if prepared is None or page is None:
            return False
        from presto_tpu.memory import page_bytes

        try:
            nbytes = page_bytes(page)
        except Exception:
            return False
        return self.cache.put(prepared[0], prepared[1], page, nbytes)

    def stats(self) -> Dict[str, Any]:
        return self.cache.stats()

    def clear(self) -> None:
        self.cache.clear()


# ---------------------------------------------------------------------------
# process-wide defaults (the sharing model of programs.default_registry:
# coordinator + every runner in the process serve from one budget)
# ---------------------------------------------------------------------------

_DEFAULTS: Dict[str, Any] = {"result": None, "subplan": None}
_DEFAULTS_LOCK = named_lock("cache._DEFAULTS_LOCK")


def default_result_cache() -> ResultCache:
    with _DEFAULTS_LOCK:
        if _DEFAULTS["result"] is None:
            _DEFAULTS["result"] = ResultCache()
        return _DEFAULTS["result"]


def default_subplan_cache() -> SubplanCache:
    with _DEFAULTS_LOCK:
        if _DEFAULTS["subplan"] is None:
            _DEFAULTS["subplan"] = SubplanCache()
        return _DEFAULTS["subplan"]


def set_result_cache_bytes(max_bytes: int) -> None:
    """Wire the ``query.result-cache-bytes`` config key into the
    process default (launcher): overrides the env/default budget and
    resizes an already-built cache in place (<= 0 is ignored — the
    env/default stands)."""
    if max_bytes <= 0:
        return
    with _DEFAULTS_LOCK:
        _RESULT_CACHE_BYTES.set(max_bytes)
        if _DEFAULTS["result"] is not None:
            _DEFAULTS["result"].cache.resize(max_bytes)


def reset_default_caches() -> None:
    """Tests: drop the process-wide caches (and re-resolve byte caps)."""
    with _DEFAULTS_LOCK:
        for k in ("result", "subplan"):
            if _DEFAULTS[k] is not None:
                _DEFAULTS[k].clear()
            _DEFAULTS[k] = None
