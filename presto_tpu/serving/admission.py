"""Memory-aware admission control with live queue positions.

Reference analog: the resource-group admission plane of
``execution/resourceGroups/InternalResourceGroupManager.java`` plus the
coordinator's memory-aware dispatch (``ClusterMemoryManager`` feeding
``QueryQueuer`` decisions) — the serving-tier half of ROADMAP item 2.

The controller fronts the existing :mod:`presto_tpu.resource_groups`
tree with two additions the bare ``group.acquire()`` call lacked:

- **memory-aware dispatch**: after winning a concurrency slot, a query
  is dispatched only when projected headroom exists on the memory pool
  — ``reserved + projected <= memory_fraction * limit`` — where the
  projection is the query's remembered peak from previous runs of the
  same statement (falling back to a configured reserve).  The gauges
  consulted are the same ``memory.pool_reserved/limit_bytes`` surfaces
  ``memory.wire_pool_gauges`` exports, so operators can reproduce every
  admission decision from scraped data.

- **queue positions**: every waiting query holds a ticket in one
  FIFO-ordered book; ``queue_position`` is served live through the
  async statement protocol (``stats.queuePosition``), the CLI progress
  line, and the web UI.  Positions are informational — dispatch order
  follows the group policy for the slot and first-fit for memory
  headroom (a light query may pass a memory-blocked heavy one; see
  docs/serving.md for the tradeoff and its mitigations).

Rejections keep their identities: a full queue raises
``QueryQueueFullError`` and an expired wait raises ``TimeoutError`` —
the coordinator maps them to the ``QUERY_QUEUE_FULL`` /
``EXCEEDED_QUEUE_TIME`` statement error codes.

Lifecycle telemetry: ``admission.*`` counters/gauges/histogram
(obs catalog) and ``QueryQueuedEvent`` / ``QueryAdmittedEvent`` query-log
lines, so queue depth, wait-time distribution, and memory stalls are
first-class observables.
"""

from __future__ import annotations

import itertools
import time
import weakref
from typing import Dict, Optional

from presto_tpu.analysis.protocols import RECORDER
from presto_tpu.resource_groups import (  # re-exported for callers
    QueryQueueFullError, ResourceGroupManager,
)
from presto_tpu.sync import named_condition

__all__ = ["AdmissionCancelledError", "AdmissionController",
           "AdmissionTicket", "QueryQueueFullError"]


class AdmissionCancelledError(Exception):
    """The query was canceled while waiting for admission — the wait
    ends without a slot, and nothing counts as admitted."""

_seq = itertools.count(1)

#: bounded per-signature peak-memory history (projection source)
_HISTORY_MAX = 1024

#: memory-gate poll interval: pool frees do not signal this condition,
#: so blocked admissions also re-check on a short timer
_MEM_POLL_S = 0.05

#: every live controller, for the process-wide admission gauges — a
#: second controller (bench harness, tests) must AGGREGATE with the
#: coordinator's, not silently hijack the gauge callbacks
_CONTROLLERS: "weakref.WeakSet" = weakref.WeakSet()
_GAUGES_WIRED = [False]


class AdmissionTicket:
    """One query's admission state: QUEUED -> ADMITTED -> RELEASED
    (or CANCELED while queued)."""

    __slots__ = ("query_id", "user", "group", "priority", "seq", "state",
                 "projected_bytes", "queued_at", "admitted_at", "released",
                 "canceled", "memory_blocked_s")

    def __init__(self, query_id: str, user: str, priority: int = 0):
        self.query_id = query_id
        self.user = user
        self.group = None
        self.priority = priority
        self.seq = next(_seq)
        self.state = "QUEUED"
        self.projected_bytes = 0
        self.queued_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.released = False
        self.canceled = False
        # seconds this ticket spent blocked on memory headroom AFTER
        # winning its concurrency slot (0.0 when the gate never blocked)
        self.memory_blocked_s = 0.0

    def queued_ms(self) -> float:
        end = self.admitted_at if self.admitted_at is not None \
            else time.monotonic()
        return round((end - self.queued_at) * 1e3, 3)


def _wire_gauges() -> None:
    """Attach the admission gauges ONCE per process; callbacks sum over
    every live controller, so a bench/test controller aggregates with
    the coordinator's instead of hijacking the series (and a collected
    controller simply drops out of the sum)."""
    if _GAUGES_WIRED[0]:
        return
    _GAUGES_WIRED[0] = True
    from presto_tpu.obs import METRICS

    METRICS.gauge("admission.queue_depth").set_fn(
        lambda: float(sum(c.queue_depth() for c in list(_CONTROLLERS))))
    METRICS.gauge("admission.running").set_fn(
        lambda: float(sum(c._running_count() for c in list(_CONTROLLERS))))


class AdmissionController:
    """Group concurrency + memory headroom gate in front of dispatch."""

    def __init__(self, groups: Optional[ResourceGroupManager] = None,
                 pool=None, memory_fraction: float = 0.9,
                 reserve_bytes: int = 0, events=None):
        self.groups = groups or ResourceGroupManager()
        # the MemoryPool whose reserved/limit gauges gate dispatch
        # (None = no memory awareness, pure concurrency admission)
        self.pool = pool
        self.memory_fraction = float(memory_fraction)
        self.reserve_bytes = int(reserve_bytes)
        # EventListenerManager (or None) for queued/admitted log lines
        self.events = events
        # one monitor serves the ticket book AND the memory gate; the
        # group tree has its own condition and is NEVER entered while
        # this one is held (acquire happens outside the lock, so the
        # only cross-lock order is admission -> resource_groups)
        import collections

        self._cond = named_condition("admission.AdmissionController._cond")
        self._tickets: Dict[str, AdmissionTicket] = {}
        # statement-signature -> observed peak bytes (projection for
        # repeat queries; bounded LRU — a hot statement re-recorded
        # every run must outlive 1024 one-off statements, or its
        # projection silently falls back to the default and a burst of
        # it overcommits exactly as if the gate were off)
        self._peak_history: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        _CONTROLLERS.add(self)
        # conformance identity: one admission spec-automaton run per
        # controller (every event carries its qid)
        self._pkey = f"adm:{id(self):x}"
        _wire_gauges()

    def _record_reject(self, ticket: AdmissionTicket, reason: str) -> None:
        if RECORDER.enabled:
            RECORDER.record("admission", self._pkey, "rejected",
                            qid=ticket.query_id, reason=reason)

    def _running_count(self) -> int:
        with self._cond:
            return sum(1 for t in self._tickets.values()
                       if t.state == "ADMITTED")

    # -- projection history -------------------------------------------------
    def record_peak(self, statement_key: Optional[str],
                    peak_bytes: int) -> None:
        """Remember a completed statement's observed peak reservation —
        the projection its next admission uses."""
        if not statement_key or peak_bytes <= 0:
            return
        with self._cond:
            prev = self._peak_history.get(statement_key, 0)
            self._peak_history[statement_key] = max(prev, int(peak_bytes))
            self._peak_history.move_to_end(statement_key)
            while len(self._peak_history) > _HISTORY_MAX:
                self._peak_history.popitem(last=False)

    def projected_bytes(self, statement_key: Optional[str]) -> int:
        with self._cond:
            seen = self._peak_history.get(statement_key or "", 0)
        return max(seen, self.reserve_bytes)

    # -- queue surfaces -----------------------------------------------------
    def queue_depth(self) -> int:
        with self._cond:
            return sum(1 for t in self._tickets.values()
                       if t.state == "QUEUED")

    def queue_position(self, query_id: str) -> Optional[int]:
        """1-based FIFO position among queued tickets; None once the
        query is admitted (or unknown)."""
        with self._cond:
            t = self._tickets.get(query_id)
            if t is None or t.state != "QUEUED":
                return None
            return 1 + sum(1 for o in self._tickets.values()
                           if o.state == "QUEUED" and o.seq < t.seq)

    # -- admission ----------------------------------------------------------
    def admit(self, query_id: str, user: str, priority: int = 0,
              timeout: Optional[float] = None,
              statement_key: Optional[str] = None) -> AdmissionTicket:
        """Block until the query may run: resource-group concurrency
        (+ queue quota) first, then memory headroom.  Raises
        ``QueryQueueFullError`` when the group queue is at quota and
        ``TimeoutError`` when ``timeout`` expires in either phase (the
        deadline is ABSOLUTE across both)."""
        from presto_tpu.obs import METRICS

        ticket = AdmissionTicket(query_id, user, priority)
        ticket.projected_bytes = self.projected_bytes(statement_key)
        with self._cond:
            self._tickets[query_id] = ticket
            if RECORDER.enabled:
                RECORDER.record("admission", self._pkey, "queued",
                                qid=query_id)
        METRICS.counter("admission.queued_total").inc()
        self._emit_queued(ticket)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        group = self.groups.group_for(user)
        ticket.group = group
        try:
            group.acquire(timeout=timeout, priority=priority)
        except QueryQueueFullError:
            METRICS.counter("admission.rejected_queue_full").inc()
            self._record_reject(ticket, "queue_full")
            self._drop(ticket)
            raise
        except TimeoutError:
            METRICS.counter("admission.rejected_timeout").inc()
            self._record_reject(ticket, "timeout")
            self._drop(ticket)
            raise
        except BaseException as e:
            self._record_reject(ticket, type(e).__name__)
            self._drop(ticket)
            raise
        try:
            # the gate flips the ticket to ADMITTED inside its own
            # critical section: the headroom decision and the moment
            # the ticket starts counting as inflight are atomic, so
            # two concurrent heavy admits can never both pass against
            # the same headroom
            self._wait_for_memory(ticket, deadline)
        except TimeoutError:
            METRICS.counter("admission.rejected_timeout").inc()
            self._record_reject(ticket, "timeout")
            group.release()
            self._drop(ticket)
            raise
        except BaseException as e:
            self._record_reject(ticket, type(e).__name__)
            group.release()
            self._drop(ticket)
            raise
        METRICS.counter("admission.admitted_total").inc()
        METRICS.histogram("admission.queue_wait_ms").observe(
            ticket.queued_ms())
        self._annotate_timeline(ticket)
        self._emit_admitted(ticket)
        return ticket

    def _inflight_projected(self) -> int:
        """Projected-but-not-yet-reserved bytes of admitted, unreleased
        tickets (caller holds ``_cond``).  Without this a burst of
        heavy statements would ALL pass the headroom check before any
        of them reserves — the exact OOM storm the gate exists to
        prevent.  Each ticket's projection is discounted by what its
        query has actually reserved so far (the pool's tagged
        reservations), so a running query is never double-counted."""
        admitted = [t for t in self._tickets.values()
                    if t.state == "ADMITTED"]
        if not admitted:
            return 0
        actual: Dict[str, int] = {}
        pool = self.pool
        if pool is not None and hasattr(pool, "tags"):
            for tag, nbytes in pool.tags().items():
                qid = tag.split("/", 1)[0]
                actual[qid] = actual.get(qid, 0) + nbytes
        return sum(max(0, t.projected_bytes - actual.get(t.query_id, 0))
                   for t in admitted)

    def _headroom_ok(self, need: int, inflight: int) -> bool:
        pool = self.pool
        if pool is None or self.memory_fraction <= 0:
            return True
        limit = getattr(pool, "limit", 0)
        if limit <= 0:
            return True
        return (pool.reserved + inflight + need
                <= self.memory_fraction * limit)

    def _wait_for_memory(self, ticket: AdmissionTicket,
                         deadline: Optional[float]) -> None:
        """Memory gate: wait (on this controller's own condition; frees
        are also caught by a short re-check timer) until projected
        headroom exists — against the pool's LIVE reservations plus the
        still-unreserved projections of already-admitted queries.  One
        query always proceeds when the pool is idle and nothing else is
        admitted, so a projection larger than the whole pool degrades
        to run-alone instead of wedging forever."""
        from presto_tpu.obs import METRICS

        need = ticket.projected_bytes
        t0 = time.monotonic()
        blocked = False
        with self._cond:
            while True:
                if ticket.canceled:
                    raise AdmissionCancelledError(
                        f"query {ticket.query_id} canceled while queued")
                inflight = self._inflight_projected()
                pool = self.pool
                idle = (pool is not None
                        and getattr(pool, "reserved", 0) <= 0
                        and inflight == 0)
                if self._headroom_ok(need, inflight) or idle:
                    # decision and ADMITTED transition are ONE critical
                    # section: the ticket counts as inflight before any
                    # concurrent admit can evaluate its own headroom
                    ticket.admitted_at = time.monotonic()
                    ticket.state = "ADMITTED"
                    if RECORDER.enabled:
                        limit = getattr(pool, "limit", 0) \
                            if pool is not None else 0
                        fields = dict(qid=ticket.query_id,
                                      reserved=int(getattr(
                                          pool, "reserved", 0) or 0),
                                      inflight=int(inflight),
                                      need=int(need), idle=bool(idle))
                        if limit > 0 and self.memory_fraction > 0:
                            fields["cap"] = int(
                                self.memory_fraction * limit)
                        RECORDER.record("admission", self._pkey,
                                        "admitted", **fields)
                    break
                if not blocked:
                    blocked = True
                    METRICS.counter("admission.memory_blocked_total").inc()
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"query {ticket.query_id}: queue wait timed out "
                        f"waiting for memory headroom "
                        f"({need} projected bytes)")
                wait = _MEM_POLL_S if remaining is None \
                    else min(_MEM_POLL_S, remaining)
                self._cond.wait(timeout=wait)
        if blocked:
            stalled = time.monotonic() - t0
            ticket.memory_blocked_s = stalled
            METRICS.counter("admission.memory_stall_seconds_total").inc(
                stalled)

    # -- release ------------------------------------------------------------
    def release(self, ticket: Optional[AdmissionTicket]) -> None:
        """Free the ticket's slot EXACTLY once (callable from the
        completion path and any killer) and wake memory-gate waiters —
        a finished query is precisely when headroom reappears."""
        if ticket is None:
            return
        with self._cond:
            if ticket.released:
                return
            ticket.released = True
            ticket.state = "RELEASED"
            self._tickets.pop(ticket.query_id, None)
            if RECORDER.enabled and ticket.admitted_at is not None:
                RECORDER.record("admission", self._pkey, "released",
                                qid=ticket.query_id)
            self._cond.notify_all()
        if ticket.group is not None and ticket.admitted_at is not None:
            ticket.group.release()

    def cancel(self, query_id: str) -> None:
        """Mark a queued query canceled so its memory-gate wait exits at
        the next wakeup (a wait inside ``group.acquire`` still runs to
        its own bound — the same cooperative window the kill protocol
        accepts)."""
        with self._cond:
            t = self._tickets.get(query_id)
            if t is not None:
                t.canceled = True
                if RECORDER.enabled:
                    RECORDER.record("admission", self._pkey, "cancel",
                                    qid=query_id)
            self._cond.notify_all()

    def _drop(self, ticket: AdmissionTicket) -> None:
        with self._cond:
            self._tickets.pop(ticket.query_id, None)
            self._cond.notify_all()

    def _annotate_timeline(self, ticket: AdmissionTicket) -> None:
        """Stamp the admission-plane waits on the query's resource
        timeline (obs/timeseries.py) so the doctor's queue-bound and
        memory-blocked rules have per-query evidence rather than only
        the process-wide counters.  Creating the timeline here — the
        runner's later ensure_timeline is get-or-create — makes the
        admission wait part of the query's recorded life."""
        try:
            from presto_tpu import obs

            tl = obs.ensure_timeline(ticket.query_id)
            if tl is None:
                return
            tl.annotate("queued_ms", ticket.queued_ms())
            if ticket.memory_blocked_s > 0:
                tl.annotate("memory_blocked_ms",
                            round(ticket.memory_blocked_s * 1e3, 3))
            tl.record("admission.queue_depth", float(self.queue_depth()))
        except Exception:
            pass  # telemetry must never block admission

    # -- events -------------------------------------------------------------
    def _emit_queued(self, ticket: AdmissionTicket) -> None:
        if self.events is None:
            return
        try:
            from presto_tpu.events import QueryQueuedEvent

            self.events.query_queued(QueryQueuedEvent(
                query_id=ticket.query_id, user=ticket.user,
                group=getattr(ticket.group, "name", None),
                position=self.queue_position(ticket.query_id),
                queue_time=time.time()))
        except Exception:
            pass  # telemetry must never block admission

    def _emit_admitted(self, ticket: AdmissionTicket) -> None:
        if self.events is None:
            return
        try:
            from presto_tpu.events import QueryAdmittedEvent

            self.events.query_admitted(QueryAdmittedEvent(
                query_id=ticket.query_id,
                group=getattr(ticket.group, "name", None),
                queued_ms=ticket.queued_ms(),
                projected_bytes=ticket.projected_bytes,
                admit_time=time.time()))
        except Exception:
            pass
