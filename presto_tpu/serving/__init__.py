"""Coordinator serving tier: admission control + structural caches.

The multi-tenant plane ROADMAP item 2 names (docs/serving.md): the
coordinator admits queries through a memory-aware
:class:`AdmissionController` (resource-group concurrency + pool
headroom, live queue positions through the statement protocol), and
repeated read-only work serves from a byte-capped
:class:`ResultCache` / :class:`SubplanCache` keyed by structural plan
signatures and invalidated by warehouse table versions.
"""

from presto_tpu.serving.admission import (
    AdmissionController,
    AdmissionTicket,
    QueryQueueFullError,
)
from presto_tpu.serving.cache import (
    ResultCache,
    StructuralCache,
    SubplanCache,
    default_result_cache,
    default_subplan_cache,
    plan_cache_key,
    plan_deterministic,
    plan_table_versions,
    reset_default_caches,
    result_nbytes,
    set_result_cache_bytes,
)

__all__ = [
    "AdmissionController", "AdmissionTicket", "QueryQueueFullError",
    "ResultCache", "StructuralCache", "SubplanCache",
    "default_result_cache", "default_subplan_cache",
    "plan_cache_key", "plan_deterministic", "plan_table_versions",
    "reset_default_caches", "result_nbytes", "set_result_cache_bytes",
]
